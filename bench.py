"""Benchmark: the full framework vs the reference architecture, end to end.

Implements BASELINE.md config 2 (the headline): a 10 M-row NYC-taxi-shaped
dataset in 10 ``.bcolzs`` shards, ``groupby passenger_count ->
sum(fare_amount)`` (int64 cents, bit-exact), measured through the REAL stack:
zmq RPC client -> controller -> calc worker -> mesh executor (shard_map
segment partials + psum merge) -> reply.

``vs_baseline`` is speedup over a faithful CPU re-creation of the reference's
dataflow (the reference publishes no numbers, SURVEY.md §6, so its
architecture is the baseline): per shard, decode the columns single-threaded
(the reference pins Blosc to 1 thread, reference bqueryd/worker.py:40, and
bcolz decompresses per query — no decoded-row cache), aggregate with pandas
(the reference's own ground truth, reference tests/test_simple_rpc.py:139-172;
bquery's Cython kernels are the same class of C loop), tar the per-shard
result (reference bqueryd/worker.py:335-346), tar-of-tars at the controller
(reference bqueryd/controller.py:186-211), then untar + concat + re-groupby
client-side (reference bqueryd/rpc.py:150-173).

Prints ONE JSON line: {"metric", "value" (rows/s through the framework),
"unit", "vs_baseline"}.

Env knobs: BENCH_ROWS (default 10_000_000), BENCH_SHARDS (10),
BENCH_REPEATS (3), BENCH_DATA_DIR (default /tmp/bqueryd_tpu_bench).
"""

import io
import json
import logging
import os
import pickle
import sys
import tarfile
import threading
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 10_000_000))
SHARDS = int(os.environ.get("BENCH_SHARDS", 10))
REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
DATA_DIR = os.environ.get("BENCH_DATA_DIR", "/tmp/bqueryd_tpu_bench")

GROUP_COL = "passenger_count"
MEASURE_COL = "fare_amount"


def build_dataset():
    """Write the sharded taxi-like dataset once; reuse across runs."""
    from bqueryd_tpu.storage.ctable import ctable

    stamp = os.path.join(DATA_DIR, f"ready_{ROWS}_{SHARDS}")
    names = [f"taxi_{i}.bcolzs" for i in range(SHARDS)]
    if not os.path.exists(stamp):
        import shutil

        import pandas as pd

        shutil.rmtree(DATA_DIR, ignore_errors=True)
        os.makedirs(DATA_DIR, exist_ok=True)
        rng = np.random.RandomState(42)
        per = ROWS // SHARDS
        for i, name in enumerate(names):
            rows = per + (ROWS % SHARDS if i == SHARDS - 1 else 0)
            df = pd.DataFrame(
                {
                    GROUP_COL: rng.randint(1, 10, rows).astype(np.int64),
                    # integer cents: int64 end-to-end, the north-star
                    # bit-exactness axis
                    MEASURE_COL: rng.randint(250, 20000, rows).astype(
                        np.int64
                    ),
                    "trip_distance": (rng.random(rows) * 30).astype(
                        np.float32
                    ),
                }
            )
            ctable.fromdataframe(df, os.path.join(DATA_DIR, name))
        open(stamp, "w").close()
    return names


def start_cluster():
    """Controller + one calc worker in-process (threads as nodes, the
    reference's own benchmark/test topology) over real zmq sockets.

    The worker's result cache is disabled: repeated identical queries would
    otherwise be served from memory and the benchmark would measure a dict
    lookup, not the engine (the kernel/storage caches stay on — they are the
    steady-state serving path being measured)."""
    os.environ["BQUERYD_TPU_RESULT_CACHE_BYTES"] = "0"
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    url = f"mem://bench-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=DATA_DIR,
        heartbeat_interval=0.2,
    )
    worker = WorkerNode(
        coordination_url=url,
        data_dir=DATA_DIR,
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.1,
    )
    threads = [
        threading.Thread(target=node.go, daemon=True)
        for node in (controller, worker)
    ]
    for t in threads:
        t.start()
    deadline = time.time() + 60
    while time.time() < deadline:
        if len(controller.files_map) >= SHARDS:
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("worker never registered its shards")
    rpc = RPC(coordination_url=url, timeout=600, loglevel=logging.WARNING)
    return rpc, (controller, worker), threads


def reference_shaped_baseline(names):
    """One query through the reference's dataflow shape on CPU (see module
    docstring); returns (wall_seconds, result_df)."""
    import pandas as pd

    from bqueryd_tpu.storage.ctable import ctable

    t0 = time.perf_counter()
    shard_tars = []
    for name in names:
        # per-query single-threaded decode, no decoded cache (bcolz behavior)
        t = ctable(os.path.join(DATA_DIR, name), auto_cache=False, nthreads=1)
        df = pd.DataFrame(
            {
                GROUP_COL: t.column_raw(GROUP_COL),
                MEASURE_COL: t.column_raw(MEASURE_COL),
            }
        )
        part = df.groupby(GROUP_COL, as_index=False)[MEASURE_COL].sum()
        # worker: result table -> tar bytes (reference bqueryd/worker.py:335-346)
        buf = io.BytesIO()
        with tarfile.open(mode="w", fileobj=buf) as tar:
            blob = pickle.dumps(part, protocol=4)
            info = tarfile.TarInfo(name="result")
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
        shard_tars.append(buf.getvalue())
    # controller: tar of tars (reference bqueryd/controller.py:186-211)
    outer = io.BytesIO()
    with tarfile.open(mode="w", fileobj=outer) as tar:
        for i, blob in enumerate(shard_tars):
            info = tarfile.TarInfo(name=f"shard_{i}")
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
    wire = outer.getvalue()
    # client: untar + untar + concat + re-groupby (reference bqueryd/rpc.py:150-173)
    parts = []
    with tarfile.open(mode="r", fileobj=io.BytesIO(wire)) as tar:
        for member in tar.getmembers():
            inner = tar.extractfile(member).read()
            with tarfile.open(mode="r", fileobj=io.BytesIO(inner)) as shard:
                for m2 in shard.getmembers():
                    parts.append(pickle.loads(shard.extractfile(m2).read()))
    merged = (
        pd.concat(parts, ignore_index=True)
        .groupby(GROUP_COL, as_index=False)[MEASURE_COL]
        .sum()
    )
    return time.perf_counter() - t0, merged


def main():
    t_start = time.time()
    names = build_dataset()
    rpc, nodes, threads = start_cluster()
    try:
        import jax

        # warmup: storage decode, XLA compile, HBM/alignment caches
        result = rpc.groupby(
            names, [GROUP_COL], [[MEASURE_COL, "sum", MEASURE_COL]], []
        )
        ours = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            result = rpc.groupby(
                names, [GROUP_COL], [[MEASURE_COL, "sum", MEASURE_COL]], []
            )
            ours.append(time.perf_counter() - t0)
        our_wall = min(ours)

        base_walls, base_df = [], None
        for _ in range(REPEATS):
            wall, base_df = reference_shaped_baseline(names)
            base_walls.append(wall)
        base_wall = min(base_walls)

        # correctness gate: int64 bit-exact against the baseline's answer
        got = dict(
            zip(
                (int(k) for k in result[GROUP_COL]),
                (int(v) for v in result[MEASURE_COL]),
            )
        )
        for _, row in base_df.iterrows():
            key, val = int(row[GROUP_COL]), int(row[MEASURE_COL])
            assert got[key] == val, f"bit-exactness failure at key {key}"

        print(
            json.dumps(
                {
                    "metric": "taxi_groupby_sum_10shard_e2e_rows_per_sec",
                    "value": round(ROWS / our_wall, 1),
                    "unit": "rows/s",
                    "vs_baseline": round(base_wall / our_wall, 3),
                    "detail": {
                        "rows": ROWS,
                        "shards": SHARDS,
                        "framework_wall_s": round(our_wall, 4),
                        "reference_shaped_wall_s": round(base_wall, 4),
                        "backend": jax.default_backend(),
                        "n_devices": len(jax.devices()),
                        "total_s": round(time.time() - t_start, 1),
                    },
                }
            )
        )
    finally:
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)


if __name__ == "__main__":
    sys.exit(main())
