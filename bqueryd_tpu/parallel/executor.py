"""Mesh executor: one query over many shards, merged on-device with psum.

This is the TPU-native replacement for the reference's shard fan-out + merge
pipeline (per-shard tar results at reference bqueryd/worker.py:335-346,
controller tar-of-tars at reference bqueryd/controller.py:186-211, client
re-groupby at reference bqueryd/rpc.py:150-173).  Where the reference ships N
serialized result tables over TCP and re-aggregates them twice, here the N
shards are laid out over a 1-D ``jax.sharding.Mesh`` and the merge is a
``jax.lax.psum`` of index-aligned partial tables riding the ICI — one compiled
program, zero host serialization between partial and merged result.

What makes the psum legal is host-side key alignment: every shard's group
codes are remapped into one *global* composite-key space before the kernel
runs (SURVEY.md §7.3 "Merge alignment"), so row ``g`` of every device's
partial table refers to the same group.  The alignment is cheap (NumPy
searchsorted over per-shard dictionaries, not data rows) and happens once per
query.

Layout: all shards' rows are concatenated and split EVENLY across the mesh's
devices (legal because codes are global — any row partition psums to the same
answer), right-padded with code ``-1`` (the null code — padding contributes
to no group, see ``ops.partial_tables``), giving a balanced, static
``[n_devices, rows_per_device]`` shape XLA can tile.

Falls back to nothing: callers (worker, __graft_entry__, bench) route
non-mergeable aggregations (count_distinct family) and the aggregate=False
raw-rows path through the per-shard ``QueryEngine`` + host merge instead —
those results carry value *sets*, which a fixed-width psum cannot merge.

Steady-state serving is cache-resident (the TPU analogue of bquery's
``auto_cache`` factorization cache, reference bqueryd/worker.py:291),
organized as the working-set layer in :mod:`bqueryd_tpu.ops.workingset`:
host-side key alignment cached per (table-set, groupby-cols), and the
packed device blocks — group codes and measure columns — HBM-resident in
LRU byte-budgeted segments keyed by table identity (rootdir + mtime, so
shard activation invalidates naturally).  A repeated query — including one
with a DIFFERENT measure column, aggregate op or filter — therefore skips
decode, factorize, alignment and (for codes) H2D, and costs one compiled
kernel dispatch; under HBM pressure the working set sheds LRU device
entries before the allocator can wedge.

The cold path is a staged pipeline on the bounded pool in
:mod:`bqueryd_tpu.parallel.pipeline`: storage decode of cache-missing
measure columns is prefetched while key alignment runs, per-shard
decode/factorize fans out on the same pool, and the column build loop
keeps one decode+pack in flight ahead of each H2D transfer — stage busy
clocks feed the ``bqueryd_tpu_pipeline_busy_seconds`` gauges and bench.py's
overlap ratio.
"""

import contextlib
import functools
import os
import threading
import time

import numpy as np

from bqueryd_tpu.models.query import GroupByQuery, ResultPayload


def make_mesh(n_devices=None, axis_name="shards"):
    """A 1-D mesh over the first ``n_devices`` JAX devices.

    In a multi-host job (``ops.maybe_init_distributed``) ``jax.devices()``
    spans every host of the slice, so the shard mesh — and the psum merge —
    covers all chips: ICI within a host, DCN across hosts."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def _put(arr_np, sharding):
    """Host->device placement that also works when the mesh spans hosts:
    multi-host shardings reject a plain device_put of a host-global array,
    so each process materializes only its addressable shards via callback
    (every worker process computes the same global array)."""
    import jax

    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            arr_np.shape, sharding, lambda idx: arr_np[idx]
        )
    return jax.device_put(arr_np, sharding)


def _wire_dtype(tables, col):
    """Narrowest signed int dtype covering every shard's stored [min, max]
    for ``col``, or None to ship the stored dtype unchanged.

    Host->device bytes are the per-query cost floor (PCIe locally, the
    network tunnel under axon), so integer measures ride the wire at the
    width their actual value range needs; the kernel accumulates sums in
    int64 regardless (``ops.groupby._accum_dtype``), keeping aggregates
    bit-exact.  min/max partials are cast back to the stored dtype on the
    host after the merge."""
    lo = hi = None
    stored = None
    for t in tables:
        if t.kind(col) != "numeric":
            return None
        dt = t.physical_dtype(col)
        if dt.kind not in "iu":
            return None
        stored = dt if stored is None else max(stored, dt, key=lambda d: d.itemsize)
        stats = t.col_stats(col)
        if stats is None:
            return None
        lo = stats[0] if lo is None else min(lo, stats[0])
        hi = stats[1] if hi is None else max(hi, stats[1])
    for cand in (np.int8, np.int16, np.int32):
        info = np.iinfo(cand)
        if lo >= info.min and hi <= info.max:
            cand = np.dtype(cand)
            return cand if cand.itemsize < stored.itemsize else None
    return None


def _stored_dtype(tables, col):
    """Widest stored numeric dtype of ``col`` across shards, or None when any
    shard stores it non-numerically (dict/datetime)."""
    dts = []
    for t in tables:
        if t.kind(col) != "numeric":
            return None
        dts.append(t.physical_dtype(col))
    return np.result_type(*dts)


def _measure_kind(tables, col):
    """'datetime' when every shard stores ``col`` as a datetime, 'uint64'
    when every shard stores it unsigned-64 (mod-2^64 sums re-view as
    unsigned at finalize, pandas semantics), None for other numeric/dict;
    mixed datetime/non-datetime storage across shards is a data error."""
    kinds = {t.kind(col) for t in tables}
    if kinds == {"datetime"}:
        return "datetime"
    if "datetime" in kinds:
        raise ValueError(
            f"column {col!r} is datetime on some shards but not others"
        )
    dtypes = [t.physical_dtype(col) for t in tables]
    # the measures themselves widen via result_type (_stored_dtype), so the
    # unsigned tag must follow the WIDENED dtype: u64+u32 shards accumulate
    # in uint64 and their mod-2^64 sums still need the unsigned view
    if dtypes:
        widened = np.result_type(*dtypes)
        if widened == np.dtype(np.uint64):
            return "uint64"
        if widened.kind == "u":
            return "uint"
    return None


def _where_signature(query):
    """Hashable, canonical identity of a query's row-filter."""
    from bqueryd_tpu.models.query import freeze_value

    return (
        freeze_value(query.where_terms or []),
        query.expand_filter_column,
    )


def _codes_dtype(n_groups):
    """Narrowest signed dtype holding dense codes in [-1, n_groups)."""
    if n_groups <= np.iinfo(np.int8).max:
        return np.dtype(np.int8)
    if n_groups <= np.iinfo(np.int16).max:
        return np.dtype(np.int16)
    return np.dtype(np.int32)


# canonical table cache identity lives with the storage layer; kept under
# the old private name for existing importers
from bqueryd_tpu.storage.ctable import table_cache_key as _table_key  # noqa: E402,E501


class MeshQueryExecutor:
    """Executes a :class:`GroupByQuery` over a list of shard tables on a
    device mesh, merging per-shard partials with ``ops.psum_partials``.

    Handles the mergeable aggregation set (``ops.MERGEABLE_OPS``); the worker
    falls back to per-shard execution for distinct-count ops and raw rows.
    """

    def __init__(self, mesh=None, axis_name="shards", timer=None):
        self._mesh = mesh
        self.axis_name = axis_name
        self.timer = timer
        self._align_engine = None
        #: the physical kernel route the last execute() dispatched
        #: (post-guards) — the worker surfaces it as ``effective_strategy``
        #: in calc replies and the ``kernel`` trace span
        self.last_effective_strategy = None
        #: how the last execute() merged partials across the mesh
        #: ("device" | "host") — the worker surfaces it as the reply
        #: envelope's ``merge_mode`` key
        self.last_merge_mode = None
        #: per-shard (decoded, skipped) chunk-prune counts of the last
        #: execute_dag() — the worker folds the totals into its chunk
        #: counters, mirroring opexec.DagExecutor._prune_counts
        self.last_prune_counts = []
        from bqueryd_tpu.ops.workingset import WorkingSet

        # the device-resident working-set layer (ops/workingset.py): LRU
        # byte-budgeted segments with hit/miss/eviction telemetry and
        # HBM-watermark pressure eviction.
        #   align:  (tables_key, groupby_cols) -> (dense codes per shard,
        #           combos, cards, key_values) — host side
        #   codes:  folded+packed group codes -> jax.Array [n_dev, width]
        #   blocks: packed wire-dtype measure columns -> jax.Array
        # On CPU/tunneled backends the device segments count against host
        # RSS, so the defaults stay well under the worker's 2 GB restart
        # threshold (the watchdog clears them before giving up,
        # worker._check_mem)
        self.workingset = WorkingSet()
        self._align_cache = self.workingset.segment("align")
        self._hbm_cache = self.workingset.segment("blocks")
        self._codes_cache = self.workingset.segment("codes")

    def clear_caches(self):
        """Drop host alignment + HBM working-set segments (memory-watchdog
        hook)."""
        self.workingset.clear()
        if self._align_engine is not None:
            self._align_engine.clear_caches()

    @staticmethod
    def _map_shards(fn, items):
        """Map ``fn`` over shards on the shared pipeline pool (the
        decode/factorize/np work dominating cold alignment releases the
        GIL); sequential for single shards or one-thread pipelines.
        BQUERYD_TPU_ALIGN_THREADS caps the alignment fan-out specifically;
        BQUERYD_TPU_PIPELINE_THREADS sizes the pool itself."""
        from bqueryd_tpu.parallel import pipeline

        items = list(items)
        cap = os.environ.get("BQUERYD_TPU_ALIGN_THREADS")
        max_workers = int(cap) if cap is not None else len(items)
        return pipeline.map_ordered(fn, items, max_workers=max_workers)

    def _engine(self):
        """The engine used for alignment/key factorization — persistent so
        its factorize cache survives across queries (a fresh engine per
        execute() would re-factorize every alignment-cache miss)."""
        if self._align_engine is None:
            from bqueryd_tpu.models.query import QueryEngine

            self._align_engine = QueryEngine()
        return self._align_engine

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh(axis_name=self.axis_name)
        return self._mesh

    def _phase(self, name):
        import contextlib

        from bqueryd_tpu.utils.tracing import trace_span

        # every phase is wall-timed (PhaseTimer -> reply phase_timings), a
        # distributed-tracing span when the timer carries a SpanRecorder
        # (obs.trace: "layout" surfaces as "h2d_transfer", "aggregate" as
        # "kernel" — the psum collective merge is fused into that compiled
        # program — and "collect" as "merge", the materialization of the
        # merged partials), and, under BQUERYD_TPU_PROFILE=1, a jax.profiler
        # TraceAnnotation tagged with the active trace_id so device
        # timelines line up with the RPC waterfall
        stack = contextlib.ExitStack()
        stack.enter_context(trace_span(name))
        if self.timer is not None:
            stack.enter_context(self.timer.phase(name))
        return stack

    @staticmethod
    def supports(query: GroupByQuery):
        from bqueryd_tpu import ops

        return query.aggregate and all(
            op in ops.MERGEABLE_OPS for op in query.ops
        )

    # -- key alignment (host-side, dictionary-sized work only) --------------
    def _global_key_space(self, tables, query, engine):
        """Remap every shard's per-column key codes into one global space.

        Returns ``(per_shard_packed, combos, cards, key_values)`` where
        ``combos`` is the sorted global composite-key array, ``cards`` the
        global per-column cardinalities, and ``key_values[col]`` the global
        per-column key-value arrays (indexable by unpacked codes).
        """
        n_cols = len(query.groupby_cols)
        shard_codes = [[] for _ in range(n_cols)]   # [col][shard] -> codes
        shard_values = [[] for _ in range(n_cols)]  # [col][shard] -> uniques
        # composite-sidecar stamps, captured BEFORE any key column is read
        # (TOCTOU note in storage/ctable.py): a mid-align shard rewrite then
        # stores a stale-stamped sidecar that future loads miss
        comp_stamps = [
            getattr(t, "composite_stamp", lambda cols: None)(
                query.groupby_cols
            )
            for t in tables
        ]
        # per-shard decode+factorize is embarrassingly parallel and the
        # native decode/factorize/np IO all release the GIL; the caches the
        # engine touches are lock-protected (utils/cache.BytesCappedCache)
        per_table = self._map_shards(
            lambda table: [
                engine._key_codes(table, col)
                for col in query.groupby_cols
            ],
            tables,
        )
        for results in per_table:
            for ci, (codes, values) in enumerate(results):
                shard_codes[ci].append(np.asarray(codes))
                shard_values[ci].append(np.asarray(values))

        cards = []
        global_values = []
        pos_maps = [[] for _ in range(n_cols)]  # [col][shard] -> local->global
        for ci in range(n_cols):
            allv = np.concatenate(shard_values[ci])
            gvals = np.unique(allv)
            # strip null VALUES (float NaN / datetime NaT) from the global
            # dictionary: the rows referencing them already carry poisoned
            # codes (-1, models/query._key_codes), so keeping the null entry
            # would only create a never-referenced dictionary slot — and the
            # single-key dense shortcut below needs "every dictionary entry
            # is an observed group" to hold exactly
            if gvals.dtype.kind == "f":
                gvals = gvals[~np.isnan(gvals)]
            elif gvals.dtype.kind == "M":
                gvals = gvals[~np.isnat(gvals)]
            cards.append(max(len(gvals), 1))
            global_values.append(gvals)
            for si in range(len(tables)):
                # local dictionary -> global position (dictionary-sized);
                # the rows-sized gather through it happens lazily so a
                # composite-sidecar hit below skips it entirely
                pos_maps[ci].append(
                    np.searchsorted(gvals, shard_values[ci][si])
                )

        def mapped_codes(si, ci):
            # gather local codes through the local->global map; null codes
            # (<0) stay null
            codes = shard_codes[ci][si]
            pos = pos_maps[ci][si]
            return np.where(
                codes >= 0, pos[np.clip(codes, 0, None)], np.int64(-1)
            )

        from bqueryd_tpu import ops

        if n_cols == 1:
            # dense shortcut: every global dictionary entry came from some
            # shard's factorize/dictionary, so it is observed in >=1 row —
            # the global codes are ALREADY dense positions in the sorted
            # dictionary.  Skips the former rows-scale unique, which was
            # ~80% of the cold align wall at bench shapes.
            combos = np.arange(len(global_values[0]), dtype=np.int64)
            dense = self._map_shards(
                lambda si: mapped_codes(si, 0).astype(np.int64),
                range(len(tables)),
            )
            key_values = dict(zip(query.groupby_cols, global_values))
            return dense, combos, cards, key_values

        # guard BEFORE the composite sidecar loader: a sidecar stored by a
        # build predating the overflow guard holds silently WRAPPED packs
        # under the same dictionaries+cards digest — a cache hit must not
        # resurrect corrupt composites.  (The mesh alignment needs the
        # radix order, so past-int64 spaces degrade to the engine path at
        # the worker.)
        if ops.total_cardinality(cards) >= ops.MAX_COMPOSITE:
            raise ops.CompositeOverflow(
                "composite group-key space "
                f"{'x'.join(str(int(c)) for c in cards)} exceeds int64"
            )

        # multi-key: observed composites per shard via the native hash
        # factorizer (O(rows) per shard, small unique sets) instead of one
        # rows-scale sort-unique over the concatenated shards.  The result
        # is persisted next to the shard (composite sidecar) keyed by a
        # digest of the GLOBAL dictionaries + cardinalities: packed codes
        # depend on the whole shard set, so any set change invalidates.
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(np.asarray(cards, dtype=np.int64).tobytes())
        for g in global_values:
            a = np.asarray(g)
            if a.dtype == object:
                h.update(repr(a.tolist()).encode())
            else:
                h.update(a.dtype.str.encode())
                h.update(a.tobytes())
        digest = h.digest()

        def shard_composites(si):
            table = tables[si]
            loader = getattr(table, "composite_cache_load", None)
            if loader is not None:
                # validate against the PRE-READ stamp: shard_codes came from
                # those bytes, not from whatever the file holds now
                hit = loader(
                    query.groupby_cols, digest, stamp=comp_stamps[si]
                )
                if hit is not None:
                    return (
                        np.asarray(hit[0]),
                        np.asarray(hit[1], dtype=np.int64),
                    )
            packed = ops.pack_codes(
                [mapped_codes(si, ci) for ci in range(n_cols)], cards
            )
            inv, uniq = ops.factorize(packed)
            inv = np.asarray(inv)
            uniq = np.asarray(uniq, dtype=np.int64)
            storer = getattr(table, "composite_cache_store", None)
            if storer is not None and comp_stamps[si] is not None:
                storer(
                    query.groupby_cols, digest, inv, uniq,
                    stamp=comp_stamps[si],
                )
            return inv, uniq

        composites = self._map_shards(shard_composites, range(len(tables)))
        local_inverse = [c[0] for c in composites]
        local_uniques = [c[1] for c in composites]
        observed = [u[u >= 0] for u in local_uniques]
        observed = [o for o in observed if len(o)]
        combos = (
            np.unique(np.concatenate(observed))
            if observed
            else np.empty(0, dtype=np.int64)
        )
        # dense codes ride the per-shard dictionary: map each shard's few
        # observed composites into the sorted global combos, then gather
        dense = []
        for inv, uniq in zip(local_inverse, local_uniques):
            lut = np.searchsorted(combos, np.clip(uniq, 0, None)).astype(
                np.int64
            )
            lut[uniq < 0] = -1
            dense.append(lut[inv])
        key_values = dict(zip(query.groupby_cols, global_values))
        return dense, combos, cards, key_values

    # -- device layout ------------------------------------------------------
    @staticmethod
    def _pack(arrays, n_devices, pad, dtype=None):
        """Concat shard arrays and split evenly into ``[n_devices, width]``.

        Because every row carries a GLOBAL dense code, any row partition is
        valid — shard boundaries don't matter to the psum merge.  An even
        split beats greedy shard->device packing: devices are perfectly
        balanced and a single big shard still uses the whole mesh.  ``dtype``
        defaults to the common (widest) dtype of the inputs so mixed-width
        shards never silently wrap."""
        if dtype is None:
            dtype = (
                np.result_type(*[a.dtype for a in arrays])
                if len(arrays) > 1
                else arrays[0].dtype
            )
        from bqueryd_tpu import ops

        total = sum(len(a) for a in arrays)
        # bucketed per-device width (ops.program_bucket): row-count drift
        # across data refreshes reuses the compiled program; padded rows
        # carry the pad code (-1 for codes) and drop from every reduction
        width = ops.program_bucket(
            max(-(-total // n_devices), 1), fine=True
        )
        out = np.full(n_devices * width, pad, dtype=dtype)
        off = 0
        for arr in arrays:
            out[off : off + len(arr)] = arr
            off += len(arr)
        return out.reshape(n_devices, width)

    # -- execution ----------------------------------------------------------
    def execute(self, tables, query: GroupByQuery,
                strategy=None) -> ResultPayload:
        """``strategy`` is the planner's kernel-route hint, threaded into the
        mesh program's ``partial_tables`` call (and its trace cache key);
        None/"auto" keeps the dispatcher's own adaptive choice."""
        from bqueryd_tpu import chaos, ops

        # chaos site worker.device: a transient DeviceBusyError raised here
        # rides the same recovery seam as a real flaky tunneled backend —
        # the worker's handler marks the ErrorMessage transient and the
        # controller fails the shard over to a replica holder.  The
        # enabled() pre-check keeps the disarmed hot path from paying the
        # signature stringification just to hand fire() a discarded ctx
        if chaos.enabled():
            chaos.fire(
                "worker.device",
                n_tables=len(tables),
                signature=str(query.signature())[:120],
            )
        self.last_effective_strategy = None  # set at the kernel dispatch
        self.last_merge_mode = None          # set once the mode resolves
        if strategy in (None, "auto", "host"):
            # "host" is meaningless inside a mesh program; the worker should
            # not have routed such a query here, but degrade to auto rather
            # than refuse
            strategy = None

        if not self.supports(query):
            raise ValueError(
                "MeshQueryExecutor handles mergeable aggregations only; "
                "route distinct-count / raw-rows queries per shard"
            )
        # datetime measures ride the mesh as raw int64 with NaT (int64 min)
        # declared as a null sentinel so NaT rows skip counts and extrema
        # exactly like float NaNs (pandas semantics).  Sums/means of
        # datetimes are rejected HERE, before any alignment/decode/upload
        # work is spent on an invalid query.
        measure_kinds = tuple(
            _measure_kind(tables, col) for col in query.in_cols
        )
        for col, kind, op in zip(query.in_cols, measure_kinds, query.ops):
            if kind == "datetime" and op in ("sum", "mean"):
                raise ValueError(
                    f"{op!r} is not defined for datetime column {col!r}"
                )
        engine = self._engine()

        with self._phase("prune"):
            tables = [
                t
                for t in tables
                if not query.where_terms
                or ops.shard_can_match(t, query.where_terms)
            ]
        if not tables:
            return ResultPayload.empty()
        # calibration buckets key on the dispatch group's total rows — the
        # same quantity the controller's selector estimated from stats
        total_rows = sum(int(t.nrows) for t in tables)

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bqueryd_tpu.parallel import pipeline

        tables_key = tuple(_table_key(t) for t in tables)
        cols_key = tuple(query.groupby_cols)
        mesh = self.mesh
        n_dev = mesh.devices.size
        from bqueryd_tpu.parallel import devicemerge

        # the traced cross-device merge for this query: span-owned
        # reduce-scatter by default, the hostmerge fallback under the
        # BQUERYD_TPU_DEVICE_MERGE=0 kill switch, replicated psum on
        # multi-host pods (devicemerge.resolve_mode)
        merge_mode = devicemerge.resolve_mode()
        self.last_merge_mode = (
            "host" if merge_mode == devicemerge.MODE_HOST else "device"
        )
        sharding = NamedSharding(mesh, P(self.axis_name, None))
        codes_key = (
            tables_key, "codes", cols_key, _where_signature(query), n_dev,
        )

        # fused multi-agg gather: sum+count+mean over the same column pack,
        # upload and feed ONE device block; measure_index maps each agg
        # back to its slot inside the compiled program, so codes are
        # gathered against each distinct column exactly once
        unique_cols = list(dict.fromkeys(query.in_cols))
        measure_index = tuple(
            unique_cols.index(col) for col in query.in_cols
        )
        missing_cols = [
            col for col in unique_cols
            if (tables_key, "col", col, n_dev) not in self._hbm_cache
        ]
        align_warm = (tables_key, cols_key) in self._align_cache
        codes_warm = codes_key in self._codes_cache

        # shed LRU device cache BEFORE this query adds residency, while the
        # PR-3 HBM watermark sample still reflects the previous steady state
        # (evicting after the allocation failed would be a wedge, not a
        # plan).  Cold branches only: a fully-warm query adds nothing, and
        # the memory sample costs a device.memory_stats() round-trip that
        # must never tax steady-state latency — nor may the shed run before
        # a warm query's gets refresh their entries' recency.
        if missing_cols or not codes_warm:
            self.workingset.evict_under_pressure()

        # chunk-decode prefetch (pipeline stage 1): fire storage decode of
        # the cache-missing measure columns on the pipeline pool so decode
        # overlaps the mask/fold + codes-H2D work below.  Deferred until
        # AFTER alignment when the alignment is cold: align's own per-shard
        # fan-out needs the pool, and a FIFO pool would drain these decode
        # jobs first, serializing decode ahead of align instead of
        # overlapping either.  (Firing nowhere on the cold path was the
        # 0.115 cold storage-decode hit rate: the depth-2 column build paid
        # every decode inline with nothing warmed.)  The prefetch decodes
        # through ``ctable.column_raw`` on the SAME table instances the
        # build loop probes, so the warmed entries land under the content
        # keys the build path reads.
        prefetch = {}

        def _prefetch_missing():
            if pipeline.pipeline_threads() <= 1:
                return
            for col in missing_cols:
                futs = []
                for t in tables:
                    warm = getattr(t, "prefetch", None)
                    if warm is not None:
                        futs.extend(warm([col]))
                if futs:
                    prefetch[col] = futs

        if align_warm:
            _prefetch_missing()

        with self._phase("align"), pipeline.stage("align"):
            cached = self._align_cache.get((tables_key, cols_key))
            if cached is None:
                dense, combos, cards, key_values = self._global_key_space(
                    tables, query, engine
                )
                self._align_cache.put(
                    (tables_key, cols_key),
                    (dense, combos, cards, key_values),
                    nbytes=sum(d.nbytes for d in dense)
                    + combos.nbytes
                    + sum(v.nbytes for v in key_values.values()),
                )
            else:
                dense, combos, cards, key_values = cached
            n_groups = max(len(combos), 1)

        if not align_warm:
            # cold-align queries fire the measure prefetch HERE, once the
            # align fan-out has released the pool: decode overlaps the
            # mask/fold/pack + codes-H2D work below instead of serializing
            # inside the column build
            _prefetch_missing()

        codes_d = self._codes_cache.get(codes_key)
        if codes_d is None:
            # cold path only: masks + fold + pack + H2D.  On a cache hit the
            # whole filter evaluation is skipped — the folded codes ARE the
            # filter.
            with self._phase("mask"):
                masks = []
                for table in tables:
                    mask = ops.build_mask(table, query.where_terms)
                    if query.expand_filter_column:
                        # cached, with nulls-are-a-basket semantics
                        bcodes, buniques = engine._basket_codes(
                            table, query.expand_filter_column
                        )
                        mask = ops.expand_mask_by_group(
                            bcodes, mask, n_groups=len(buniques)
                        )
                    masks.append(None if mask is None else np.asarray(mask))
            with self._phase("layout"):
                # fold the row mask into the codes: masked-out rows become
                # null (code -1) and vanish from every segment reduction.
                # Folds into fresh arrays — cached dense stays unmasked.
                with pipeline.stage("align"):
                    cdt = _codes_dtype(n_groups)
                    folded = [
                        np.where(mask, d, -1).astype(cdt)
                        if mask is not None
                        else d.astype(cdt)
                        for d, mask in zip(dense, masks)
                    ]
                    packed = self._pack(
                        folded, n_dev, cdt.type(-1), dtype=cdt
                    )
                with pipeline.stage("h2d"):
                    codes_d = _put(packed, sharding)
                self._codes_cache.put(codes_key, codes_d)

        with self._phase("layout"):
            def build_packed(col):
                # wait for this column's prefetched decodes first: they
                # populate the storage cache, and racing a duplicate decode
                # here would burn the cores the pipeline is trying to share
                for fut in prefetch.get(col, ()):
                    fut.result()
                with pipeline.stage("decode"):
                    # decode (C++ chunk threads, GIL released) + narrow +
                    # pack into the [n_dev, width] device layout
                    wire = (
                        _wire_dtype(tables, col)
                        or _stored_dtype(tables, col)
                    )
                    cols = [np.asarray(t.column_raw(col)) for t in tables]
                    if wire is not None:
                        cols = [c.astype(wire, copy=False) for c in cols]
                    return self._pack(cols, n_dev, 0, dtype=wire)

            # cold path with several columns: overlap the NEXT column's
            # decode+pack with the CURRENT column's host->device transfer
            # (the two dominate cold latency and use disjoint resources)
            missing = [
                col
                for col in unique_cols
                if (tables_key, "col", col, n_dev) not in self._hbm_cache
            ]
            futures = {}
            use_pool = len(missing) > 1 and pipeline.pipeline_threads() > 1
            missing_iter = iter(missing)

            def submit_next():
                for c in missing_iter:
                    futures[c] = pipeline.submit(build_packed, c)
                    return

            if use_pool:
                # prime ONE build ahead of the put loop; the next is
                # submitted as each is consumed — exactly one build in
                # flight plus the column being uploaded, so peak host
                # residency stays ~2 packed columns however many are
                # missing (priming two would run both concurrently on the
                # shared pool: ~3 resident)
                submit_next()
            measures_d = []
            for col in unique_cols:
                mkey = (tables_key, "col", col, n_dev)
                arr = self._hbm_cache.get(mkey)
                if arr is None:
                    if col in futures:
                        packed = futures.pop(col).result()
                        submit_next()
                    else:
                        packed = build_packed(col)
                    with pipeline.stage("h2d"):
                        arr = _put(packed, sharding)
                    self._hbm_cache.put(mkey, arr)
                measures_d.append(arr)

        with self._phase("aggregate"), pipeline.stage("kernel"):
            sentinels = tuple(
                np.iinfo(np.int64).min if k == "datetime" else None
                for k in measure_kinds
            )
            # returns host numpy partials; with packed fetch (default) the
            # whole merged pytree comes back as ONE device buffer — per-leaf
            # pulls cost a full transport round-trip each on tunneled/remote
            # devices
            # the program computes over the BUCKETED group count (shape
            # reuse across cardinality drift, ops.program_bucket); padded
            # groups have zero rows and are sliced off right below, on host
            n_prog = ops.program_bucket(n_groups)
            # the physical route this dispatch takes post-guards: reported
            # as effective_strategy and the label calibration samples land
            # under (hints silently normalized here until this existed —
            # neither traces nor bench could tell what actually ran)
            per_agg_d = tuple(measures_d[i] for i in measure_index)
            # normalize the hint BEFORE predicting/labelling the route: a
            # hint the guards would normalize inside _mesh_partials (e.g.
            # "scatter" on a backend whose auto dispatch internally sorts)
            # must not be reported — or recorded into calibration cells —
            # as a route the program never ran (the highcard cell-keying
            # bug: "scatter"-labelled walls that were really the sort path)
            strategy = _effective_mesh_strategy(
                strategy, tuple(query.ops), n_prog, per_agg_d,
                int(codes_d.shape[1]),
            )
            route = ops.kernel_route(
                strategy, per_agg_d, tuple(query.ops),
                int(codes_d.shape[1]), n_prog,
            )
            self.last_effective_strategy = route
            from bqueryd_tpu.obs import profile as obs_profile

            profiler = obs_profile.profiler()
            # tunneled backends surface transient remote-compile INTERNAL
            # errors (HTTP 500 compile-helper crashes observed on hardware,
            # TPU_VALIDATE_r5_prefix.json case7/case13): one retry keeps
            # the on-device merge path; a second failure propagates to the
            # worker, which degrades to the per-shard engine path
            for attempt in range(2):
                misses_before = profiler.jit_cache_misses
                kernel_clock = time.perf_counter()
                try:
                    merged = _mesh_partials(
                        mesh, self.axis_name, query.ops, n_prog,
                        codes_d, tuple(measures_d),
                        null_sentinels=sentinels,
                        strategy=strategy,
                        measure_index=measure_index,
                        merge_mode=merge_mode,
                        timer=self.timer,
                    )
                    kernel_wall = time.perf_counter() - kernel_clock
                    break
                except jax.errors.JaxRuntimeError as exc:
                    # deterministic failures (INVALID_ARGUMENT, device OOM)
                    # would fail identically: propagate at once and let the
                    # worker degrade, keeping the sleep out of their path
                    # (and out of the aggregate-phase timing)
                    if attempt or not _transient_status(exc):
                        raise
                    time.sleep(0.5)
            # measured-cost calibration sample (the planner feedback loop):
            # walls tainted by a jit compile are skipped — a 20 s compile
            # inside a 4 ms kernel wall would poison the route's EWMA
            from bqueryd_tpu.plan import calibrate

            if (
                calibrate.enabled()
                and profiler.jit_cache_misses == misses_before
            ):
                prog = profiler.last_program("executor.mesh_program")
                calibrate.record_sample(
                    rows=total_rows, groups=n_groups,
                    dtypes=[m.dtype for m in per_agg_d],
                    backend=jax.default_backend(),
                    strategy=route, wall_s=kernel_wall,
                    flops=(prog or {}).get("flops"),
                    bytes_accessed=(prog or {}).get("bytes_accessed"),
                )
            if n_prog != n_groups:
                import jax as _jax

                # group axis is LAST: host-mode partials carry a leading
                # per-device axis, merged tables are flat
                merged = _jax.tree_util.tree_map(
                    lambda a: a[..., :n_groups], merged
                )

        with self._phase("collect"), pipeline.stage("merge"):
            return self._finish_collect(
                merged, merge_mode, int(n_dev), query, tables,
                combos, cards, key_values, measure_kinds,
            )

    def _collect_payload(self, partial_table, query, tables, combos, cards,
                         key_values, measure_kinds):
        """One merged (or single-device) partial table -> ResultPayload
        keyed by actual key values."""
        from bqueryd_tpu import ops

        rows = partial_table["rows"]
        present = rows > 0
        combos_present = combos[present]
        if len(query.groupby_cols) == 1:
            key_codes = [combos_present]
        else:
            key_codes = ops.unpack_codes(combos_present, cards)
        keys = {}
        for col, codes_g in zip(query.groupby_cols, key_codes):
            idx = np.asarray(codes_g, dtype=np.int64)
            keys[col] = key_values[col][idx]
        aggs = []
        for in_col, part in zip(query.in_cols, partial_table["aggs"]):
            stored = _stored_dtype(tables, in_col)
            selected = {}
            for k, v in part.items():
                v = v[present]
                # min/max partials computed on a narrowed wire dtype go
                # back to the column's stored dtype
                if (
                    k in ("min", "max")
                    and stored is not None
                    and v.dtype != stored
                    and stored.kind in "iu"
                ):
                    v = v.astype(stored)
                selected[k] = v
            aggs.append(selected)
        return ResultPayload.partials(
            key_cols=query.groupby_cols,
            keys=keys,
            rows=rows[present],
            aggs=aggs,
            ops=query.ops,
            out_cols=query.out_cols,
            value_kinds=list(measure_kinds),
        )

    def _finish_collect(self, merged, merge_mode, n_dev, query, tables,
                        combos, cards, key_values, measure_kinds):
        """Merged partials (one query's pytree) -> its ResultPayload, per
        merge mode.  Host mode re-merges the per-device tables with the
        always-correct value-keyed merge — bit-identical aggregates,
        host-gather economics."""
        import jax

        from bqueryd_tpu.parallel import devicemerge

        if merge_mode == devicemerge.MODE_HOST:
            from bqueryd_tpu.parallel import hostmerge

            payloads = [
                self._collect_payload(
                    jax.tree_util.tree_map(lambda a: a[d], merged),
                    query, tables, combos, cards, key_values, measure_kinds,
                )
                for d in range(int(n_dev))
            ]
            return ResultPayload(hostmerge.merge_payloads(payloads))
        return self._collect_payload(
            merged, query, tables, combos, cards, key_values, measure_kinds,
        )

    # -- shared-scan bundles -------------------------------------------------
    def execute_bundle(self, tables, queries, strategy=None):
        """Shared-scan execution of a compatible query bundle: every query
        scans the same ``tables`` with the same group-key columns; measures
        and filters may differ per member.  One decode/align/factorize pass,
        one (unmasked) codes upload, one deduplicated union measure upload,
        one stacked-mask H2D, and ONE mesh program whose per-member partial
        tables merge in one collective pass.  Returns one
        :class:`ResultPayload` per query, input order.

        Parity contract: each member's partials are emitted by the same
        per-member :func:`ops.partial_tables` dispatch its solo execution
        would run (the mask rides the kernel's ``mask=`` argument, which
        zeroes exactly the contributions code-folding would drop), so
        integer aggregates are bit-identical to unfused execution and float
        aggregates differ only by kernel-route reassociation."""
        from bqueryd_tpu import chaos, ops
        from bqueryd_tpu.models.query import freeze_value

        if not queries:
            return []
        if chaos.enabled():
            chaos.fire(
                "worker.device",
                n_tables=len(tables),
                signature=f"bundle:{len(queries)}",
            )
        self.last_effective_strategy = None
        self.last_merge_mode = None
        if strategy in (None, "auto", "host"):
            strategy = None
        gcols = tuple(queries[0].groupby_cols)
        for query in queries:
            if tuple(query.groupby_cols) != gcols:
                raise ValueError(
                    "bundle members must share group-key columns"
                )
            if not self.supports(query):
                raise ValueError(
                    "bundle members must be mergeable aggregations"
                )
        # the union measure upload: every DISTINCT column across the bundle,
        # first-seen order; per-member aggs map onto slots in this union
        union_cols = list(
            dict.fromkeys(c for q in queries for c in q.in_cols)
        )
        union_kinds = tuple(
            _measure_kind(tables, col) for col in union_cols
        )
        kind_of = dict(zip(union_cols, union_kinds))
        for query in queries:
            for col, op in zip(query.in_cols, query.ops):
                if kind_of[col] == "datetime" and op in ("sum", "mean"):
                    raise ValueError(
                        f"{op!r} is not defined for datetime column {col!r}"
                    )
        engine = self._engine()

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bqueryd_tpu.parallel import devicemerge, pipeline

        tables_key = tuple(_table_key(t) for t in tables)
        cols_key = tuple(gcols)
        mesh = self.mesh
        n_dev = mesh.devices.size
        merge_mode = devicemerge.resolve_mode()
        self.last_merge_mode = (
            "host" if merge_mode == devicemerge.MODE_HOST else "device"
        )
        sharding = NamedSharding(mesh, P(self.axis_name, None))
        # the bundle's codes ride UNMASKED (each member's filter applies on
        # device through the stacked mask axis) — which is exactly the codes
        # entry an unfiltered single query folds, so the cache key is shared
        # with (and warms) the plain single-query path
        codes_key = (
            tables_key, "codes", cols_key, (freeze_value([]), None), n_dev,
        )
        missing_cols = [
            col for col in union_cols
            if (tables_key, "col", col, n_dev) not in self._hbm_cache
        ]
        align_warm = (tables_key, cols_key) in self._align_cache
        codes_warm = codes_key in self._codes_cache
        if missing_cols or not codes_warm:
            self.workingset.evict_under_pressure()

        # prefetch depth = the whole bundle's union: every member's missing
        # measure column fires its storage decode on the pool up front, so
        # the shared pass never pays a member's decode inline (the single-
        # query path prefetches only its own columns)
        prefetch = {}

        def _prefetch_missing():
            if pipeline.pipeline_threads() <= 1:
                return
            for col in missing_cols:
                futs = []
                for t in tables:
                    warm = getattr(t, "prefetch", None)
                    if warm is not None:
                        futs.extend(warm([col]))
                if futs:
                    prefetch[col] = futs

        if align_warm:
            _prefetch_missing()

        with self._phase("align"), pipeline.stage("align"):
            cached = self._align_cache.get((tables_key, cols_key))
            if cached is None:
                dense, combos, cards, key_values = self._global_key_space(
                    tables, queries[0], engine
                )
                self._align_cache.put(
                    (tables_key, cols_key),
                    (dense, combos, cards, key_values),
                    nbytes=sum(d.nbytes for d in dense)
                    + combos.nbytes
                    + sum(v.nbytes for v in key_values.values()),
                )
            else:
                dense, combos, cards, key_values = cached
            n_groups = max(len(combos), 1)

        if not align_warm:
            _prefetch_missing()

        codes_d = self._codes_cache.get(codes_key)
        if codes_d is None:
            with self._phase("layout"):
                with pipeline.stage("align"):
                    cdt = _codes_dtype(n_groups)
                    packed = self._pack(
                        [d.astype(cdt) for d in dense], n_dev,
                        cdt.type(-1), dtype=cdt,
                    )
                with pipeline.stage("h2d"):
                    codes_d = _put(packed, sharding)
                self._codes_cache.put(codes_key, codes_d)

        # stacked per-member masks: one row per member that filters, one
        # H2D for the whole stack.  Members without filters index None and
        # feed the kernel mask=None — the bit-identical solo form.
        mask_rows = []
        mask_idx_of = {}
        with self._phase("mask"):
            for qi, query in enumerate(queries):
                if not query.where_terms:
                    continue
                shard_masks = []
                for table in tables:
                    mask = ops.build_mask(table, query.where_terms)
                    shard_masks.append(
                        np.ones(int(table.nrows), dtype=bool)
                        if mask is None else np.asarray(mask)
                    )
                mask_idx_of[qi] = len(mask_rows)
                mask_rows.append(
                    self._pack(shard_masks, n_dev, False, dtype=np.bool_)
                )
        masks_d = None
        if mask_rows:
            with self._phase("layout"), pipeline.stage("h2d"):
                masks_d = _put(
                    np.stack(mask_rows),
                    NamedSharding(mesh, P(None, self.axis_name, None)),
                )

        with self._phase("layout"):
            def build_packed(col):
                for fut in prefetch.get(col, ()):
                    fut.result()
                with pipeline.stage("decode"):
                    wire = (
                        _wire_dtype(tables, col)
                        or _stored_dtype(tables, col)
                    )
                    cols = [np.asarray(t.column_raw(col)) for t in tables]
                    if wire is not None:
                        cols = [c.astype(wire, copy=False) for c in cols]
                    return self._pack(cols, n_dev, 0, dtype=wire)

            missing = [
                col
                for col in union_cols
                if (tables_key, "col", col, n_dev) not in self._hbm_cache
            ]
            futures = {}
            use_pool = len(missing) > 1 and pipeline.pipeline_threads() > 1
            missing_iter = iter(missing)

            def submit_next():
                for c in missing_iter:
                    futures[c] = pipeline.submit(build_packed, c)
                    return

            if use_pool:
                submit_next()
            measures_d = []
            for col in union_cols:
                mkey = (tables_key, "col", col, n_dev)
                arr = self._hbm_cache.get(mkey)
                if arr is None:
                    if col in futures:
                        packed = futures.pop(col).result()
                        submit_next()
                    else:
                        packed = build_packed(col)
                    with pipeline.stage("h2d"):
                        arr = _put(packed, sharding)
                    self._hbm_cache.put(mkey, arr)
                measures_d.append(arr)

        slot_of = {col: i for i, col in enumerate(union_cols)}
        sentinels = tuple(
            np.iinfo(np.int64).min if k == "datetime" else None
            for k in union_kinds
        )
        member_specs = tuple(
            (
                mask_idx_of.get(qi),
                tuple(
                    (slot_of[col], op)
                    for col, op in zip(query.in_cols, query.ops)
                ),
            )
            for qi, query in enumerate(queries)
        )

        with self._phase("aggregate"), pipeline.stage("kernel"):
            n_prog = ops.program_bucket(n_groups)
            # route label: on CPU the shared-scan kernel is the batched
            # scatter family regardless of any hint; on accelerators the
            # bundle runs per-member partial_tables dispatches (the
            # batched form would be the emulated wide scatter — see
            # ops.bundle_partial_tables), where the first member's
            # predicted route speaks for the bundle
            import jax as _jax

            if _jax.default_backend() == "cpu":
                self.last_effective_strategy = "scatter"
            else:
                first = queries[0]
                self.last_effective_strategy = ops.kernel_route(
                    strategy,
                    tuple(measures_d[slot_of[c]] for c in first.in_cols),
                    tuple(first.ops), int(codes_d.shape[1]), n_prog,
                )
            merged_members = _mesh_bundle_partials(
                mesh, self.axis_name, n_prog, codes_d, masks_d,
                tuple(measures_d), member_specs, sentinels,
                strategy=strategy, merge_mode=merge_mode,
                timer=self.timer,
            )
            if n_prog != n_groups:
                merged_members = jax.tree_util.tree_map(
                    lambda a: a[..., :n_groups], merged_members
                )

        with self._phase("collect"), pipeline.stage("merge"):
            out = []
            for query, merged in zip(queries, merged_members):
                member_kinds = [kind_of[c] for c in query.in_cols]
                out.append(
                    self._finish_collect(
                        merged, merge_mode, int(n_dev), query, tables,
                        combos, cards, key_values, member_kinds,
                    )
                )
            return out

    # -- operator-DAG fast path ----------------------------------------------
    def execute_dag(self, tables, dag):
        """Batched mesh execution of an EXTENDED operator DAG (joins /
        top-k / quantile sketches / window rollups): one decode/align/H2D
        pass over the whole shard group — join-probe gathers, window-bucket
        derived keys and the folded composite codes all land in the same
        content-keyed working-set segments the classic path uses — one
        compiled mesh program emitting every aggregation's partial state,
        and the PR-7 span-owned device-resident merge: classic GroupAgg
        partials and sketch bucket grids reduce-scatter (associative
        bucket-count addition), top-k dense tables all-gather + re-select
        on device, so only the final merged table leaves HBM.  Returns ONE
        :class:`ResultPayload` for the whole group (``merge_mode``
        "device").

        Raises :class:`DagFastPathUnsupported` for shapes the mesh cannot
        merge (count_distinct sets, raw rows, object-dtype derived
        measures, an over-budget sketch grid, composite overflow, the
        ``BQUERYD_TPU_DEVICE_MERGE=0`` kill switch): the worker then falls
        back to the PR-13 per-shard pipeline + host value-keyed merge.
        Parity vs that fallback: integer aggregates, top-k value multisets
        and sketch buckets are bit-identical; float sums/means differ only
        by reassociation (the same tolerance class as every kernel route
        choice); query-shape validation errors (:class:`DagValidationError`,
        datetime sums) raise identically on both routes."""
        from bqueryd_tpu import chaos, ops
        from bqueryd_tpu.models.query import (
            MERGEABLE_OPS,
            ResultPayload,
        )
        from bqueryd_tpu.parallel import devicemerge, opexec, pipeline
        from bqueryd_tpu.plan.dag import DagValidationError, parse_op

        if chaos.enabled():
            chaos.fire(
                "worker.device",
                n_tables=len(tables),
                signature=f"dag:{str(dag.signature())[:100]}",
            )
        self.last_effective_strategy = None
        self.last_merge_mode = None
        self.last_prune_counts = []
        merge_mode = devicemerge.resolve_mode()
        if merge_mode == devicemerge.MODE_HOST:
            raise DagFastPathUnsupported(
                "BQUERYD_TPU_DEVICE_MERGE=0: merge stays host-side"
            )
        if not dag.aggregate_rows:
            raise DagFastPathUnsupported("raw-rows DAGs dispatch per shard")
        parsed = [parse_op(a[1]) for a in dag.aggs]
        classic_idx, topk_idx, sketch_idx = [], [], []
        for i, p in enumerate(parsed):
            if p[0] in MERGEABLE_OPS:
                classic_idx.append(i)
            elif p[0] == "topk":
                topk_idx.append(i)
            elif p[0] == "quantile":
                sketch_idx.append(i)
            else:
                raise DagFastPathUnsupported(
                    f"op {dag.aggs[i][1]!r} has no device-mergeable partial"
                )

        with self._phase("prune"):
            if dag.scan.pushdown:
                tables = [
                    t for t in tables
                    if ops.shard_can_match(t, dag.scan.pushdown)
                ]
                pruned = []
                for t in tables:
                    view, decoded, skipped = ops.chunk_pruned_table(
                        t, dag.scan.pushdown
                    )
                    pruned.append(view)
                    if decoded or skipped:
                        self.last_prune_counts.append((decoded, skipped))
                tables = pruned
        if not tables:
            return ResultPayload.empty()

        first = tables[0]

        def col_source(col):
            if dag.window is not None and col == dag.window.alias:
                return "window"
            if dag.join is not None and col in dag.join.select:
                return "join"
            if col not in first:
                raise DagValidationError(
                    f"column {col!r} is not a fact column, a join-selected "
                    f"column, or the window alias"
                )
            return "fact"

        from bqueryd_tpu.parallel.opexec import NAT_SENTINEL

        unique_cols = list(dict.fromkeys(a[0] for a in dag.aggs))
        kind_of, sentinel_of = {}, {}
        for col in unique_cols:
            src = col_source(col)
            if src == "window":
                kind_of[col], sentinel_of[col] = "datetime", NAT_SENTINEL
            elif src == "join":
                dimv = np.asarray(dag.join.table[col])
                if dimv.dtype == object:
                    raise DagFastPathUnsupported(
                        f"object-dtype join measure {col!r}"
                    )
                # the ONE shared copy of the dim-measure dtype rules
                # (opexec.dim_measure_kind): leg parity depends on it
                sentinel_of[col], kind_of[col] = opexec.dim_measure_kind(
                    dimv.dtype
                )
            else:
                kind_of[col] = _measure_kind(tables, col)
                sentinel_of[col] = (
                    NAT_SENTINEL if kind_of[col] == "datetime" else None
                )
        # query-shape validation, identical (message and class) to the
        # per-shard route so the fast path never masks or changes an error
        for i, (in_col, op, _out) in enumerate(dag.aggs):
            kind = parsed[i][0]
            if kind in ("sum", "mean") and kind_of[in_col] == "datetime":
                raise ValueError(
                    f"{kind!r} is not defined for datetime column {in_col!r}"
                )
            src = col_source(in_col)
            is_dict = src == "fact" and first.kind(in_col) == "dict"
            if kind == "topk" and is_dict:
                raise DagValidationError(
                    f"topk measure {in_col!r} must be numeric or "
                    f"datetime, not strings"
                )
            if kind == "quantile" and (
                is_dict or sentinel_of[in_col] is not None
            ):
                raise DagValidationError(
                    f"quantile measure {in_col!r} must be numeric "
                    f"(strings/datetimes have no sketch ordering)"
                )

        engine = self._engine()
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        tables_key = tuple(_table_key(t) for t in tables)
        derive_sig = dag.derive_signature()
        mesh = self.mesh
        n_dev = mesh.devices.size
        self.last_merge_mode = "device"
        sharding = NamedSharding(mesh, P(self.axis_name, None))

        # per-shard derivations (join probe / window buckets / per-key
        # codes) — the EXACT per-shard host code of the fallback route
        # (opexec.DagExecutor), content-keyed in the align segment so a
        # repeat query (same derivations, any measures) skips them all
        dexec = opexec.DagExecutor(engine)

        def derive(table):
            dkey = (_table_key(table), "dagderive", derive_sig)
            hit = self._align_cache.get(dkey)
            if hit is not None:
                return hit
            state = opexec._ShardState(table, dag)
            mask = ops.build_mask(table, dag.scan.pushdown)
            mask = None if mask is None else np.asarray(mask, dtype=bool)
            if dag.join is not None:
                mask = dexec._probe_join(state, mask)
            if dag.window is not None:
                dexec._derive_window(state)
            if dag.filter is not None and dag.filter.terms:
                for col, fop, value in dag.filter.terms:
                    m = opexec._eval_post_term(
                        dexec._post_filter_values(state, col), fop, value
                    )
                    mask = m if mask is None else (mask & m)
            per_key = [
                dexec._key_codes_for(state, c) for c in dag.group_keys
            ]
            entry = (mask, per_key, state.row_pos, state.window_ints)
            nbytes = sum(
                np.asarray(c).nbytes + np.asarray(v).nbytes
                for c, v in per_key
            )
            for extra in (mask, state.row_pos, state.window_ints):
                if extra is not None:
                    nbytes += np.asarray(extra).nbytes
            self._align_cache.put(dkey, entry, nbytes=nbytes)
            return entry

        derived_memo = {}

        def get_derived():
            if "v" not in derived_memo:
                derived_memo["v"] = self._map_shards(derive, tables)
            return derived_memo["v"]

        missing_cols = [
            col for col in unique_cols
            if (
                (tables_key, "col", col, n_dev) not in self._hbm_cache
                if col_source(col) == "fact"
                else (tables_key, "dagcol", col, derive_sig, n_dev)
                not in self._hbm_cache
            )
        ]
        codes_key = (tables_key, "dagcodes", derive_sig, n_dev)
        codes_warm = codes_key in self._codes_cache
        if missing_cols or not codes_warm:
            self.workingset.evict_under_pressure()

        with self._phase("align"), pipeline.stage("align"):
            akey = (tables_key, "dagalign", derive_sig)
            cached = self._align_cache.get(akey)
            if cached is None:
                dense, combo_cols, key_values = self._dag_key_space(
                    get_derived(), dag
                )
                self._align_cache.put(
                    akey, (dense, combo_cols, key_values),
                    nbytes=sum(d.nbytes for d in dense)
                    + combo_cols.nbytes
                    + sum(
                        np.asarray(v).nbytes for v in key_values.values()
                    ),
                )
            else:
                dense, combo_cols, key_values = cached
            n_groups = max(len(combo_cols), 1)

        # sketch-grid budget BEFORE any upload: the device merge
        # materializes one dense [padded_groups, width] int64 grid per
        # sketch agg per device — past the cell budget the flat host merge
        # is the better economics and the whole query falls back
        n_prog = ops.program_bucket(n_groups)
        span, padded = devicemerge.bucket_span(n_prog, int(n_dev))
        sketch_geo = {}
        for i in sketch_idx:
            alpha = parsed[i][2]
            width, kmin = opexec.sketch_grid_layout(alpha)
            if padded * width > sketch_grid_cells_limit():
                raise DagFastPathUnsupported(
                    f"sketch grid {padded}x{width} cells exceeds "
                    f"BQUERYD_TPU_SKETCH_GRID_CELLS"
                )
            sketch_geo[i] = (width, kmin)

        codes_d = self._codes_cache.get(codes_key)
        if codes_d is None:
            with self._phase("layout"):
                with pipeline.stage("align"):
                    cdt = _codes_dtype(n_groups)
                    packed = self._pack(
                        [d.astype(cdt) for d in dense], n_dev,
                        cdt.type(-1), dtype=cdt,
                    )
                with pipeline.stage("h2d"):
                    codes_d = _put(packed, sharding)
                self._codes_cache.put(codes_key, codes_d)

        with self._phase("layout"):
            measures_d, slot_of = [], {}
            for col in unique_cols:
                if col_source(col) == "fact":
                    mkey = (tables_key, "col", col, n_dev)
                    arr = self._hbm_cache.get(mkey)
                    if arr is None:
                        with pipeline.stage("decode"):
                            wire = (
                                _wire_dtype(tables, col)
                                or _stored_dtype(tables, col)
                            )
                            cols = [
                                np.asarray(t.column_raw(col))
                                for t in tables
                            ]
                            if wire is not None:
                                cols = [
                                    c.astype(wire, copy=False)
                                    for c in cols
                                ]
                            packed = self._pack(cols, n_dev, 0, dtype=wire)
                        with pipeline.stage("h2d"):
                            arr = _put(packed, sharding)
                        self._hbm_cache.put(mkey, arr)
                else:
                    mkey = (tables_key, "dagcol", col, derive_sig, n_dev)
                    arr = self._hbm_cache.get(mkey)
                    if arr is None:
                        with pipeline.stage("decode"):
                            vals = []
                            for entry in get_derived():
                                _m, _pk, row_pos, window_ints = entry
                                if col_source(col) == "window":
                                    vals.append(np.asarray(window_ints))
                                else:
                                    vals.append(
                                        opexec.gathered_dim_values(
                                            dag.join.table[col], row_pos
                                        )
                                    )
                            packed = self._pack(vals, n_dev, 0)
                        with pipeline.stage("h2d"):
                            arr = _put(packed, sharding)
                        self._hbm_cache.put(mkey, arr)
                slot_of[col] = len(measures_d)
                measures_d.append(arr)

        classic_spec = tuple(
            (
                slot_of[dag.aggs[i][0]],
                parsed[i][0],
                sentinel_of[dag.aggs[i][0]],
            )
            for i in classic_idx
        )
        topk_spec = []
        for i in topk_idx:
            col = dag.aggs[i][0]
            dt = np.dtype(measures_d[slot_of[col]].dtype)
            if dt == object:
                raise DagFastPathUnsupported(
                    f"object-dtype topk measure {col!r}"
                )
            is_float = np.issubdtype(dt, np.floating)
            topk_spec.append(
                (
                    slot_of[col], parsed[i][1], parsed[i][2],
                    is_float,
                    None if sentinel_of[col] is None
                    else int(sentinel_of[col]),
                    is_float,
                )
            )
        topk_spec = tuple(topk_spec)
        sketch_spec = []
        for i in sketch_idx:
            col = dag.aggs[i][0]
            alpha = parsed[i][2]
            _gamma, lg, imin, imax = opexec.sketch_layout(alpha)
            width, kmin = sketch_geo[i]
            sketch_spec.append(
                (slot_of[col], float(lg), int(imin), int(imax),
                 int(kmin), int(width))
            )
        sketch_spec = tuple(sketch_spec)

        with self._phase("aggregate"), pipeline.stage("kernel"):
            per_classic_d = tuple(
                measures_d[s] for s, _op, _st in classic_spec
            )
            self.last_effective_strategy = ops.kernel_route(
                None, per_classic_d,
                tuple(op for _s, op, _st in classic_spec),
                int(codes_d.shape[1]), n_prog,
            )
            merged = _mesh_dag_partials(
                mesh, self.axis_name, n_prog, codes_d, tuple(measures_d),
                classic_spec, topk_spec, sketch_spec,
                merge_mode=merge_mode, timer=self.timer,
            )
            if n_prog != n_groups:
                merged = jax.tree_util.tree_map(
                    lambda a: a[:n_groups], merged
                )

        with self._phase("collect"), pipeline.stage("merge"):
            rows = np.asarray(merged["classic"]["rows"])
            present = rows > 0
            present_idx = np.flatnonzero(present)
            keys = {}
            for ci, col in enumerate(dag.group_keys):
                vals = np.asarray(key_values[col])
                keys[col] = vals[combo_cols[present_idx, ci]]
            aggs_out = [None] * len(dag.aggs)
            for pos, i in enumerate(classic_idx):
                in_col = dag.aggs[i][0]
                stored = (
                    _stored_dtype(tables, in_col)
                    if col_source(in_col) == "fact" else None
                )
                sel = {}
                for kname, v in dict(
                    merged["classic"]["aggs"][pos]
                ).items():
                    v = np.asarray(v)[present]
                    if (
                        kname in ("min", "max")
                        and stored is not None
                        and v.dtype != stored
                        and stored.kind in "iu"
                    ):
                        v = v.astype(stored)
                    sel[kname] = v
                aggs_out[i] = sel
            for pos, i in enumerate(topk_idx):
                in_col = dag.aggs[i][0]
                top, cnt = merged["topk"][pos]
                top = np.asarray(top)[present_idx]
                cnt = np.asarray(cnt)[present_idx]
                stored = (
                    _stored_dtype(tables, in_col)
                    if col_source(in_col) == "fact" else None
                )
                if (
                    stored is not None
                    and top.dtype != stored
                    and stored.kind in "iu"
                ):
                    top = top.astype(stored)
                flat, offsets = opexec.dense_topk_to_flat(top, cnt)
                aggs_out[i] = {
                    "topk_values": flat, "topk_offsets": offsets
                }
            for pos, i in enumerate(sketch_idx):
                grid = np.asarray(merged["sketch"][pos])[present_idx]
                _width, kmin = sketch_geo[i]
                skeys, scounts, soffs = opexec.sketch_grid_to_flat(
                    grid, kmin
                )
                aggs_out[i] = {
                    "sketch_keys": skeys,
                    "sketch_counts": scounts,
                    "sketch_offsets": soffs,
                }
            value_kinds = [
                None if parsed[i][0] == "quantile"
                else kind_of[dag.aggs[i][0]]
                for i in range(len(dag.aggs))
            ]
            return ResultPayload.partials(
                key_cols=list(dag.group_keys),
                keys=keys,
                rows=rows[present],
                aggs=aggs_out,
                ops=[a[1] for a in dag.aggs],
                out_cols=[a[2] for a in dag.aggs],
                value_kinds=value_kinds,
            )

    def _dag_key_space(self, derived, dag):
        """Global composite key space over the DAG's (possibly derived)
        group keys — the DAG twin of :meth:`_global_key_space`, fed by the
        cached per-shard derivations instead of ``engine._key_codes``.
        The pushdown / join-miss / post-derivation-filter mask is folded
        INTO the dense codes here (the derivation signature keys the cache
        entry, so a different filter is a different entry): masked rows
        carry code -1 and vanish from every reduction, exactly like the
        classic folded codes.  Returns ``(folded dense codes per shard,
        combo_cols [n_combos, n_cols] global dictionary positions,
        key_values)`` with combos in sorted composite order."""
        from bqueryd_tpu import ops

        n_cols = len(dag.group_keys)
        n_shards = len(derived)
        masks = [d[0] for d in derived]
        shard_codes = [
            [np.asarray(d[1][ci][0]) for d in derived]
            for ci in range(n_cols)
        ]
        shard_values = [
            [np.asarray(d[1][ci][1]) for d in derived]
            for ci in range(n_cols)
        ]
        cards, global_values = [], []
        pos_maps = [[] for _ in range(n_cols)]
        for ci in range(n_cols):
            gvals = np.unique(np.concatenate(shard_values[ci]))
            # null VALUES (NaN/NaT) strip from the global dictionary: the
            # rows referencing them already carry poisoned codes (-1) —
            # same rule as the classic alignment
            if gvals.dtype.kind == "f":
                gvals = gvals[~np.isnan(gvals)]
            elif gvals.dtype.kind == "M":
                gvals = gvals[~np.isnat(gvals)]
            cards.append(max(len(gvals), 1))
            global_values.append(gvals)
            for si in range(n_shards):
                pos_maps[ci].append(
                    np.searchsorted(gvals, shard_values[ci][si])
                )

        def mapped(si, ci):
            codes = shard_codes[ci][si]
            pos = pos_maps[ci][si]
            if len(pos) == 0:
                return np.full(len(codes), np.int64(-1))
            return np.where(
                codes >= 0, pos[np.clip(codes, 0, None)], np.int64(-1)
            )

        def fold(si, dense_si):
            m = masks[si]
            if m is None:
                return dense_si
            return np.where(m, dense_si, np.int64(-1))

        key_values = dict(zip(dag.group_keys, global_values))
        if n_cols == 1:
            dense = self._map_shards(
                lambda si: fold(si, mapped(si, 0).astype(np.int64)),
                range(n_shards),
            )
            combo_cols = np.arange(
                len(global_values[0]), dtype=np.int64
            )[:, None]
            return dense, combo_cols, key_values

        if ops.total_cardinality(cards) >= ops.MAX_COMPOSITE:
            raise ops.CompositeOverflow(
                "composite group-key space "
                f"{'x'.join(str(int(c)) for c in cards)} exceeds int64"
            )

        def shard_composites(si):
            packed = np.asarray(
                ops.pack_codes(
                    [mapped(si, ci) for ci in range(n_cols)], cards
                )
            )
            m = masks[si]
            if m is not None:
                packed = np.where(m, packed, np.int64(-1))
            inv, uniq = ops.factorize(packed)
            return np.asarray(inv), np.asarray(uniq, dtype=np.int64)

        composites = self._map_shards(shard_composites, range(n_shards))
        observed = [u[u >= 0] for _inv, u in composites]
        observed = [o for o in observed if len(o)]
        combos = (
            np.unique(np.concatenate(observed))
            if observed
            else np.empty(0, dtype=np.int64)
        )
        dense = []
        for inv, uniq in composites:
            lut = np.searchsorted(
                combos, np.clip(uniq, 0, None)
            ).astype(np.int64)
            lut[uniq < 0] = -1
            dense.append(lut[inv])
        combo_cols = (
            np.stack(ops.unpack_codes(combos, cards), axis=1)
            if len(combos)
            else np.empty((0, n_cols), dtype=np.int64)
        )
        return dense, combo_cols, key_values


class DagFastPathUnsupported(Exception):
    """The mesh fast path cannot serve this extended-DAG dispatch (shape,
    dtype, budget, or the device-merge kill switch).  NOT an error the
    client ever sees: the worker catches it and falls back to the PR-13
    per-shard operator pipeline + host value-keyed merge, which serves
    every DAG shape."""


def sketch_grid_cells_limit():
    """Cell budget (padded groups x bucket width) above which a quantile
    sketch keeps the per-shard host path: the device merge materializes one
    dense int64 ``[groups, width]`` grid per sketch agg per device, and
    past this budget (default 2^23 cells = 64 MiB of HBM + ICI per agg)
    the flat host merge it replaces is the better economics.  Tune with
    BQUERYD_TPU_SKETCH_GRID_CELLS."""
    return int(
        os.environ.get("BQUERYD_TPU_SKETCH_GRID_CELLS", str(1 << 23))
    )


def _pack_leaf(leaf):
    """Bitcast any result leaf to its native bytes (lossless, no widening —
    the packed buffer carries exactly the leaves' own byte sizes)."""
    import jax.numpy as jnp
    from jax import lax

    if leaf.dtype.itemsize == 1:
        return leaf.astype(jnp.uint8).ravel() if leaf.dtype != jnp.uint8 \
            else leaf.ravel()
    # bitcast to a SMALLER dtype appends a trailing byte axis
    return lax.bitcast_convert_type(leaf, jnp.uint8).ravel()


def _unpack_host(flat, spec):
    """Invert :func:`_pack_leaf` on the fetched numpy uint8 byte buffer."""
    leaves = []
    off = 0
    for dtype, shape in spec:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dtype.itemsize
        seg = flat[off:off + nbytes]
        off += nbytes
        # copy() realigns the slice so the view is valid at any offset
        leaves.append(seg.copy().view(dtype).reshape(shape))
    return leaves


def packed_fetch_enabled():
    """Fetch the merged result as ONE device buffer (default on): the merged
    pytree has one leaf per aggregation partial, and ``jax.device_get``
    copies leaves buffer-by-buffer — on a remote/tunneled backend each copy
    is a transport round-trip, turning a 2 ms kernel into tens of ms of
    fetch latency.  Packing bitcasts every leaf to its native bytes and
    concatenates INSIDE the compiled mesh program, so dispatch+fetch is
    exactly one program and one buffer of the leaves' own total size."""
    return os.environ.get("BQUERYD_TPU_PACKED_FETCH", "1") == "1"


def _route_key():
    """The env-derived knobs that steer the kernel route inside
    ``ops.partial_tables`` AT TRACE TIME.  They must be part of the
    ``_mesh_program`` cache key: the dispatcher reads them per call, but a
    cached program never re-runs the dispatcher — without the key a
    runtime flag flip (the bench's pallas variants, a live worker being
    re-tuned) would silently keep serving the previously-traced route."""
    from bqueryd_tpu.ops import groupby as gb
    from bqueryd_tpu.ops import pallas_groupby as pg

    return (
        pg.pallas_enabled(),
        os.environ.get("BQUERYD_TPU_FORCE_MATMUL") == "1",
        gb.matmul_groups_limit(),
        gb._matmul_cells_limit(),
        pg.hicard_groups_limit(),
    )


def _shard_map(fn, mesh, in_specs, out_specs, check):
    """Version-portable shard_map: ``jax.shard_map`` (its home since jax
    0.6, ``check_vma=``) with a fallback to the pre-0.6
    ``jax.experimental.shard_map`` location (``check_rep=``)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    return legacy_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


@functools.lru_cache(maxsize=64)
def _mesh_program(mesh, axis, agg_ops, n_groups, in_dtypes, in_width, pack,
                  null_sentinels=None, route=None, strategy=None,
                  measure_index=None, merge_mode="psum"):
    """Build + cache the jitted shard_map program for one query shape.

    The key carries everything that can change the traced program — measure
    wire dtypes AND the per-device row width (``in_width``): the packed
    output's host-side unpack spec is captured at trace time, and both leaf
    dtypes (via the measure dtypes) and the kernel route (via the row count,
    ``_matmul_cells_limit``, and the ``route`` flag tuple) feed it, so one
    cache entry must map to exactly one trace.  ``measure_index`` (static)
    maps each aggregation to its slot in the DEDUPLICATED measure blocks:
    ``sum+count+mean`` of one column ride one uploaded block and one
    program argument instead of three.

    ``merge_mode`` (static, devicemerge.MODE_*) picks the cross-device
    merge traced into the program:

    * ``device`` — bucketized partials reduce-scatter over the mesh axis so
      each device owns a contiguous key span; outputs are span-sized and
      the D2H fetch is the final table only (the default);
    * ``psum``   — the all-reduce + replicated-output contract (multi-host
      pods, where a span-sharded output is not host-fetchable);
    * ``host``   — NO collective: every device's full partial table comes
      back (leading device axis host-side) for ``hostmerge.merge_payloads``
      — the kill-switch baseline."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bqueryd_tpu import ops
    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    spec = {}  # populated at trace time: treedef + (dtype, shape) per leaf

    def block_fn(codes_blk, *measure_blks):
        per_block = tuple(m[0] for m in measure_blks)
        per_agg = (
            per_block
            if measure_index is None
            else tuple(per_block[i] for i in measure_index)
        )
        partials = ops.partial_tables(
            codes_blk[0],
            per_agg,
            agg_ops,
            n_groups,
            null_sentinels=null_sentinels,
            strategy=strategy,
        )
        if merge_mode == devicemerge.MODE_DEVICE:
            # key-span ownership: pad onto the bucket layout (behind the
            # kernel guards — this is the dispatched partials' OUTPUT) and
            # reduce-scatter so this device keeps only its span's totals
            bucketized, span = ops.bucketize_partials(
                partials, n_groups, n_dev
            )
            merged = devicemerge.scatter_merge_partials(
                bucketized, axis, n_dev, span
            )
        elif merge_mode == devicemerge.MODE_HOST:
            # kill switch: no collective — the per-device partial tables
            # leave HBM whole and merge on the worker host
            merged = partials
        else:
            merged = ops.psum_partials(partials, axis)
        if not pack:
            return merged
        leaves, treedef = jax.tree_util.tree_flatten(merged)
        spec["treedef"] = treedef
        spec["leaves"] = tuple(
            (np.dtype(leaf.dtype), tuple(leaf.shape)) for leaf in leaves
        )
        import jax.numpy as jnp

        return jnp.concatenate([_pack_leaf(leaf).ravel() for leaf in leaves])

    # pallas_call outputs carry no varying-mesh-axes metadata, so the vma/rep
    # check would reject the kernel path; the psum in block_fn is what makes
    # the out_specs=P() replication true by construction.  Span-owned
    # (device) and per-device (host) outputs are axis-sharded instead: the
    # global result concatenates every device's slice in device order.
    out_spec = P() if merge_mode == devicemerge.MODE_PSUM else P(axis)
    fn = _shard_map(
        block_fn,
        mesh=mesh,
        in_specs=tuple([P(axis, None)] * len(in_dtypes)),
        out_specs=out_spec,
        check=False,
    )
    # compile/call accounting (obs.profile): every mesh-program call lands
    # in the jit-cache hit/miss counters, compiles in the compile-seconds
    # histogram + per-shape program registry with cost_analysis FLOPs
    from bqueryd_tpu.obs import profile as obsprofile

    return obsprofile.instrument("executor.mesh_program", jax.jit(fn)), spec


@functools.lru_cache(maxsize=32)
def _mesh_bundle_program(mesh, axis, n_groups, in_dtypes, in_width, pack,
                         member_specs, null_sentinels, route=None,
                         strategy=None, merge_mode="psum", n_masks=0):
    """Build + cache the jitted shared-scan BUNDLE program for one bundle
    shape.  The key carries everything that changes the trace: the static
    per-member spec tuple (mask slot + (measure slot, op) pairs), the
    stacked-mask count, the union measure dtypes, and the same route/merge
    knobs as :func:`_mesh_program`.  The program emits one merged partial
    table PER MEMBER (a tuple pytree): each member's emission is the same
    :func:`ops.partial_tables` dispatch its solo program runs, under its
    own stacked-mask row, and each member's cross-device merge is the same
    collective the solo program traces — the whole bundle reduces in one
    compiled dispatch."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bqueryd_tpu import ops
    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    spec = {}

    def merge_member(partials):
        if merge_mode == devicemerge.MODE_DEVICE:
            bucketized, span = ops.bucketize_partials(
                partials, n_groups, n_dev
            )
            return devicemerge.scatter_merge_partials(
                bucketized, axis, n_dev, span
            )
        if merge_mode == devicemerge.MODE_HOST:
            return partials
        return ops.psum_partials(partials, axis)

    def body(codes_blk, masks_blk, measure_blks):
        codes = codes_blk[0]
        masks = None if masks_blk is None else masks_blk[:, 0, :]
        per_col = tuple(m[0] for m in measure_blks)
        members = ops.bundle_partial_tables(
            codes, masks, per_col, member_specs, n_groups,
            null_sentinels=null_sentinels, strategy=strategy,
        )
        merged = tuple(merge_member(partials) for partials in members)
        if not pack:
            return merged
        leaves, treedef = jax.tree_util.tree_flatten(merged)
        spec["treedef"] = treedef
        spec["leaves"] = tuple(
            (np.dtype(leaf.dtype), tuple(leaf.shape)) for leaf in leaves
        )
        import jax.numpy as jnp

        return jnp.concatenate([_pack_leaf(leaf).ravel() for leaf in leaves])

    n_measures = len(in_dtypes) - 1 - (1 if n_masks else 0)
    if n_masks:
        def block_fn(codes_blk, masks_blk, *measure_blks):
            return body(codes_blk, masks_blk, measure_blks)

        in_specs = (P(axis, None), P(None, axis, None)) + tuple(
            [P(axis, None)] * n_measures
        )
    else:
        def block_fn(codes_blk, *measure_blks):
            return body(codes_blk, None, measure_blks)

        in_specs = tuple([P(axis, None)] * (1 + n_measures))
    out_spec = P() if merge_mode == devicemerge.MODE_PSUM else P(axis)
    fn = _shard_map(
        block_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_spec,
        check=False,
    )
    from bqueryd_tpu.obs import profile as obsprofile

    return obsprofile.instrument(
        "executor.mesh_bundle_program", jax.jit(fn)
    ), spec


def _fetch_merged(run, call, merge_mode, n_dev, finish, timer, latch, what):
    """The ONE packed-fetch scaffold shared by the three mesh fetch paths
    (:func:`_mesh_partials`, :func:`_mesh_bundle_partials`,
    :func:`_mesh_dag_partials`): run the packed program and fetch one byte
    buffer, falling back to the per-leaf ``device_get`` of the unpacked
    program when the packed one fails.

    ``latch`` is the per-path policy after a DETERMINISTIC packed failure:
    ``True`` (the solo and DAG paths) counts consecutive transient-classed
    failures against ``_PACKED_TRANSIENT_LIMIT`` (a deterministic XLA bug
    misclassed INTERNAL cannot dodge the latch forever) and commits
    ``_packed_fetch_broken`` once per-leaf succeeds — per-leaf working
    where packed failed is the actual evidence against packing; ``False``
    (bundles) propagates transients unconditionally and never latches, the
    solo path owning the packed-broken diagnosis.  ``run(pack_flag)``
    returns ``(program, spec)``; ``call(program)`` invokes it with the
    caller's argument tuple; ``finish(merged, fetched_bytes)`` is the
    caller's layout normalization + merge-byte accounting."""
    global _packed_fetch_broken, _packed_transient_count
    import jax

    from bqueryd_tpu.parallel import devicemerge

    pack = packed_fetch_enabled() and not _packed_fetch_broken
    latch_pending = False
    if pack:
        try:
            program, spec = run(True)
            with _collective_guard():
                out = call(program)
                _block_ready(out)
                with _fetch_phase(timer):
                    flat = np.asarray(jax.device_get(out))
        except Exception as exc:
            transient = isinstance(
                exc, jax.errors.JaxRuntimeError
            ) and _transient_status(exc)
            if transient and (
                not latch
                or _packed_transient_count + 1 < _PACKED_TRANSIENT_LIMIT
            ):
                # transient infrastructure fault (flaky remote-compile
                # HTTP 500s as INTERNAL, dropped links as UNAVAILABLE):
                # NOT evidence against packing — propagate so the caller's
                # retry / the worker's degrade+failover machinery decides
                # instead of re-executing the whole program per-leaf on
                # the same flaky backend
                if latch:
                    _packed_transient_count += 1
                raise
            if latch:
                # deterministic packed failure: per-leaf retry below, and
                # the process latches off packed fetch once it succeeds
                latch_pending = True
            import logging

            logging.getLogger("bqueryd_tpu").exception(
                "packed %s fetch failed; retrying via per-leaf "
                "device_get", what,
            )
        else:
            if latch:
                _packed_transient_count = 0
            if merge_mode == devicemerge.MODE_PSUM:
                merged = jax.tree_util.tree_unflatten(
                    spec["treedef"], _unpack_host(flat, spec["leaves"])
                )
            else:
                merged = _assemble_sharded(flat, spec, n_dev, merge_mode)
            return finish(merged, flat.nbytes)
    program, _spec = run(False)
    with _collective_guard():
        out = call(program)
        _block_ready(out)
        with _fetch_phase(timer):
            result = jax.device_get(out)
    if latch_pending:
        _packed_fetch_broken = True
        _packed_transient_count = 0
        import logging

        logging.getLogger("bqueryd_tpu").warning(
            "packed fetch unavailable on this backend (per-leaf fetch "
            "succeeded where the packed %s program failed); using "
            "per-leaf device_get for the process lifetime", what,
        )
    fetched = sum(
        np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(result)
    )
    return finish(result, fetched)


def _mesh_bundle_partials(mesh, axis, n_groups, codes_d, masks_d, measures_d,
                          member_specs, null_sentinels, strategy=None,
                          merge_mode="psum", timer=None):
    """Run the bundle program and return the per-member merged partials
    tuple ON HOST (numpy leaves) — one packed fetch for the whole bundle
    when packing is enabled, with a per-query fallback to per-leaf
    ``device_get`` (no process latch: the single-query path owns the
    packed-broken diagnosis).  Shapes follow :func:`_mesh_partials`:
    ``device``/``psum`` leaves are ``[n_groups]`` per member, ``host``
    leaves ``[n_dev, n_groups]`` for the hostmerge fallback."""
    import jax

    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    in_dtypes = (
        (str(codes_d.dtype),)
        + ((str(masks_d.dtype),) if masks_d is not None else ())
        + tuple(str(m.dtype) for m in measures_d)
    )
    n_masks = 0 if masks_d is None else int(masks_d.shape[0])
    args = (
        (codes_d,)
        + ((masks_d,) if masks_d is not None else ())
        + tuple(measures_d)
    )

    def run(pack_flag):
        return _mesh_bundle_program(
            mesh, axis, int(n_groups), in_dtypes, int(codes_d.shape[1]),
            pack_flag, member_specs, null_sentinels,
            route=_route_key(), strategy=strategy, merge_mode=merge_mode,
            n_masks=n_masks,
        )

    def finish(merged, fetched):
        if merge_mode == devicemerge.MODE_DEVICE:
            merged = jax.tree_util.tree_map(
                lambda a: a[: int(n_groups)], merged
            )
        elif merge_mode == devicemerge.MODE_HOST:
            merged = jax.tree_util.tree_map(
                lambda a: np.asarray(a).reshape(n_dev, int(n_groups)),
                merged,
            )
        _record_merge_bytes(
            merge_mode, fetched, n_dev, int(n_groups), merged
        )
        return merged

    return _fetch_merged(
        run, lambda program: program(*args), merge_mode, n_dev, finish,
        timer, latch=False, what="bundle",
    )


@functools.lru_cache(maxsize=32)
def _mesh_dag_program(mesh, axis, n_groups, in_dtypes, in_width, pack,
                      classic_spec, topk_spec, sketch_spec, route=None,
                      merge_mode="device"):
    """Build + cache the jitted mesh program of one extended-DAG shape:
    every aggregation's partial state emitted AND cross-device merged in
    one compiled dispatch, so the only D2H is the final merged table.

    Static specs (all in the lru key, like every knob that changes the
    trace):

    * ``classic_spec`` — ``((measure_slot, op, sentinel), ...)``: ONE
      :func:`ops.partial_tables` dispatch (every kernel guard / strategy
      route unchanged) whose bucketized output reduce-scatters span-owned
      (the PR-7 ``devicemerge.scatter_merge_partials`` machinery);
    * ``topk_spec`` — ``((slot, k, largest, drop_nan, sentinel,
      float_neg), ...)``: dense ``[padded_groups, k]`` emission via
      :func:`ops.relops.topk_dense_emit` — the SAME routed dispatcher
      (matrix-argmax / k-pass / lexsort, all value-multiset identical)
      the jitted per-shard kernel runs — merged by all-gather +
      on-device re-select (:func:`devicemerge.allgather_topk_merge`);
    * ``sketch_spec`` — ``((slot, log_gamma, imin, imax, kmin,
      width), ...)``: dense bucket-count grids
      (:func:`ops.relops.sketch_grid_block`) merged by reduce-scatter
      ADDITION (:func:`devicemerge.scatter_merge_grid`) — the mergeable-
      histogram property, now on the ICI instead of the host.

    ``merge_mode`` is ``device`` or ``psum`` only: under the
    ``BQUERYD_TPU_DEVICE_MERGE=0`` / ``BQUERYD_TPU_DAG_BATCH=0`` kill
    switches the controller stops batching DAG dispatches, so no batched
    program ever runs host-merged."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bqueryd_tpu import ops
    from bqueryd_tpu.ops import relops
    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    span, padded = devicemerge.bucket_span(n_groups, n_dev)
    device_mode = merge_mode == devicemerge.MODE_DEVICE
    g_emit = padded if device_mode else n_groups
    span_arg = span if device_mode else None
    spec = {}

    def block_fn(codes_blk, *measure_blks):
        codes = codes_blk[0]
        per_slot = tuple(m[0] for m in measure_blks)
        partials = ops.partial_tables(
            codes,
            tuple(per_slot[s] for s, _op, _st in classic_spec),
            tuple(op for _s, op, _st in classic_spec),
            n_groups,
            null_sentinels=tuple(st for _s, _op, st in classic_spec),
        )
        if device_mode:
            bucketized, sp = ops.bucketize_partials(
                partials, n_groups, n_dev
            )
            classic = devicemerge.scatter_merge_partials(
                bucketized, axis, n_dev, sp
            )
        else:
            classic = ops.psum_partials(partials, axis)
        topk = []
        for slot, k, largest, drop_nan, sentinel, float_neg in topk_spec:
            dense, cnt = relops.topk_dense_emit(
                codes, per_slot[slot], None, k, largest, g_emit,
                drop_nan, sentinel, float_neg,
            )
            topk.append(
                devicemerge.allgather_topk_merge(
                    dense, cnt, axis, span_arg, largest, float_neg
                )
            )
        sketches = []
        for slot, lg, imin, imax, kmin, width in sketch_spec:
            grid = relops.sketch_grid_block(
                codes, per_slot[slot], g_emit, lg, imin, imax, kmin,
                width,
            )
            sketches.append(
                devicemerge.scatter_merge_grid(grid, axis, span_arg)
            )
        merged = {
            "classic": classic,
            "topk": tuple(topk),
            "sketch": tuple(sketches),
        }
        if not pack:
            return merged
        leaves, treedef = jax.tree_util.tree_flatten(merged)
        spec["treedef"] = treedef
        spec["leaves"] = tuple(
            (np.dtype(leaf.dtype), tuple(leaf.shape)) for leaf in leaves
        )
        import jax.numpy as jnp

        return jnp.concatenate([_pack_leaf(leaf).ravel() for leaf in leaves])

    out_spec = P(axis) if device_mode else P()
    fn = _shard_map(
        block_fn,
        mesh=mesh,
        in_specs=tuple([P(axis, None)] * len(in_dtypes)),
        out_specs=out_spec,
        check=False,
    )
    from bqueryd_tpu.obs import profile as obsprofile

    return obsprofile.instrument(
        "executor.mesh_dag_program", jax.jit(fn)
    ), spec


def _mesh_dag_partials(mesh, axis, n_groups, codes_d, measures_d,
                       classic_spec, topk_spec, sketch_spec,
                       merge_mode="device", timer=None):
    """Run the DAG program and return the merged pytree ON HOST (numpy
    leaves, group axis leading, length ``n_groups`` = the program bucket):
    one packed fetch for the whole query when packing is enabled, with the
    per-leaf ``device_get`` fallback (same transient-vs-deterministic
    contract as the bundle fetch — the worker's degrade path owns
    failures).  Every leaf's group axis is fully merged: classic tables
    ``[n_groups]``, top-k ``([n_groups, k], [n_groups])`` pairs, sketch
    grids ``[n_groups, width]``."""
    import jax

    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    in_dtypes = (str(codes_d.dtype),) + tuple(
        str(m.dtype) for m in measures_d
    )
    args = (codes_d,) + tuple(measures_d)

    def run(pack_flag):
        return _mesh_dag_program(
            mesh, axis, int(n_groups), in_dtypes, int(codes_d.shape[1]),
            pack_flag, classic_spec, topk_spec, sketch_spec,
            route=_route_key(), merge_mode=merge_mode,
        )

    def finish(merged, fetched):
        if merge_mode == devicemerge.MODE_DEVICE:
            # device-mode leaves concatenate spans to the PADDED group
            # axis; slice back to the program bucket (the caller slices
            # the bucket down to the real group count)
            merged = jax.tree_util.tree_map(
                lambda a: a[: int(n_groups)], merged
            )
        # host-gather counterfactual: every device's full merged-size
        # partial state crossing to the host (the =0 economics)
        counterfactual = n_dev * sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(merged)
        )
        devicemerge.stats().record(
            merge_mode, int(fetched), saved=counterfactual - int(fetched)
        )
        return merged

    return _fetch_merged(
        run, lambda program: program(*args), merge_mode, n_dev, finish,
        timer, latch=True, what="DAG",
    )


#: set when the packed program failed to build/run on this backend (seen
#: nowhere yet; guards against a backend rejecting the byte bitcasts) — all
#: later queries go straight to the per-leaf fetch
_packed_fetch_broken = False

#: consecutive transiently-classed packed-fetch failures; once it reaches
#: _PACKED_TRANSIENT_LIMIT the "transient" diagnosis is abandoned and the
#: per-leaf latch sets anyway (an XLA lowering bug classed INTERNAL would
#: otherwise dodge the latch forever, costing every query two failed packed
#: dispatches and an engine degrade)
_packed_transient_count = 0
_PACKED_TRANSIENT_LIMIT = 3

#: gRPC-style status prefixes a flaky tunneled backend surfaces for
#: infrastructure (retry-worthy) failures, as opposed to deterministic
#: program rejections (INVALID_ARGUMENT, UNIMPLEMENTED, FAILED_PRECONDITION)
#: or deterministic resource exhaustion.  Observed on hardware: remote
#: compile-helper crashes arrive as "INTERNAL: ... HTTP 500"
#: (TPU_VALIDATE_r5_prefix.json case7/case13).
_TRANSIENT_STATUSES = (
    "INTERNAL", "UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED", "UNKNOWN"
)


def _transient_status(exc):
    """Whether a JaxRuntimeError looks like transient infrastructure failure
    (worth one in-place retry) rather than a deterministic rejection."""
    msg = str(exc)
    return any(s in msg for s in _TRANSIENT_STATUSES)


def _effective_mesh_strategy(strategy, agg_ops, n_groups, measures_d, width):
    """Canonicalize a planner hint for the mesh-program cache key: a hint
    that cannot change the traced route must key (and trace) exactly like
    ``auto``, or an identical program would be compiled twice — a "matmul"
    hint is advisory by definition (the dispatcher decides identically under
    auto), a "scatter" hint is a no-op whenever auto would scatter anyway
    (always on CPU backends, and past the matmul group ceiling), and the
    calibration-backed "matmul!" normalizes to auto both when auto already
    takes the MXU route (identical program) and when the kernel guards
    would demote it (backend/value guards stand under promotion)."""
    if strategy in (None, "auto", "matmul"):
        return None
    from bqueryd_tpu.ops import groupby as gb

    mm = gb._matmul_profitable(
        measures_d, agg_ops, width, int(n_groups)
    ) or gb._hicard_matmul_profitable(
        measures_d, agg_ops, width, int(n_groups)
    )
    if strategy == "matmul!":
        if mm or not gb.matmul_route_allowed(width, int(n_groups)):
            return None
        return strategy
    if strategy == "scatter" and not mm:
        return None
    if strategy == "sort" and not mm:
        # auto's scatter entry already sorts past the blocks x groups budget
        blocks = -(-width // gb._SUM_BLOCK)
        if blocks * int(n_groups) > gb._MAX_BLOCK_SEGMENTS:
            return None
    return strategy


#: serializes mesh-program execution on CPU backends: XLA:CPU cross-module
#: collectives rendezvous by participant count process-globally, so two
#: concurrent psum programs from different threads (an in-process multi-
#: worker test cluster) interleave their AllReduce participants and
#: deadlock.  Production topology is one process per device set, where the
#: lock is uncontended; TPU backends skip it entirely.
_CPU_COLLECTIVE_LOCK = threading.Lock()


def _collective_guard():
    import contextlib

    import jax

    if jax.default_backend() == "cpu":
        return _CPU_COLLECTIVE_LOCK
    return contextlib.nullcontext()


def _assemble_sharded(flat, spec, n_dev, merge_mode):
    """Host-side reassembly of a packed axis-sharded fetch: the global byte
    buffer concatenates every device's packed slice in device order.  Device
    mode concatenates the span slices back into the (padded) merged table;
    host mode stacks the full per-device tables onto a leading device axis.
    Layout normalization (pad-tail slice / device-axis reshape) is the
    caller's ``finish`` — the contract lives there for BOTH fetch paths."""
    import jax

    per_dev = [
        _unpack_host(chunk, spec["leaves"])
        for chunk in flat.reshape(n_dev, -1)
    ]
    from bqueryd_tpu.parallel import devicemerge

    if merge_mode == devicemerge.MODE_DEVICE:
        leaves = [
            np.concatenate([dev[i] for dev in per_dev])
            for i in range(len(spec["leaves"]))
        ]
    else:
        leaves = [
            np.stack([dev[i] for dev in per_dev])
            for i in range(len(spec["leaves"]))
        ]
    return jax.tree_util.tree_unflatten(spec["treedef"], leaves)


def _record_merge_bytes(merge_mode, fetched, n_dev, n_groups, merged):
    """Account the D2H movement of one merged fetch: ``fetched`` actual
    bytes vs the host-gather counterfactual — every device's full partial
    table (``n_dev x n_groups`` rows per leaf) crossing to the host."""
    from bqueryd_tpu.parallel import devicemerge

    leaves = []
    import jax

    for leaf in jax.tree_util.tree_leaves(merged):
        leaves.append(np.dtype(np.asarray(leaf).dtype).itemsize)
    counterfactual = n_dev * n_groups * sum(leaves)
    devicemerge.stats().record(
        merge_mode, fetched, saved=counterfactual - int(fetched)
    )


@contextlib.contextmanager
def _fetch_phase(timer):
    """The D2H fetch timed as its own phase ("fetch" -> span "d2h_fetch"):
    the program output is blocked-until-ready first, so what this phase
    measures is the transfer itself, not the async kernel dispatch it used
    to hide inside the "aggregate" wall.  The fetch runs serially nested
    inside the open "aggregate" phase, so its wall is DEBITED from
    aggregate — one second of D2H bills the fetch phase once, not the
    kernel histogram too."""
    if timer is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        with timer.phase("fetch"):
            yield
    finally:
        timer.debit("aggregate", time.perf_counter() - t0)


def _block_ready(out):
    """``jax.block_until_ready`` with a pytree-walking fallback for older
    jaxlibs that predate the top-level helper."""
    import jax

    block = getattr(jax, "block_until_ready", None)
    if block is not None:
        return block(out)
    return jax.tree_util.tree_map(
        lambda a: a.block_until_ready()
        if hasattr(a, "block_until_ready") else a,
        out,
    )


def _mesh_partials(mesh, axis, agg_ops, n_groups, codes_d, measures_d,
                   null_sentinels=None, strategy=None, measure_index=None,
                   merge_mode="psum", timer=None):
    """Run the mesh program and return the merged partials pytree ON HOST
    (numpy leaves) — fetching one packed buffer when packing is enabled.
    ``measures_d`` holds one device block per DISTINCT measure column;
    ``measure_index`` maps each agg onto those slots (None = identity).

    ``merge_mode`` shapes the result: ``device``/``psum`` return the merged
    table (leaves ``[n_groups]``); ``host`` returns the UNMERGED per-device
    partials (leaves ``[n_dev, n_groups]``) for the hostmerge fallback.

    ``timer``: optional PhaseTimer; the device→host fetch is carved into
    its own "fetch" phase so attribution can split kernel wall from D2H."""
    import jax

    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    per_agg_measures = (
        measures_d
        if measure_index is None
        else tuple(measures_d[i] for i in measure_index)
    )
    strategy = _effective_mesh_strategy(
        strategy, tuple(agg_ops), n_groups, per_agg_measures,
        int(codes_d.shape[1]),
    )
    in_dtypes = (str(codes_d.dtype),) + tuple(str(m.dtype) for m in measures_d)

    def run(pack_flag):
        return _mesh_program(
            mesh, axis, tuple(agg_ops), int(n_groups), in_dtypes,
            int(codes_d.shape[1]), pack_flag,
            null_sentinels,  # part of the lru key: it changes the trace
            route=_route_key(),  # ditto: the flags steer the traced route
            strategy=strategy,  # planner hint: a different traced route too
            measure_index=measure_index,  # agg -> deduped block slot
            merge_mode=merge_mode,  # the traced cross-device merge differs
        )

    def finish(merged, fetched):
        if merge_mode == devicemerge.MODE_DEVICE:
            # axis-sharded span outputs concatenate to the padded table;
            # the bucket pad tail holds no real group
            merged = jax.tree_util.tree_map(
                lambda a: a[: int(n_groups)], merged
            )
        elif merge_mode == devicemerge.MODE_HOST:
            merged = jax.tree_util.tree_map(
                lambda a: np.asarray(a).reshape(n_dev, int(n_groups)),
                merged,
            )
        _record_merge_bytes(
            merge_mode, fetched, n_dev, int(n_groups), merged
        )
        return merged

    return _fetch_merged(
        run, lambda program: program(codes_d, *measures_d), merge_mode,
        n_dev, finish, timer, latch=True, what="query",
    )
