"""Mesh executor: one query over many shards, merged on-device with psum.

This is the TPU-native replacement for the reference's shard fan-out + merge
pipeline (per-shard tar results at reference bqueryd/worker.py:335-346,
controller tar-of-tars at reference bqueryd/controller.py:186-211, client
re-groupby at reference bqueryd/rpc.py:150-173).  Where the reference ships N
serialized result tables over TCP and re-aggregates them twice, here the N
shards are laid out over a 1-D ``jax.sharding.Mesh`` and the merge is a
``jax.lax.psum`` of index-aligned partial tables riding the ICI — one compiled
program, zero host serialization between partial and merged result.

What makes the psum legal is host-side key alignment: every shard's group
codes are remapped into one *global* composite-key space before the kernel
runs (SURVEY.md §7.3 "Merge alignment"), so row ``g`` of every device's
partial table refers to the same group.  The alignment is cheap (NumPy
searchsorted over per-shard dictionaries, not data rows) and happens once per
query.

Layout: shards are packed greedily onto the mesh's devices (longest shard to
least-loaded device), per-device rows concatenated and right-padded with
code ``-1`` (the null code — padding therefore contributes to no group, see
``ops.partial_tables``), giving a static ``[n_devices, rows_per_device]``
shape XLA can tile.

Falls back to nothing: callers (worker, __graft_entry__, bench) route
non-mergeable aggregations (count_distinct family) and the aggregate=False
raw-rows path through the per-shard ``QueryEngine`` + host merge instead —
those results carry value *sets*, which a fixed-width psum cannot merge.
"""

import functools

import numpy as np

from bqueryd_tpu.models.query import GroupByQuery, ResultPayload


def make_mesh(n_devices=None, axis_name="shards"):
    """A 1-D mesh over the first ``n_devices`` local JAX devices."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


class MeshQueryExecutor:
    """Executes a :class:`GroupByQuery` over a list of shard tables on a
    device mesh, merging per-shard partials with ``ops.psum_partials``.

    Handles the mergeable aggregation set (``ops.MERGEABLE_OPS``); the worker
    falls back to per-shard execution for distinct-count ops and raw rows.
    """

    def __init__(self, mesh=None, axis_name="shards", timer=None):
        self._mesh = mesh
        self.axis_name = axis_name
        self.timer = timer

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh(axis_name=self.axis_name)
        return self._mesh

    def _phase(self, name):
        import contextlib

        if self.timer is None:
            return contextlib.nullcontext()
        return self.timer.phase(name)

    @staticmethod
    def supports(query: GroupByQuery):
        from bqueryd_tpu import ops

        return query.aggregate and all(
            op in ops.MERGEABLE_OPS for op in query.ops
        )

    # -- key alignment (host-side, dictionary-sized work only) --------------
    def _global_key_space(self, tables, query, engine):
        """Remap every shard's per-column key codes into one global space.

        Returns ``(per_shard_packed, combos, cards, key_values)`` where
        ``combos`` is the sorted global composite-key array, ``cards`` the
        global per-column cardinalities, and ``key_values[col]`` the global
        per-column key-value arrays (indexable by unpacked codes).
        """
        n_cols = len(query.groupby_cols)
        shard_codes = [[] for _ in range(n_cols)]   # [col][shard] -> codes
        shard_values = [[] for _ in range(n_cols)]  # [col][shard] -> uniques
        for table in tables:
            for ci, col in enumerate(query.groupby_cols):
                codes, values = engine._key_codes(table, col)
                shard_codes[ci].append(np.asarray(codes))
                shard_values[ci].append(np.asarray(values))

        cards = []
        global_values = []
        global_codes = [[] for _ in range(len(tables))]  # [shard][col]
        for ci in range(n_cols):
            allv = np.concatenate(shard_values[ci])
            gvals = np.unique(allv)
            cards.append(max(len(gvals), 1))
            global_values.append(gvals)
            for si in range(len(tables)):
                # local dictionary -> global position, gathered through the
                # local codes; null codes (<0) stay null
                pos = np.searchsorted(gvals, shard_values[ci][si])
                codes = shard_codes[ci][si]
                mapped = np.where(
                    codes >= 0, pos[np.clip(codes, 0, None)], np.int64(-1)
                )
                global_codes[si].append(mapped)

        from bqueryd_tpu import ops

        per_shard_packed = []
        for si in range(len(tables)):
            if n_cols == 1:
                packed = global_codes[si][0].astype(np.int64)
            else:
                packed = ops.pack_codes(global_codes[si], cards)
            per_shard_packed.append(packed)

        observed = [p[p >= 0] for p in per_shard_packed]
        observed = [o for o in observed if len(o)]
        combos = (
            np.unique(np.concatenate(observed))
            if observed
            else np.empty(0, dtype=np.int64)
        )
        # dense codes: position of each packed composite in the sorted combos
        dense = []
        for packed in per_shard_packed:
            pos = np.searchsorted(combos, np.clip(packed, 0, None))
            dense.append(np.where(packed >= 0, pos, np.int64(-1)))
        key_values = dict(zip(query.groupby_cols, global_values))
        return dense, combos, cards, key_values

    # -- device layout ------------------------------------------------------
    def _bucketize(self, arrays_per_shard, n_devices, pad_values):
        """Greedy-pack shards onto devices; concat + right-pad each bucket.

        ``arrays_per_shard``: list (per shard) of tuples of 1-D arrays, all
        the same length within a shard.  Returns a tuple of stacked
        ``[n_devices, L]`` arrays.
        """
        order = sorted(
            range(len(arrays_per_shard)),
            key=lambda i: -len(arrays_per_shard[i][0]),
        )
        buckets = [[] for _ in range(n_devices)]
        loads = [0] * n_devices
        for si in order:
            d = loads.index(min(loads))
            buckets[d].append(si)
            loads[d] += len(arrays_per_shard[si][0])
        width = max(max(loads), 1)

        n_arrays = len(arrays_per_shard[0])
        stacked = []
        for ai in range(n_arrays):
            sample = arrays_per_shard[0][ai]
            out = np.full(
                (n_devices, width), pad_values[ai], dtype=sample.dtype
            )
            for d, members in enumerate(buckets):
                off = 0
                for si in members:
                    arr = arrays_per_shard[si][ai]
                    out[d, off : off + len(arr)] = arr
                    off += len(arr)
            stacked.append(out)
        return stacked

    # -- execution ----------------------------------------------------------
    def execute(self, tables, query: GroupByQuery) -> ResultPayload:
        from bqueryd_tpu import ops
        from bqueryd_tpu.models.query import QueryEngine

        if not self.supports(query):
            raise ValueError(
                "MeshQueryExecutor handles mergeable aggregations only; "
                "route distinct-count / raw-rows queries per shard"
            )
        engine = QueryEngine()

        with self._phase("prune"):
            tables = [
                t
                for t in tables
                if not query.where_terms
                or ops.shard_can_match(t, query.where_terms)
            ]
        if not tables:
            return ResultPayload.empty()

        with self._phase("mask"):
            masks = []
            for table in tables:
                mask = ops.build_mask(table, query.where_terms)
                if query.expand_filter_column:
                    basket_raw = table.column_raw(query.expand_filter_column)
                    bcodes, buniques = ops.factorize(basket_raw)
                    mask = ops.expand_mask_by_group(
                        bcodes, mask, n_groups=len(buniques)
                    )
                masks.append(None if mask is None else np.asarray(mask))

        with self._phase("align"):
            dense, combos, cards, key_values = self._global_key_space(
                tables, query, engine
            )
            n_groups = max(len(combos), 1)
            # fold the row mask into the codes: masked-out rows become null
            # (code -1) and vanish from every segment reduction
            for si, mask in enumerate(masks):
                if mask is not None:
                    dense[si] = np.where(mask, dense[si], np.int64(-1))

        with self._phase("layout"):
            n_dev = self.mesh.devices.size
            measure_cols = query.in_cols
            per_shard = []
            for si, table in enumerate(tables):
                arrs = [dense[si].astype(np.int32)]
                for col in measure_cols:
                    arrs.append(np.asarray(table.column_raw(col)))
                per_shard.append(tuple(arrs))
            pads = [np.int32(-1)] + [0] * len(measure_cols)
            stacked = self._bucketize(per_shard, n_dev, pads)

        with self._phase("aggregate"):
            merged = self._run_mesh(
                stacked[0], tuple(stacked[1:]), query.ops, n_groups
            )
            merged = {
                "rows": np.asarray(merged["rows"]),
                "aggs": [
                    {k: np.asarray(v) for k, v in part.items()}
                    for part in merged["aggs"]
                ],
            }

        with self._phase("collect"):
            rows = merged["rows"]
            present = rows > 0
            combos_present = combos[present]
            if len(query.groupby_cols) == 1:
                key_codes = [combos_present]
            else:
                key_codes = ops.unpack_codes(combos_present, cards)
            keys = {}
            for col, codes_g in zip(query.groupby_cols, key_codes):
                idx = np.asarray(codes_g, dtype=np.int64)
                keys[col] = key_values[col][idx]
            aggs = [
                {k: v[present] for k, v in part.items()}
                for part in merged["aggs"]
            ]
            return ResultPayload.partials(
                key_cols=query.groupby_cols,
                keys=keys,
                rows=rows[present],
                aggs=aggs,
                ops=query.ops,
                out_cols=query.out_cols,
            )

    def _run_mesh(self, codes, measures, agg_ops, n_groups):
        """Place ``[n_dev, L]`` blocks over the mesh and run the compiled
        partials + psum program; result is replicated, one copy pulled."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        axis = self.axis_name
        sharding = NamedSharding(mesh, P(axis, None))
        codes_d = jax.device_put(codes, sharding)
        measures_d = tuple(jax.device_put(m, sharding) for m in measures)
        return _mesh_partials(
            mesh, axis, agg_ops, n_groups, codes_d, measures_d
        )


@functools.lru_cache(maxsize=64)
def _mesh_program(mesh, axis, agg_ops, n_groups, n_measures):
    """Build + cache the jitted shard_map program for one query shape."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bqueryd_tpu import ops

    def block_fn(codes_blk, *measure_blks):
        partials = ops.partial_tables(
            codes_blk[0],
            tuple(m[0] for m in measure_blks),
            agg_ops,
            n_groups,
        )
        return ops.psum_partials(partials, axis)

    fn = jax.shard_map(
        block_fn,
        mesh=mesh,
        in_specs=tuple([P(axis, None)] * (1 + n_measures)),
        out_specs=P(),
    )
    return jax.jit(fn)


def _mesh_partials(mesh, axis, agg_ops, n_groups, codes_d, measures_d):
    program = _mesh_program(
        mesh, axis, tuple(agg_ops), int(n_groups), len(measures_d)
    )
    return program(codes_d, *measures_d)
