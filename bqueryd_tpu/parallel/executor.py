"""Mesh executor: one query over many shards, merged on-device with psum.

This is the TPU-native replacement for the reference's shard fan-out + merge
pipeline (per-shard tar results at reference bqueryd/worker.py:335-346,
controller tar-of-tars at reference bqueryd/controller.py:186-211, client
re-groupby at reference bqueryd/rpc.py:150-173).  Where the reference ships N
serialized result tables over TCP and re-aggregates them twice, here the N
shards are laid out over a 1-D ``jax.sharding.Mesh`` and the merge is a
``jax.lax.psum`` of index-aligned partial tables riding the ICI — one compiled
program, zero host serialization between partial and merged result.

What makes the psum legal is host-side key alignment: every shard's group
codes are remapped into one *global* composite-key space before the kernel
runs (SURVEY.md §7.3 "Merge alignment"), so row ``g`` of every device's
partial table refers to the same group.  The alignment is cheap (NumPy
searchsorted over per-shard dictionaries, not data rows) and happens once per
query.

Layout: all shards' rows are concatenated and split EVENLY across the mesh's
devices (legal because codes are global — any row partition psums to the same
answer), right-padded with code ``-1`` (the null code — padding contributes
to no group, see ``ops.partial_tables``), giving a balanced, static
``[n_devices, rows_per_device]`` shape XLA can tile.

Falls back to nothing: callers (worker, __graft_entry__, bench) route
non-mergeable aggregations (count_distinct family) and the aggregate=False
raw-rows path through the per-shard ``QueryEngine`` + host merge instead —
those results carry value *sets*, which a fixed-width psum cannot merge.

Steady-state serving is cache-resident (the TPU analogue of bquery's
``auto_cache`` factorization cache, reference bqueryd/worker.py:291),
organized as the working-set layer in :mod:`bqueryd_tpu.ops.workingset`:
host-side key alignment cached per (table-set, groupby-cols), and the
packed device blocks — group codes and measure columns — HBM-resident in
LRU byte-budgeted segments keyed by table identity (rootdir + mtime, so
shard activation invalidates naturally).  A repeated query — including one
with a DIFFERENT measure column, aggregate op or filter — therefore skips
decode, factorize, alignment and (for codes) H2D, and costs one compiled
kernel dispatch; under HBM pressure the working set sheds LRU device
entries before the allocator can wedge.

The cold path is a staged pipeline on the bounded pool in
:mod:`bqueryd_tpu.parallel.pipeline`: storage decode of cache-missing
measure columns is prefetched while key alignment runs, per-shard
decode/factorize fans out on the same pool, and the column build loop
keeps one decode+pack in flight ahead of each H2D transfer — stage busy
clocks feed the ``bqueryd_tpu_pipeline_busy_seconds`` gauges and bench.py's
overlap ratio.
"""

import contextlib
import functools
import os
import threading
import time

import numpy as np

from bqueryd_tpu.models.query import GroupByQuery, ResultPayload


def make_mesh(n_devices=None, axis_name="shards"):
    """A 1-D mesh over the first ``n_devices`` JAX devices.

    In a multi-host job (``ops.maybe_init_distributed``) ``jax.devices()``
    spans every host of the slice, so the shard mesh — and the psum merge —
    covers all chips: ICI within a host, DCN across hosts."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def _put(arr_np, sharding):
    """Host->device placement that also works when the mesh spans hosts:
    multi-host shardings reject a plain device_put of a host-global array,
    so each process materializes only its addressable shards via callback
    (every worker process computes the same global array)."""
    import jax

    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            arr_np.shape, sharding, lambda idx: arr_np[idx]
        )
    return jax.device_put(arr_np, sharding)


def _wire_dtype(tables, col):
    """Narrowest signed int dtype covering every shard's stored [min, max]
    for ``col``, or None to ship the stored dtype unchanged.

    Host->device bytes are the per-query cost floor (PCIe locally, the
    network tunnel under axon), so integer measures ride the wire at the
    width their actual value range needs; the kernel accumulates sums in
    int64 regardless (``ops.groupby._accum_dtype``), keeping aggregates
    bit-exact.  min/max partials are cast back to the stored dtype on the
    host after the merge."""
    lo = hi = None
    stored = None
    for t in tables:
        if t.kind(col) != "numeric":
            return None
        dt = t.physical_dtype(col)
        if dt.kind not in "iu":
            return None
        stored = dt if stored is None else max(stored, dt, key=lambda d: d.itemsize)
        stats = t.col_stats(col)
        if stats is None:
            return None
        lo = stats[0] if lo is None else min(lo, stats[0])
        hi = stats[1] if hi is None else max(hi, stats[1])
    for cand in (np.int8, np.int16, np.int32):
        info = np.iinfo(cand)
        if lo >= info.min and hi <= info.max:
            cand = np.dtype(cand)
            return cand if cand.itemsize < stored.itemsize else None
    return None


def _stored_dtype(tables, col):
    """Widest stored numeric dtype of ``col`` across shards, or None when any
    shard stores it non-numerically (dict/datetime)."""
    dts = []
    for t in tables:
        if t.kind(col) != "numeric":
            return None
        dts.append(t.physical_dtype(col))
    return np.result_type(*dts)


def _measure_kind(tables, col):
    """'datetime' when every shard stores ``col`` as a datetime, 'uint64'
    when every shard stores it unsigned-64 (mod-2^64 sums re-view as
    unsigned at finalize, pandas semantics), None for other numeric/dict;
    mixed datetime/non-datetime storage across shards is a data error."""
    kinds = {t.kind(col) for t in tables}
    if kinds == {"datetime"}:
        return "datetime"
    if "datetime" in kinds:
        raise ValueError(
            f"column {col!r} is datetime on some shards but not others"
        )
    dtypes = [t.physical_dtype(col) for t in tables]
    # the measures themselves widen via result_type (_stored_dtype), so the
    # unsigned tag must follow the WIDENED dtype: u64+u32 shards accumulate
    # in uint64 and their mod-2^64 sums still need the unsigned view
    if dtypes:
        widened = np.result_type(*dtypes)
        if widened == np.dtype(np.uint64):
            return "uint64"
        if widened.kind == "u":
            return "uint"
    return None


def _where_signature(query):
    """Hashable, canonical identity of a query's row-filter."""
    from bqueryd_tpu.models.query import freeze_value

    return (
        freeze_value(query.where_terms or []),
        query.expand_filter_column,
    )


def _codes_dtype(n_groups):
    """Narrowest signed dtype holding dense codes in [-1, n_groups)."""
    if n_groups <= np.iinfo(np.int8).max:
        return np.dtype(np.int8)
    if n_groups <= np.iinfo(np.int16).max:
        return np.dtype(np.int16)
    return np.dtype(np.int32)


# canonical table cache identity lives with the storage layer; kept under
# the old private name for existing importers
from bqueryd_tpu.storage.ctable import table_cache_key as _table_key  # noqa: E402,E501


class MeshQueryExecutor:
    """Executes a :class:`GroupByQuery` over a list of shard tables on a
    device mesh, merging per-shard partials with ``ops.psum_partials``.

    Handles the mergeable aggregation set (``ops.MERGEABLE_OPS``); the worker
    falls back to per-shard execution for distinct-count ops and raw rows.
    """

    def __init__(self, mesh=None, axis_name="shards", timer=None):
        self._mesh = mesh
        self.axis_name = axis_name
        self.timer = timer
        self._align_engine = None
        #: the physical kernel route the last execute() dispatched
        #: (post-guards) — the worker surfaces it as ``effective_strategy``
        #: in calc replies and the ``kernel`` trace span
        self.last_effective_strategy = None
        #: how the last execute() merged partials across the mesh
        #: ("device" | "host") — the worker surfaces it as the reply
        #: envelope's ``merge_mode`` key
        self.last_merge_mode = None
        from bqueryd_tpu.ops.workingset import WorkingSet

        # the device-resident working-set layer (ops/workingset.py): LRU
        # byte-budgeted segments with hit/miss/eviction telemetry and
        # HBM-watermark pressure eviction.
        #   align:  (tables_key, groupby_cols) -> (dense codes per shard,
        #           combos, cards, key_values) — host side
        #   codes:  folded+packed group codes -> jax.Array [n_dev, width]
        #   blocks: packed wire-dtype measure columns -> jax.Array
        # On CPU/tunneled backends the device segments count against host
        # RSS, so the defaults stay well under the worker's 2 GB restart
        # threshold (the watchdog clears them before giving up,
        # worker._check_mem)
        self.workingset = WorkingSet()
        self._align_cache = self.workingset.segment("align")
        self._hbm_cache = self.workingset.segment("blocks")
        self._codes_cache = self.workingset.segment("codes")

    def clear_caches(self):
        """Drop host alignment + HBM working-set segments (memory-watchdog
        hook)."""
        self.workingset.clear()
        if self._align_engine is not None:
            self._align_engine.clear_caches()

    @staticmethod
    def _map_shards(fn, items):
        """Map ``fn`` over shards on the shared pipeline pool (the
        decode/factorize/np work dominating cold alignment releases the
        GIL); sequential for single shards or one-thread pipelines.
        BQUERYD_TPU_ALIGN_THREADS caps the alignment fan-out specifically;
        BQUERYD_TPU_PIPELINE_THREADS sizes the pool itself."""
        from bqueryd_tpu.parallel import pipeline

        items = list(items)
        cap = os.environ.get("BQUERYD_TPU_ALIGN_THREADS")
        max_workers = int(cap) if cap is not None else len(items)
        return pipeline.map_ordered(fn, items, max_workers=max_workers)

    def _engine(self):
        """The engine used for alignment/key factorization — persistent so
        its factorize cache survives across queries (a fresh engine per
        execute() would re-factorize every alignment-cache miss)."""
        if self._align_engine is None:
            from bqueryd_tpu.models.query import QueryEngine

            self._align_engine = QueryEngine()
        return self._align_engine

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh(axis_name=self.axis_name)
        return self._mesh

    def _phase(self, name):
        import contextlib

        from bqueryd_tpu.utils.tracing import trace_span

        # every phase is wall-timed (PhaseTimer -> reply phase_timings), a
        # distributed-tracing span when the timer carries a SpanRecorder
        # (obs.trace: "layout" surfaces as "h2d_transfer", "aggregate" as
        # "kernel" — the psum collective merge is fused into that compiled
        # program — and "collect" as "merge", the materialization of the
        # merged partials), and, under BQUERYD_TPU_PROFILE=1, a jax.profiler
        # TraceAnnotation tagged with the active trace_id so device
        # timelines line up with the RPC waterfall
        stack = contextlib.ExitStack()
        stack.enter_context(trace_span(name))
        if self.timer is not None:
            stack.enter_context(self.timer.phase(name))
        return stack

    @staticmethod
    def supports(query: GroupByQuery):
        from bqueryd_tpu import ops

        return query.aggregate and all(
            op in ops.MERGEABLE_OPS for op in query.ops
        )

    # -- key alignment (host-side, dictionary-sized work only) --------------
    def _global_key_space(self, tables, query, engine):
        """Remap every shard's per-column key codes into one global space.

        Returns ``(per_shard_packed, combos, cards, key_values)`` where
        ``combos`` is the sorted global composite-key array, ``cards`` the
        global per-column cardinalities, and ``key_values[col]`` the global
        per-column key-value arrays (indexable by unpacked codes).
        """
        n_cols = len(query.groupby_cols)
        shard_codes = [[] for _ in range(n_cols)]   # [col][shard] -> codes
        shard_values = [[] for _ in range(n_cols)]  # [col][shard] -> uniques
        # composite-sidecar stamps, captured BEFORE any key column is read
        # (TOCTOU note in storage/ctable.py): a mid-align shard rewrite then
        # stores a stale-stamped sidecar that future loads miss
        comp_stamps = [
            getattr(t, "composite_stamp", lambda cols: None)(
                query.groupby_cols
            )
            for t in tables
        ]
        # per-shard decode+factorize is embarrassingly parallel and the
        # native decode/factorize/np IO all release the GIL; the caches the
        # engine touches are lock-protected (utils/cache.BytesCappedCache)
        per_table = self._map_shards(
            lambda table: [
                engine._key_codes(table, col)
                for col in query.groupby_cols
            ],
            tables,
        )
        for results in per_table:
            for ci, (codes, values) in enumerate(results):
                shard_codes[ci].append(np.asarray(codes))
                shard_values[ci].append(np.asarray(values))

        cards = []
        global_values = []
        pos_maps = [[] for _ in range(n_cols)]  # [col][shard] -> local->global
        for ci in range(n_cols):
            allv = np.concatenate(shard_values[ci])
            gvals = np.unique(allv)
            # strip null VALUES (float NaN / datetime NaT) from the global
            # dictionary: the rows referencing them already carry poisoned
            # codes (-1, models/query._key_codes), so keeping the null entry
            # would only create a never-referenced dictionary slot — and the
            # single-key dense shortcut below needs "every dictionary entry
            # is an observed group" to hold exactly
            if gvals.dtype.kind == "f":
                gvals = gvals[~np.isnan(gvals)]
            elif gvals.dtype.kind == "M":
                gvals = gvals[~np.isnat(gvals)]
            cards.append(max(len(gvals), 1))
            global_values.append(gvals)
            for si in range(len(tables)):
                # local dictionary -> global position (dictionary-sized);
                # the rows-sized gather through it happens lazily so a
                # composite-sidecar hit below skips it entirely
                pos_maps[ci].append(
                    np.searchsorted(gvals, shard_values[ci][si])
                )

        def mapped_codes(si, ci):
            # gather local codes through the local->global map; null codes
            # (<0) stay null
            codes = shard_codes[ci][si]
            pos = pos_maps[ci][si]
            return np.where(
                codes >= 0, pos[np.clip(codes, 0, None)], np.int64(-1)
            )

        from bqueryd_tpu import ops

        if n_cols == 1:
            # dense shortcut: every global dictionary entry came from some
            # shard's factorize/dictionary, so it is observed in >=1 row —
            # the global codes are ALREADY dense positions in the sorted
            # dictionary.  Skips the former rows-scale unique, which was
            # ~80% of the cold align wall at bench shapes.
            combos = np.arange(len(global_values[0]), dtype=np.int64)
            dense = self._map_shards(
                lambda si: mapped_codes(si, 0).astype(np.int64),
                range(len(tables)),
            )
            key_values = dict(zip(query.groupby_cols, global_values))
            return dense, combos, cards, key_values

        # guard BEFORE the composite sidecar loader: a sidecar stored by a
        # build predating the overflow guard holds silently WRAPPED packs
        # under the same dictionaries+cards digest — a cache hit must not
        # resurrect corrupt composites.  (The mesh alignment needs the
        # radix order, so past-int64 spaces degrade to the engine path at
        # the worker.)
        if ops.total_cardinality(cards) >= ops.MAX_COMPOSITE:
            raise ops.CompositeOverflow(
                "composite group-key space "
                f"{'x'.join(str(int(c)) for c in cards)} exceeds int64"
            )

        # multi-key: observed composites per shard via the native hash
        # factorizer (O(rows) per shard, small unique sets) instead of one
        # rows-scale sort-unique over the concatenated shards.  The result
        # is persisted next to the shard (composite sidecar) keyed by a
        # digest of the GLOBAL dictionaries + cardinalities: packed codes
        # depend on the whole shard set, so any set change invalidates.
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(np.asarray(cards, dtype=np.int64).tobytes())
        for g in global_values:
            a = np.asarray(g)
            if a.dtype == object:
                h.update(repr(a.tolist()).encode())
            else:
                h.update(a.dtype.str.encode())
                h.update(a.tobytes())
        digest = h.digest()

        def shard_composites(si):
            table = tables[si]
            loader = getattr(table, "composite_cache_load", None)
            if loader is not None:
                # validate against the PRE-READ stamp: shard_codes came from
                # those bytes, not from whatever the file holds now
                hit = loader(
                    query.groupby_cols, digest, stamp=comp_stamps[si]
                )
                if hit is not None:
                    return (
                        np.asarray(hit[0]),
                        np.asarray(hit[1], dtype=np.int64),
                    )
            packed = ops.pack_codes(
                [mapped_codes(si, ci) for ci in range(n_cols)], cards
            )
            inv, uniq = ops.factorize(packed)
            inv = np.asarray(inv)
            uniq = np.asarray(uniq, dtype=np.int64)
            storer = getattr(table, "composite_cache_store", None)
            if storer is not None and comp_stamps[si] is not None:
                storer(
                    query.groupby_cols, digest, inv, uniq,
                    stamp=comp_stamps[si],
                )
            return inv, uniq

        composites = self._map_shards(shard_composites, range(len(tables)))
        local_inverse = [c[0] for c in composites]
        local_uniques = [c[1] for c in composites]
        observed = [u[u >= 0] for u in local_uniques]
        observed = [o for o in observed if len(o)]
        combos = (
            np.unique(np.concatenate(observed))
            if observed
            else np.empty(0, dtype=np.int64)
        )
        # dense codes ride the per-shard dictionary: map each shard's few
        # observed composites into the sorted global combos, then gather
        dense = []
        for inv, uniq in zip(local_inverse, local_uniques):
            lut = np.searchsorted(combos, np.clip(uniq, 0, None)).astype(
                np.int64
            )
            lut[uniq < 0] = -1
            dense.append(lut[inv])
        key_values = dict(zip(query.groupby_cols, global_values))
        return dense, combos, cards, key_values

    # -- device layout ------------------------------------------------------
    @staticmethod
    def _pack(arrays, n_devices, pad, dtype=None):
        """Concat shard arrays and split evenly into ``[n_devices, width]``.

        Because every row carries a GLOBAL dense code, any row partition is
        valid — shard boundaries don't matter to the psum merge.  An even
        split beats greedy shard->device packing: devices are perfectly
        balanced and a single big shard still uses the whole mesh.  ``dtype``
        defaults to the common (widest) dtype of the inputs so mixed-width
        shards never silently wrap."""
        if dtype is None:
            dtype = (
                np.result_type(*[a.dtype for a in arrays])
                if len(arrays) > 1
                else arrays[0].dtype
            )
        from bqueryd_tpu import ops

        total = sum(len(a) for a in arrays)
        # bucketed per-device width (ops.program_bucket): row-count drift
        # across data refreshes reuses the compiled program; padded rows
        # carry the pad code (-1 for codes) and drop from every reduction
        width = ops.program_bucket(
            max(-(-total // n_devices), 1), fine=True
        )
        out = np.full(n_devices * width, pad, dtype=dtype)
        off = 0
        for arr in arrays:
            out[off : off + len(arr)] = arr
            off += len(arr)
        return out.reshape(n_devices, width)

    # -- execution ----------------------------------------------------------
    def execute(self, tables, query: GroupByQuery,
                strategy=None) -> ResultPayload:
        """``strategy`` is the planner's kernel-route hint, threaded into the
        mesh program's ``partial_tables`` call (and its trace cache key);
        None/"auto" keeps the dispatcher's own adaptive choice."""
        from bqueryd_tpu import chaos, ops

        # chaos site worker.device: a transient DeviceBusyError raised here
        # rides the same recovery seam as a real flaky tunneled backend —
        # the worker's handler marks the ErrorMessage transient and the
        # controller fails the shard over to a replica holder.  The
        # enabled() pre-check keeps the disarmed hot path from paying the
        # signature stringification just to hand fire() a discarded ctx
        if chaos.enabled():
            chaos.fire(
                "worker.device",
                n_tables=len(tables),
                signature=str(query.signature())[:120],
            )
        self.last_effective_strategy = None  # set at the kernel dispatch
        self.last_merge_mode = None          # set once the mode resolves
        if strategy in (None, "auto", "host"):
            # "host" is meaningless inside a mesh program; the worker should
            # not have routed such a query here, but degrade to auto rather
            # than refuse
            strategy = None

        if not self.supports(query):
            raise ValueError(
                "MeshQueryExecutor handles mergeable aggregations only; "
                "route distinct-count / raw-rows queries per shard"
            )
        # datetime measures ride the mesh as raw int64 with NaT (int64 min)
        # declared as a null sentinel so NaT rows skip counts and extrema
        # exactly like float NaNs (pandas semantics).  Sums/means of
        # datetimes are rejected HERE, before any alignment/decode/upload
        # work is spent on an invalid query.
        measure_kinds = tuple(
            _measure_kind(tables, col) for col in query.in_cols
        )
        for col, kind, op in zip(query.in_cols, measure_kinds, query.ops):
            if kind == "datetime" and op in ("sum", "mean"):
                raise ValueError(
                    f"{op!r} is not defined for datetime column {col!r}"
                )
        engine = self._engine()

        with self._phase("prune"):
            tables = [
                t
                for t in tables
                if not query.where_terms
                or ops.shard_can_match(t, query.where_terms)
            ]
        if not tables:
            return ResultPayload.empty()
        # calibration buckets key on the dispatch group's total rows — the
        # same quantity the controller's selector estimated from stats
        total_rows = sum(int(t.nrows) for t in tables)

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bqueryd_tpu.parallel import pipeline

        tables_key = tuple(_table_key(t) for t in tables)
        cols_key = tuple(query.groupby_cols)
        mesh = self.mesh
        n_dev = mesh.devices.size
        from bqueryd_tpu.parallel import devicemerge

        # the traced cross-device merge for this query: span-owned
        # reduce-scatter by default, the hostmerge fallback under the
        # BQUERYD_TPU_DEVICE_MERGE=0 kill switch, replicated psum on
        # multi-host pods (devicemerge.resolve_mode)
        merge_mode = devicemerge.resolve_mode()
        self.last_merge_mode = (
            "host" if merge_mode == devicemerge.MODE_HOST else "device"
        )
        sharding = NamedSharding(mesh, P(self.axis_name, None))
        codes_key = (
            tables_key, "codes", cols_key, _where_signature(query), n_dev,
        )

        # fused multi-agg gather: sum+count+mean over the same column pack,
        # upload and feed ONE device block; measure_index maps each agg
        # back to its slot inside the compiled program, so codes are
        # gathered against each distinct column exactly once
        unique_cols = list(dict.fromkeys(query.in_cols))
        measure_index = tuple(
            unique_cols.index(col) for col in query.in_cols
        )
        missing_cols = [
            col for col in unique_cols
            if (tables_key, "col", col, n_dev) not in self._hbm_cache
        ]
        align_warm = (tables_key, cols_key) in self._align_cache
        codes_warm = codes_key in self._codes_cache

        # shed LRU device cache BEFORE this query adds residency, while the
        # PR-3 HBM watermark sample still reflects the previous steady state
        # (evicting after the allocation failed would be a wedge, not a
        # plan).  Cold branches only: a fully-warm query adds nothing, and
        # the memory sample costs a device.memory_stats() round-trip that
        # must never tax steady-state latency — nor may the shed run before
        # a warm query's gets refresh their entries' recency.
        if missing_cols or not codes_warm:
            self.workingset.evict_under_pressure()

        # chunk-decode prefetch (pipeline stage 1): fire storage decode of
        # the cache-missing measure columns on the pipeline pool so decode
        # overlaps the mask/fold + codes-H2D work below.  Deferred until
        # AFTER alignment when the alignment is cold: align's own per-shard
        # fan-out needs the pool, and a FIFO pool would drain these decode
        # jobs first, serializing decode ahead of align instead of
        # overlapping either.  (Firing nowhere on the cold path was the
        # 0.115 cold storage-decode hit rate: the depth-2 column build paid
        # every decode inline with nothing warmed.)  The prefetch decodes
        # through ``ctable.column_raw`` on the SAME table instances the
        # build loop probes, so the warmed entries land under the content
        # keys the build path reads.
        prefetch = {}

        def _prefetch_missing():
            if pipeline.pipeline_threads() <= 1:
                return
            for col in missing_cols:
                futs = []
                for t in tables:
                    warm = getattr(t, "prefetch", None)
                    if warm is not None:
                        futs.extend(warm([col]))
                if futs:
                    prefetch[col] = futs

        if align_warm:
            _prefetch_missing()

        with self._phase("align"), pipeline.stage("align"):
            cached = self._align_cache.get((tables_key, cols_key))
            if cached is None:
                dense, combos, cards, key_values = self._global_key_space(
                    tables, query, engine
                )
                self._align_cache.put(
                    (tables_key, cols_key),
                    (dense, combos, cards, key_values),
                    nbytes=sum(d.nbytes for d in dense)
                    + combos.nbytes
                    + sum(v.nbytes for v in key_values.values()),
                )
            else:
                dense, combos, cards, key_values = cached
            n_groups = max(len(combos), 1)

        if not align_warm:
            # cold-align queries fire the measure prefetch HERE, once the
            # align fan-out has released the pool: decode overlaps the
            # mask/fold/pack + codes-H2D work below instead of serializing
            # inside the column build
            _prefetch_missing()

        codes_d = self._codes_cache.get(codes_key)
        if codes_d is None:
            # cold path only: masks + fold + pack + H2D.  On a cache hit the
            # whole filter evaluation is skipped — the folded codes ARE the
            # filter.
            with self._phase("mask"):
                masks = []
                for table in tables:
                    mask = ops.build_mask(table, query.where_terms)
                    if query.expand_filter_column:
                        # cached, with nulls-are-a-basket semantics
                        bcodes, buniques = engine._basket_codes(
                            table, query.expand_filter_column
                        )
                        mask = ops.expand_mask_by_group(
                            bcodes, mask, n_groups=len(buniques)
                        )
                    masks.append(None if mask is None else np.asarray(mask))
            with self._phase("layout"):
                # fold the row mask into the codes: masked-out rows become
                # null (code -1) and vanish from every segment reduction.
                # Folds into fresh arrays — cached dense stays unmasked.
                with pipeline.stage("align"):
                    cdt = _codes_dtype(n_groups)
                    folded = [
                        np.where(mask, d, -1).astype(cdt)
                        if mask is not None
                        else d.astype(cdt)
                        for d, mask in zip(dense, masks)
                    ]
                    packed = self._pack(
                        folded, n_dev, cdt.type(-1), dtype=cdt
                    )
                with pipeline.stage("h2d"):
                    codes_d = _put(packed, sharding)
                self._codes_cache.put(codes_key, codes_d)

        with self._phase("layout"):
            def build_packed(col):
                # wait for this column's prefetched decodes first: they
                # populate the storage cache, and racing a duplicate decode
                # here would burn the cores the pipeline is trying to share
                for fut in prefetch.get(col, ()):
                    fut.result()
                with pipeline.stage("decode"):
                    # decode (C++ chunk threads, GIL released) + narrow +
                    # pack into the [n_dev, width] device layout
                    wire = (
                        _wire_dtype(tables, col)
                        or _stored_dtype(tables, col)
                    )
                    cols = [np.asarray(t.column_raw(col)) for t in tables]
                    if wire is not None:
                        cols = [c.astype(wire, copy=False) for c in cols]
                    return self._pack(cols, n_dev, 0, dtype=wire)

            # cold path with several columns: overlap the NEXT column's
            # decode+pack with the CURRENT column's host->device transfer
            # (the two dominate cold latency and use disjoint resources)
            missing = [
                col
                for col in unique_cols
                if (tables_key, "col", col, n_dev) not in self._hbm_cache
            ]
            futures = {}
            use_pool = len(missing) > 1 and pipeline.pipeline_threads() > 1
            missing_iter = iter(missing)

            def submit_next():
                for c in missing_iter:
                    futures[c] = pipeline.submit(build_packed, c)
                    return

            if use_pool:
                # prime ONE build ahead of the put loop; the next is
                # submitted as each is consumed — exactly one build in
                # flight plus the column being uploaded, so peak host
                # residency stays ~2 packed columns however many are
                # missing (priming two would run both concurrently on the
                # shared pool: ~3 resident)
                submit_next()
            measures_d = []
            for col in unique_cols:
                mkey = (tables_key, "col", col, n_dev)
                arr = self._hbm_cache.get(mkey)
                if arr is None:
                    if col in futures:
                        packed = futures.pop(col).result()
                        submit_next()
                    else:
                        packed = build_packed(col)
                    with pipeline.stage("h2d"):
                        arr = _put(packed, sharding)
                    self._hbm_cache.put(mkey, arr)
                measures_d.append(arr)

        with self._phase("aggregate"), pipeline.stage("kernel"):
            sentinels = tuple(
                np.iinfo(np.int64).min if k == "datetime" else None
                for k in measure_kinds
            )
            # returns host numpy partials; with packed fetch (default) the
            # whole merged pytree comes back as ONE device buffer — per-leaf
            # pulls cost a full transport round-trip each on tunneled/remote
            # devices
            # the program computes over the BUCKETED group count (shape
            # reuse across cardinality drift, ops.program_bucket); padded
            # groups have zero rows and are sliced off right below, on host
            n_prog = ops.program_bucket(n_groups)
            # the physical route this dispatch takes post-guards: reported
            # as effective_strategy and the label calibration samples land
            # under (hints silently normalized here until this existed —
            # neither traces nor bench could tell what actually ran)
            per_agg_d = tuple(measures_d[i] for i in measure_index)
            # normalize the hint BEFORE predicting/labelling the route: a
            # hint the guards would normalize inside _mesh_partials (e.g.
            # "scatter" on a backend whose auto dispatch internally sorts)
            # must not be reported — or recorded into calibration cells —
            # as a route the program never ran (the highcard cell-keying
            # bug: "scatter"-labelled walls that were really the sort path)
            strategy = _effective_mesh_strategy(
                strategy, tuple(query.ops), n_prog, per_agg_d,
                int(codes_d.shape[1]),
            )
            route = ops.kernel_route(
                strategy, per_agg_d, tuple(query.ops),
                int(codes_d.shape[1]), n_prog,
            )
            self.last_effective_strategy = route
            from bqueryd_tpu.obs import profile as obs_profile

            profiler = obs_profile.profiler()
            # tunneled backends surface transient remote-compile INTERNAL
            # errors (HTTP 500 compile-helper crashes observed on hardware,
            # TPU_VALIDATE_r5_prefix.json case7/case13): one retry keeps
            # the on-device merge path; a second failure propagates to the
            # worker, which degrades to the per-shard engine path
            for attempt in range(2):
                misses_before = profiler.jit_cache_misses
                kernel_clock = time.perf_counter()
                try:
                    merged = _mesh_partials(
                        mesh, self.axis_name, query.ops, n_prog,
                        codes_d, tuple(measures_d),
                        null_sentinels=sentinels,
                        strategy=strategy,
                        measure_index=measure_index,
                        merge_mode=merge_mode,
                        timer=self.timer,
                    )
                    kernel_wall = time.perf_counter() - kernel_clock
                    break
                except jax.errors.JaxRuntimeError as exc:
                    # deterministic failures (INVALID_ARGUMENT, device OOM)
                    # would fail identically: propagate at once and let the
                    # worker degrade, keeping the sleep out of their path
                    # (and out of the aggregate-phase timing)
                    if attempt or not _transient_status(exc):
                        raise
                    time.sleep(0.5)
            # measured-cost calibration sample (the planner feedback loop):
            # walls tainted by a jit compile are skipped — a 20 s compile
            # inside a 4 ms kernel wall would poison the route's EWMA
            from bqueryd_tpu.plan import calibrate

            if (
                calibrate.enabled()
                and profiler.jit_cache_misses == misses_before
            ):
                prog = profiler.last_program("executor.mesh_program")
                calibrate.record_sample(
                    rows=total_rows, groups=n_groups,
                    dtypes=[m.dtype for m in per_agg_d],
                    backend=jax.default_backend(),
                    strategy=route, wall_s=kernel_wall,
                    flops=(prog or {}).get("flops"),
                    bytes_accessed=(prog or {}).get("bytes_accessed"),
                )
            if n_prog != n_groups:
                import jax as _jax

                # group axis is LAST: host-mode partials carry a leading
                # per-device axis, merged tables are flat
                merged = _jax.tree_util.tree_map(
                    lambda a: a[..., :n_groups], merged
                )

        with self._phase("collect"), pipeline.stage("merge"):
            return self._finish_collect(
                merged, merge_mode, int(n_dev), query, tables,
                combos, cards, key_values, measure_kinds,
            )

    def _collect_payload(self, partial_table, query, tables, combos, cards,
                         key_values, measure_kinds):
        """One merged (or single-device) partial table -> ResultPayload
        keyed by actual key values."""
        from bqueryd_tpu import ops

        rows = partial_table["rows"]
        present = rows > 0
        combos_present = combos[present]
        if len(query.groupby_cols) == 1:
            key_codes = [combos_present]
        else:
            key_codes = ops.unpack_codes(combos_present, cards)
        keys = {}
        for col, codes_g in zip(query.groupby_cols, key_codes):
            idx = np.asarray(codes_g, dtype=np.int64)
            keys[col] = key_values[col][idx]
        aggs = []
        for in_col, part in zip(query.in_cols, partial_table["aggs"]):
            stored = _stored_dtype(tables, in_col)
            selected = {}
            for k, v in part.items():
                v = v[present]
                # min/max partials computed on a narrowed wire dtype go
                # back to the column's stored dtype
                if (
                    k in ("min", "max")
                    and stored is not None
                    and v.dtype != stored
                    and stored.kind in "iu"
                ):
                    v = v.astype(stored)
                selected[k] = v
            aggs.append(selected)
        return ResultPayload.partials(
            key_cols=query.groupby_cols,
            keys=keys,
            rows=rows[present],
            aggs=aggs,
            ops=query.ops,
            out_cols=query.out_cols,
            value_kinds=list(measure_kinds),
        )

    def _finish_collect(self, merged, merge_mode, n_dev, query, tables,
                        combos, cards, key_values, measure_kinds):
        """Merged partials (one query's pytree) -> its ResultPayload, per
        merge mode.  Host mode re-merges the per-device tables with the
        always-correct value-keyed merge — bit-identical aggregates,
        host-gather economics."""
        import jax

        from bqueryd_tpu.parallel import devicemerge

        if merge_mode == devicemerge.MODE_HOST:
            from bqueryd_tpu.parallel import hostmerge

            payloads = [
                self._collect_payload(
                    jax.tree_util.tree_map(lambda a: a[d], merged),
                    query, tables, combos, cards, key_values, measure_kinds,
                )
                for d in range(int(n_dev))
            ]
            return ResultPayload(hostmerge.merge_payloads(payloads))
        return self._collect_payload(
            merged, query, tables, combos, cards, key_values, measure_kinds,
        )

    # -- shared-scan bundles -------------------------------------------------
    def execute_bundle(self, tables, queries, strategy=None):
        """Shared-scan execution of a compatible query bundle: every query
        scans the same ``tables`` with the same group-key columns; measures
        and filters may differ per member.  One decode/align/factorize pass,
        one (unmasked) codes upload, one deduplicated union measure upload,
        one stacked-mask H2D, and ONE mesh program whose per-member partial
        tables merge in one collective pass.  Returns one
        :class:`ResultPayload` per query, input order.

        Parity contract: each member's partials are emitted by the same
        per-member :func:`ops.partial_tables` dispatch its solo execution
        would run (the mask rides the kernel's ``mask=`` argument, which
        zeroes exactly the contributions code-folding would drop), so
        integer aggregates are bit-identical to unfused execution and float
        aggregates differ only by kernel-route reassociation."""
        from bqueryd_tpu import chaos, ops
        from bqueryd_tpu.models.query import freeze_value

        if not queries:
            return []
        if chaos.enabled():
            chaos.fire(
                "worker.device",
                n_tables=len(tables),
                signature=f"bundle:{len(queries)}",
            )
        self.last_effective_strategy = None
        self.last_merge_mode = None
        if strategy in (None, "auto", "host"):
            strategy = None
        gcols = tuple(queries[0].groupby_cols)
        for query in queries:
            if tuple(query.groupby_cols) != gcols:
                raise ValueError(
                    "bundle members must share group-key columns"
                )
            if not self.supports(query):
                raise ValueError(
                    "bundle members must be mergeable aggregations"
                )
        # the union measure upload: every DISTINCT column across the bundle,
        # first-seen order; per-member aggs map onto slots in this union
        union_cols = list(
            dict.fromkeys(c for q in queries for c in q.in_cols)
        )
        union_kinds = tuple(
            _measure_kind(tables, col) for col in union_cols
        )
        kind_of = dict(zip(union_cols, union_kinds))
        for query in queries:
            for col, op in zip(query.in_cols, query.ops):
                if kind_of[col] == "datetime" and op in ("sum", "mean"):
                    raise ValueError(
                        f"{op!r} is not defined for datetime column {col!r}"
                    )
        engine = self._engine()

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bqueryd_tpu.parallel import devicemerge, pipeline

        tables_key = tuple(_table_key(t) for t in tables)
        cols_key = tuple(gcols)
        mesh = self.mesh
        n_dev = mesh.devices.size
        merge_mode = devicemerge.resolve_mode()
        self.last_merge_mode = (
            "host" if merge_mode == devicemerge.MODE_HOST else "device"
        )
        sharding = NamedSharding(mesh, P(self.axis_name, None))
        # the bundle's codes ride UNMASKED (each member's filter applies on
        # device through the stacked mask axis) — which is exactly the codes
        # entry an unfiltered single query folds, so the cache key is shared
        # with (and warms) the plain single-query path
        codes_key = (
            tables_key, "codes", cols_key, (freeze_value([]), None), n_dev,
        )
        missing_cols = [
            col for col in union_cols
            if (tables_key, "col", col, n_dev) not in self._hbm_cache
        ]
        align_warm = (tables_key, cols_key) in self._align_cache
        codes_warm = codes_key in self._codes_cache
        if missing_cols or not codes_warm:
            self.workingset.evict_under_pressure()

        # prefetch depth = the whole bundle's union: every member's missing
        # measure column fires its storage decode on the pool up front, so
        # the shared pass never pays a member's decode inline (the single-
        # query path prefetches only its own columns)
        prefetch = {}

        def _prefetch_missing():
            if pipeline.pipeline_threads() <= 1:
                return
            for col in missing_cols:
                futs = []
                for t in tables:
                    warm = getattr(t, "prefetch", None)
                    if warm is not None:
                        futs.extend(warm([col]))
                if futs:
                    prefetch[col] = futs

        if align_warm:
            _prefetch_missing()

        with self._phase("align"), pipeline.stage("align"):
            cached = self._align_cache.get((tables_key, cols_key))
            if cached is None:
                dense, combos, cards, key_values = self._global_key_space(
                    tables, queries[0], engine
                )
                self._align_cache.put(
                    (tables_key, cols_key),
                    (dense, combos, cards, key_values),
                    nbytes=sum(d.nbytes for d in dense)
                    + combos.nbytes
                    + sum(v.nbytes for v in key_values.values()),
                )
            else:
                dense, combos, cards, key_values = cached
            n_groups = max(len(combos), 1)

        if not align_warm:
            _prefetch_missing()

        codes_d = self._codes_cache.get(codes_key)
        if codes_d is None:
            with self._phase("layout"):
                with pipeline.stage("align"):
                    cdt = _codes_dtype(n_groups)
                    packed = self._pack(
                        [d.astype(cdt) for d in dense], n_dev,
                        cdt.type(-1), dtype=cdt,
                    )
                with pipeline.stage("h2d"):
                    codes_d = _put(packed, sharding)
                self._codes_cache.put(codes_key, codes_d)

        # stacked per-member masks: one row per member that filters, one
        # H2D for the whole stack.  Members without filters index None and
        # feed the kernel mask=None — the bit-identical solo form.
        mask_rows = []
        mask_idx_of = {}
        with self._phase("mask"):
            for qi, query in enumerate(queries):
                if not query.where_terms:
                    continue
                shard_masks = []
                for table in tables:
                    mask = ops.build_mask(table, query.where_terms)
                    shard_masks.append(
                        np.ones(int(table.nrows), dtype=bool)
                        if mask is None else np.asarray(mask)
                    )
                mask_idx_of[qi] = len(mask_rows)
                mask_rows.append(
                    self._pack(shard_masks, n_dev, False, dtype=np.bool_)
                )
        masks_d = None
        if mask_rows:
            with self._phase("layout"), pipeline.stage("h2d"):
                masks_d = _put(
                    np.stack(mask_rows),
                    NamedSharding(mesh, P(None, self.axis_name, None)),
                )

        with self._phase("layout"):
            def build_packed(col):
                for fut in prefetch.get(col, ()):
                    fut.result()
                with pipeline.stage("decode"):
                    wire = (
                        _wire_dtype(tables, col)
                        or _stored_dtype(tables, col)
                    )
                    cols = [np.asarray(t.column_raw(col)) for t in tables]
                    if wire is not None:
                        cols = [c.astype(wire, copy=False) for c in cols]
                    return self._pack(cols, n_dev, 0, dtype=wire)

            missing = [
                col
                for col in union_cols
                if (tables_key, "col", col, n_dev) not in self._hbm_cache
            ]
            futures = {}
            use_pool = len(missing) > 1 and pipeline.pipeline_threads() > 1
            missing_iter = iter(missing)

            def submit_next():
                for c in missing_iter:
                    futures[c] = pipeline.submit(build_packed, c)
                    return

            if use_pool:
                submit_next()
            measures_d = []
            for col in union_cols:
                mkey = (tables_key, "col", col, n_dev)
                arr = self._hbm_cache.get(mkey)
                if arr is None:
                    if col in futures:
                        packed = futures.pop(col).result()
                        submit_next()
                    else:
                        packed = build_packed(col)
                    with pipeline.stage("h2d"):
                        arr = _put(packed, sharding)
                    self._hbm_cache.put(mkey, arr)
                measures_d.append(arr)

        slot_of = {col: i for i, col in enumerate(union_cols)}
        sentinels = tuple(
            np.iinfo(np.int64).min if k == "datetime" else None
            for k in union_kinds
        )
        member_specs = tuple(
            (
                mask_idx_of.get(qi),
                tuple(
                    (slot_of[col], op)
                    for col, op in zip(query.in_cols, query.ops)
                ),
            )
            for qi, query in enumerate(queries)
        )

        with self._phase("aggregate"), pipeline.stage("kernel"):
            n_prog = ops.program_bucket(n_groups)
            # route label: on CPU the shared-scan kernel is the batched
            # scatter family regardless of any hint; on accelerators the
            # bundle runs per-member partial_tables dispatches (the
            # batched form would be the emulated wide scatter — see
            # ops.bundle_partial_tables), where the first member's
            # predicted route speaks for the bundle
            import jax as _jax

            if _jax.default_backend() == "cpu":
                self.last_effective_strategy = "scatter"
            else:
                first = queries[0]
                self.last_effective_strategy = ops.kernel_route(
                    strategy,
                    tuple(measures_d[slot_of[c]] for c in first.in_cols),
                    tuple(first.ops), int(codes_d.shape[1]), n_prog,
                )
            merged_members = _mesh_bundle_partials(
                mesh, self.axis_name, n_prog, codes_d, masks_d,
                tuple(measures_d), member_specs, sentinels,
                strategy=strategy, merge_mode=merge_mode,
                timer=self.timer,
            )
            if n_prog != n_groups:
                merged_members = jax.tree_util.tree_map(
                    lambda a: a[..., :n_groups], merged_members
                )

        with self._phase("collect"), pipeline.stage("merge"):
            out = []
            for query, merged in zip(queries, merged_members):
                member_kinds = [kind_of[c] for c in query.in_cols]
                out.append(
                    self._finish_collect(
                        merged, merge_mode, int(n_dev), query, tables,
                        combos, cards, key_values, member_kinds,
                    )
                )
            return out


def _pack_leaf(leaf):
    """Bitcast any result leaf to its native bytes (lossless, no widening —
    the packed buffer carries exactly the leaves' own byte sizes)."""
    import jax.numpy as jnp
    from jax import lax

    if leaf.dtype.itemsize == 1:
        return leaf.astype(jnp.uint8).ravel() if leaf.dtype != jnp.uint8 \
            else leaf.ravel()
    # bitcast to a SMALLER dtype appends a trailing byte axis
    return lax.bitcast_convert_type(leaf, jnp.uint8).ravel()


def _unpack_host(flat, spec):
    """Invert :func:`_pack_leaf` on the fetched numpy uint8 byte buffer."""
    leaves = []
    off = 0
    for dtype, shape in spec:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dtype.itemsize
        seg = flat[off:off + nbytes]
        off += nbytes
        # copy() realigns the slice so the view is valid at any offset
        leaves.append(seg.copy().view(dtype).reshape(shape))
    return leaves


def packed_fetch_enabled():
    """Fetch the merged result as ONE device buffer (default on): the merged
    pytree has one leaf per aggregation partial, and ``jax.device_get``
    copies leaves buffer-by-buffer — on a remote/tunneled backend each copy
    is a transport round-trip, turning a 2 ms kernel into tens of ms of
    fetch latency.  Packing bitcasts every leaf to its native bytes and
    concatenates INSIDE the compiled mesh program, so dispatch+fetch is
    exactly one program and one buffer of the leaves' own total size."""
    return os.environ.get("BQUERYD_TPU_PACKED_FETCH", "1") == "1"


def _route_key():
    """The env-derived knobs that steer the kernel route inside
    ``ops.partial_tables`` AT TRACE TIME.  They must be part of the
    ``_mesh_program`` cache key: the dispatcher reads them per call, but a
    cached program never re-runs the dispatcher — without the key a
    runtime flag flip (the bench's pallas variants, a live worker being
    re-tuned) would silently keep serving the previously-traced route."""
    from bqueryd_tpu.ops import groupby as gb
    from bqueryd_tpu.ops import pallas_groupby as pg

    return (
        pg.pallas_enabled(),
        os.environ.get("BQUERYD_TPU_FORCE_MATMUL") == "1",
        gb.matmul_groups_limit(),
        gb._matmul_cells_limit(),
        pg.hicard_groups_limit(),
    )


def _shard_map(fn, mesh, in_specs, out_specs, check):
    """Version-portable shard_map: ``jax.shard_map`` (its home since jax
    0.6, ``check_vma=``) with a fallback to the pre-0.6
    ``jax.experimental.shard_map`` location (``check_rep=``)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    return legacy_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


@functools.lru_cache(maxsize=64)
def _mesh_program(mesh, axis, agg_ops, n_groups, in_dtypes, in_width, pack,
                  null_sentinels=None, route=None, strategy=None,
                  measure_index=None, merge_mode="psum"):
    """Build + cache the jitted shard_map program for one query shape.

    The key carries everything that can change the traced program — measure
    wire dtypes AND the per-device row width (``in_width``): the packed
    output's host-side unpack spec is captured at trace time, and both leaf
    dtypes (via the measure dtypes) and the kernel route (via the row count,
    ``_matmul_cells_limit``, and the ``route`` flag tuple) feed it, so one
    cache entry must map to exactly one trace.  ``measure_index`` (static)
    maps each aggregation to its slot in the DEDUPLICATED measure blocks:
    ``sum+count+mean`` of one column ride one uploaded block and one
    program argument instead of three.

    ``merge_mode`` (static, devicemerge.MODE_*) picks the cross-device
    merge traced into the program:

    * ``device`` — bucketized partials reduce-scatter over the mesh axis so
      each device owns a contiguous key span; outputs are span-sized and
      the D2H fetch is the final table only (the default);
    * ``psum``   — the all-reduce + replicated-output contract (multi-host
      pods, where a span-sharded output is not host-fetchable);
    * ``host``   — NO collective: every device's full partial table comes
      back (leading device axis host-side) for ``hostmerge.merge_payloads``
      — the kill-switch baseline."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bqueryd_tpu import ops
    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    spec = {}  # populated at trace time: treedef + (dtype, shape) per leaf

    def block_fn(codes_blk, *measure_blks):
        per_block = tuple(m[0] for m in measure_blks)
        per_agg = (
            per_block
            if measure_index is None
            else tuple(per_block[i] for i in measure_index)
        )
        partials = ops.partial_tables(
            codes_blk[0],
            per_agg,
            agg_ops,
            n_groups,
            null_sentinels=null_sentinels,
            strategy=strategy,
        )
        if merge_mode == devicemerge.MODE_DEVICE:
            # key-span ownership: pad onto the bucket layout (behind the
            # kernel guards — this is the dispatched partials' OUTPUT) and
            # reduce-scatter so this device keeps only its span's totals
            bucketized, span = ops.bucketize_partials(
                partials, n_groups, n_dev
            )
            merged = devicemerge.scatter_merge_partials(
                bucketized, axis, n_dev, span
            )
        elif merge_mode == devicemerge.MODE_HOST:
            # kill switch: no collective — the per-device partial tables
            # leave HBM whole and merge on the worker host
            merged = partials
        else:
            merged = ops.psum_partials(partials, axis)
        if not pack:
            return merged
        leaves, treedef = jax.tree_util.tree_flatten(merged)
        spec["treedef"] = treedef
        spec["leaves"] = tuple(
            (np.dtype(leaf.dtype), tuple(leaf.shape)) for leaf in leaves
        )
        import jax.numpy as jnp

        return jnp.concatenate([_pack_leaf(leaf).ravel() for leaf in leaves])

    # pallas_call outputs carry no varying-mesh-axes metadata, so the vma/rep
    # check would reject the kernel path; the psum in block_fn is what makes
    # the out_specs=P() replication true by construction.  Span-owned
    # (device) and per-device (host) outputs are axis-sharded instead: the
    # global result concatenates every device's slice in device order.
    out_spec = P() if merge_mode == devicemerge.MODE_PSUM else P(axis)
    fn = _shard_map(
        block_fn,
        mesh=mesh,
        in_specs=tuple([P(axis, None)] * len(in_dtypes)),
        out_specs=out_spec,
        check=False,
    )
    # compile/call accounting (obs.profile): every mesh-program call lands
    # in the jit-cache hit/miss counters, compiles in the compile-seconds
    # histogram + per-shape program registry with cost_analysis FLOPs
    from bqueryd_tpu.obs import profile as obsprofile

    return obsprofile.instrument("executor.mesh_program", jax.jit(fn)), spec


@functools.lru_cache(maxsize=32)
def _mesh_bundle_program(mesh, axis, n_groups, in_dtypes, in_width, pack,
                         member_specs, null_sentinels, route=None,
                         strategy=None, merge_mode="psum", n_masks=0):
    """Build + cache the jitted shared-scan BUNDLE program for one bundle
    shape.  The key carries everything that changes the trace: the static
    per-member spec tuple (mask slot + (measure slot, op) pairs), the
    stacked-mask count, the union measure dtypes, and the same route/merge
    knobs as :func:`_mesh_program`.  The program emits one merged partial
    table PER MEMBER (a tuple pytree): each member's emission is the same
    :func:`ops.partial_tables` dispatch its solo program runs, under its
    own stacked-mask row, and each member's cross-device merge is the same
    collective the solo program traces — the whole bundle reduces in one
    compiled dispatch."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bqueryd_tpu import ops
    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    spec = {}

    def merge_member(partials):
        if merge_mode == devicemerge.MODE_DEVICE:
            bucketized, span = ops.bucketize_partials(
                partials, n_groups, n_dev
            )
            return devicemerge.scatter_merge_partials(
                bucketized, axis, n_dev, span
            )
        if merge_mode == devicemerge.MODE_HOST:
            return partials
        return ops.psum_partials(partials, axis)

    def body(codes_blk, masks_blk, measure_blks):
        codes = codes_blk[0]
        masks = None if masks_blk is None else masks_blk[:, 0, :]
        per_col = tuple(m[0] for m in measure_blks)
        members = ops.bundle_partial_tables(
            codes, masks, per_col, member_specs, n_groups,
            null_sentinels=null_sentinels, strategy=strategy,
        )
        merged = tuple(merge_member(partials) for partials in members)
        if not pack:
            return merged
        leaves, treedef = jax.tree_util.tree_flatten(merged)
        spec["treedef"] = treedef
        spec["leaves"] = tuple(
            (np.dtype(leaf.dtype), tuple(leaf.shape)) for leaf in leaves
        )
        import jax.numpy as jnp

        return jnp.concatenate([_pack_leaf(leaf).ravel() for leaf in leaves])

    n_measures = len(in_dtypes) - 1 - (1 if n_masks else 0)
    if n_masks:
        def block_fn(codes_blk, masks_blk, *measure_blks):
            return body(codes_blk, masks_blk, measure_blks)

        in_specs = (P(axis, None), P(None, axis, None)) + tuple(
            [P(axis, None)] * n_measures
        )
    else:
        def block_fn(codes_blk, *measure_blks):
            return body(codes_blk, None, measure_blks)

        in_specs = tuple([P(axis, None)] * (1 + n_measures))
    out_spec = P() if merge_mode == devicemerge.MODE_PSUM else P(axis)
    fn = _shard_map(
        block_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_spec,
        check=False,
    )
    from bqueryd_tpu.obs import profile as obsprofile

    return obsprofile.instrument(
        "executor.mesh_bundle_program", jax.jit(fn)
    ), spec


def _mesh_bundle_partials(mesh, axis, n_groups, codes_d, masks_d, measures_d,
                          member_specs, null_sentinels, strategy=None,
                          merge_mode="psum", timer=None):
    """Run the bundle program and return the per-member merged partials
    tuple ON HOST (numpy leaves) — one packed fetch for the whole bundle
    when packing is enabled, with a per-query fallback to per-leaf
    ``device_get`` (no process latch: the single-query path owns the
    packed-broken diagnosis).  Shapes follow :func:`_mesh_partials`:
    ``device``/``psum`` leaves are ``[n_groups]`` per member, ``host``
    leaves ``[n_dev, n_groups]`` for the hostmerge fallback."""
    import jax

    from bqueryd_tpu.parallel import devicemerge

    n_dev = int(mesh.devices.size)
    pack = packed_fetch_enabled() and not _packed_fetch_broken
    in_dtypes = (
        (str(codes_d.dtype),)
        + ((str(masks_d.dtype),) if masks_d is not None else ())
        + tuple(str(m.dtype) for m in measures_d)
    )
    n_masks = 0 if masks_d is None else int(masks_d.shape[0])
    args = (
        (codes_d,)
        + ((masks_d,) if masks_d is not None else ())
        + tuple(measures_d)
    )

    def run(pack_flag):
        return _mesh_bundle_program(
            mesh, axis, int(n_groups), in_dtypes, int(codes_d.shape[1]),
            pack_flag, member_specs, null_sentinels,
            route=_route_key(), strategy=strategy, merge_mode=merge_mode,
            n_masks=n_masks,
        )

    def finish(merged, fetched):
        if merge_mode == devicemerge.MODE_DEVICE:
            merged = jax.tree_util.tree_map(
                lambda a: a[: int(n_groups)], merged
            )
        elif merge_mode == devicemerge.MODE_HOST:
            merged = jax.tree_util.tree_map(
                lambda a: np.asarray(a).reshape(n_dev, int(n_groups)),
                merged,
            )
        _record_merge_bytes(
            merge_mode, fetched, n_dev, int(n_groups), merged
        )
        return merged

    if pack:
        try:
            program, spec = run(True)
            with _collective_guard():
                out = program(*args)
                _block_ready(out)
                with _fetch_phase(timer):
                    flat = np.asarray(jax.device_get(out))
        except Exception as exc:
            if isinstance(
                exc, jax.errors.JaxRuntimeError
            ) and _transient_status(exc):
                # transient infrastructure fault (same contract as
                # _mesh_partials): NOT evidence against packing, and
                # re-executing the whole N-member bundle per-leaf on the
                # same flaky backend would double the device work —
                # propagate so the worker's degrade/failover machinery
                # decides
                raise
            import logging

            logging.getLogger("bqueryd_tpu").exception(
                "packed bundle fetch failed; retrying via per-leaf "
                "device_get"
            )
        else:
            if merge_mode == devicemerge.MODE_PSUM:
                merged = jax.tree_util.tree_unflatten(
                    spec["treedef"], _unpack_host(flat, spec["leaves"])
                )
            else:
                merged = _assemble_sharded(flat, spec, n_dev, merge_mode)
            return finish(merged, flat.nbytes)
    program, _spec = run(False)
    with _collective_guard():
        out = program(*args)
        _block_ready(out)
        with _fetch_phase(timer):
            result = jax.device_get(out)
    fetched = sum(
        np.asarray(leaf).nbytes for leaf in jax.tree_util.tree_leaves(result)
    )
    return finish(result, fetched)


#: set when the packed program failed to build/run on this backend (seen
#: nowhere yet; guards against a backend rejecting the byte bitcasts) — all
#: later queries go straight to the per-leaf fetch
_packed_fetch_broken = False

#: consecutive transiently-classed packed-fetch failures; once it reaches
#: _PACKED_TRANSIENT_LIMIT the "transient" diagnosis is abandoned and the
#: per-leaf latch sets anyway (an XLA lowering bug classed INTERNAL would
#: otherwise dodge the latch forever, costing every query two failed packed
#: dispatches and an engine degrade)
_packed_transient_count = 0
_PACKED_TRANSIENT_LIMIT = 3

#: gRPC-style status prefixes a flaky tunneled backend surfaces for
#: infrastructure (retry-worthy) failures, as opposed to deterministic
#: program rejections (INVALID_ARGUMENT, UNIMPLEMENTED, FAILED_PRECONDITION)
#: or deterministic resource exhaustion.  Observed on hardware: remote
#: compile-helper crashes arrive as "INTERNAL: ... HTTP 500"
#: (TPU_VALIDATE_r5_prefix.json case7/case13).
_TRANSIENT_STATUSES = (
    "INTERNAL", "UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED", "UNKNOWN"
)


def _transient_status(exc):
    """Whether a JaxRuntimeError looks like transient infrastructure failure
    (worth one in-place retry) rather than a deterministic rejection."""
    msg = str(exc)
    return any(s in msg for s in _TRANSIENT_STATUSES)


def _effective_mesh_strategy(strategy, agg_ops, n_groups, measures_d, width):
    """Canonicalize a planner hint for the mesh-program cache key: a hint
    that cannot change the traced route must key (and trace) exactly like
    ``auto``, or an identical program would be compiled twice — a "matmul"
    hint is advisory by definition (the dispatcher decides identically under
    auto), a "scatter" hint is a no-op whenever auto would scatter anyway
    (always on CPU backends, and past the matmul group ceiling), and the
    calibration-backed "matmul!" normalizes to auto both when auto already
    takes the MXU route (identical program) and when the kernel guards
    would demote it (backend/value guards stand under promotion)."""
    if strategy in (None, "auto", "matmul"):
        return None
    from bqueryd_tpu.ops import groupby as gb

    mm = gb._matmul_profitable(
        measures_d, agg_ops, width, int(n_groups)
    ) or gb._hicard_matmul_profitable(
        measures_d, agg_ops, width, int(n_groups)
    )
    if strategy == "matmul!":
        if mm or not gb.matmul_route_allowed(width, int(n_groups)):
            return None
        return strategy
    if strategy == "scatter" and not mm:
        return None
    if strategy == "sort" and not mm:
        # auto's scatter entry already sorts past the blocks x groups budget
        blocks = -(-width // gb._SUM_BLOCK)
        if blocks * int(n_groups) > gb._MAX_BLOCK_SEGMENTS:
            return None
    return strategy


#: serializes mesh-program execution on CPU backends: XLA:CPU cross-module
#: collectives rendezvous by participant count process-globally, so two
#: concurrent psum programs from different threads (an in-process multi-
#: worker test cluster) interleave their AllReduce participants and
#: deadlock.  Production topology is one process per device set, where the
#: lock is uncontended; TPU backends skip it entirely.
_CPU_COLLECTIVE_LOCK = threading.Lock()


def _collective_guard():
    import contextlib

    import jax

    if jax.default_backend() == "cpu":
        return _CPU_COLLECTIVE_LOCK
    return contextlib.nullcontext()


def _assemble_sharded(flat, spec, n_dev, merge_mode):
    """Host-side reassembly of a packed axis-sharded fetch: the global byte
    buffer concatenates every device's packed slice in device order.  Device
    mode concatenates the span slices back into the (padded) merged table;
    host mode stacks the full per-device tables onto a leading device axis.
    Layout normalization (pad-tail slice / device-axis reshape) is the
    caller's ``finish`` — the contract lives there for BOTH fetch paths."""
    import jax

    per_dev = [
        _unpack_host(chunk, spec["leaves"])
        for chunk in flat.reshape(n_dev, -1)
    ]
    from bqueryd_tpu.parallel import devicemerge

    if merge_mode == devicemerge.MODE_DEVICE:
        leaves = [
            np.concatenate([dev[i] for dev in per_dev])
            for i in range(len(spec["leaves"]))
        ]
    else:
        leaves = [
            np.stack([dev[i] for dev in per_dev])
            for i in range(len(spec["leaves"]))
        ]
    return jax.tree_util.tree_unflatten(spec["treedef"], leaves)


def _record_merge_bytes(merge_mode, fetched, n_dev, n_groups, merged):
    """Account the D2H movement of one merged fetch: ``fetched`` actual
    bytes vs the host-gather counterfactual — every device's full partial
    table (``n_dev x n_groups`` rows per leaf) crossing to the host."""
    from bqueryd_tpu.parallel import devicemerge

    leaves = []
    import jax

    for leaf in jax.tree_util.tree_leaves(merged):
        leaves.append(np.dtype(np.asarray(leaf).dtype).itemsize)
    counterfactual = n_dev * n_groups * sum(leaves)
    devicemerge.stats().record(
        merge_mode, fetched, saved=counterfactual - int(fetched)
    )


@contextlib.contextmanager
def _fetch_phase(timer):
    """The D2H fetch timed as its own phase ("fetch" -> span "d2h_fetch"):
    the program output is blocked-until-ready first, so what this phase
    measures is the transfer itself, not the async kernel dispatch it used
    to hide inside the "aggregate" wall.  The fetch runs serially nested
    inside the open "aggregate" phase, so its wall is DEBITED from
    aggregate — one second of D2H bills the fetch phase once, not the
    kernel histogram too."""
    if timer is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        with timer.phase("fetch"):
            yield
    finally:
        timer.debit("aggregate", time.perf_counter() - t0)


def _block_ready(out):
    """``jax.block_until_ready`` with a pytree-walking fallback for older
    jaxlibs that predate the top-level helper."""
    import jax

    block = getattr(jax, "block_until_ready", None)
    if block is not None:
        return block(out)
    return jax.tree_util.tree_map(
        lambda a: a.block_until_ready()
        if hasattr(a, "block_until_ready") else a,
        out,
    )


def _mesh_partials(mesh, axis, agg_ops, n_groups, codes_d, measures_d,
                   null_sentinels=None, strategy=None, measure_index=None,
                   merge_mode="psum", timer=None):
    """Run the mesh program and return the merged partials pytree ON HOST
    (numpy leaves) — fetching one packed buffer when packing is enabled.
    ``measures_d`` holds one device block per DISTINCT measure column;
    ``measure_index`` maps each agg onto those slots (None = identity).

    ``merge_mode`` shapes the result: ``device``/``psum`` return the merged
    table (leaves ``[n_groups]``); ``host`` returns the UNMERGED per-device
    partials (leaves ``[n_dev, n_groups]``) for the hostmerge fallback.

    ``timer``: optional PhaseTimer; the device→host fetch is carved into
    its own "fetch" phase so attribution can split kernel wall from D2H."""
    global _packed_fetch_broken
    import jax

    from bqueryd_tpu.parallel import devicemerge

    pack = packed_fetch_enabled() and not _packed_fetch_broken
    n_dev = int(mesh.devices.size)
    per_agg_measures = (
        measures_d
        if measure_index is None
        else tuple(measures_d[i] for i in measure_index)
    )
    strategy = _effective_mesh_strategy(
        strategy, tuple(agg_ops), n_groups, per_agg_measures,
        int(codes_d.shape[1]),
    )
    in_dtypes = (str(codes_d.dtype),) + tuple(str(m.dtype) for m in measures_d)

    def run(pack_flag):
        return _mesh_program(
            mesh, axis, tuple(agg_ops), int(n_groups), in_dtypes,
            int(codes_d.shape[1]), pack_flag,
            null_sentinels,  # part of the lru key: it changes the trace
            route=_route_key(),  # ditto: the flags steer the traced route
            strategy=strategy,  # planner hint: a different traced route too
            measure_index=measure_index,  # agg -> deduped block slot
            merge_mode=merge_mode,  # the traced cross-device merge differs
        )

    def finish(merged, fetched):
        if merge_mode == devicemerge.MODE_DEVICE:
            # axis-sharded span outputs concatenate to the padded table;
            # the bucket pad tail holds no real group
            merged = jax.tree_util.tree_map(
                lambda a: a[: int(n_groups)], merged
            )
        elif merge_mode == devicemerge.MODE_HOST:
            merged = jax.tree_util.tree_map(
                lambda a: np.asarray(a).reshape(n_dev, int(n_groups)),
                merged,
            )
        _record_merge_bytes(
            merge_mode, fetched, n_dev, int(n_groups), merged
        )
        return merged

    global _packed_transient_count
    latch_pending = False
    if pack:
        try:
            program, spec = run(True)
            with _collective_guard():
                out = program(codes_d, *measures_d)
                _block_ready(out)
                with _fetch_phase(timer):
                    flat = np.asarray(jax.device_get(out))
        except Exception as exc:
            if (
                isinstance(exc, jax.errors.JaxRuntimeError)
                and _transient_status(exc)
                and _packed_transient_count + 1 < _PACKED_TRANSIENT_LIMIT
            ):
                # transient infrastructure error (tunneled backends surface
                # flaky remote-compile HTTP 500s as INTERNAL, dropped links
                # as UNAVAILABLE): NOT evidence against packing — re-raise
                # so the caller's retry re-attempts the packed program
                # instead of latching the process into per-leaf fetch (one
                # transport round-trip per result leaf) forever.  A
                # DETERMINISTIC failure that happens to carry a transient
                # status (e.g. an XLA lowering bug classed INTERNAL) is
                # caught by the consecutive-failure cap: past the limit the
                # latch path below runs after all.
                _packed_transient_count += 1
                raise
            # packed compile/run failure must never fail the query: fall
            # back to per-leaf fetch.  The process-lifetime latch commits
            # only AFTER per-leaf succeeds below — per-leaf working while
            # packed fails is the actual evidence against packing; if
            # per-leaf fails too (whole backend down), the failure carried
            # no packed-specific signal and must not latch.
            latch_pending = True
            import logging

            logging.getLogger("bqueryd_tpu").exception(
                "packed fetch failed; retrying this query via per-leaf "
                "device_get"
            )
        else:
            _packed_transient_count = 0
            if merge_mode == devicemerge.MODE_PSUM:
                leaves = _unpack_host(flat, spec["leaves"])
                merged = jax.tree_util.tree_unflatten(
                    spec["treedef"], leaves
                )
            else:
                merged = _assemble_sharded(
                    flat, spec, n_dev, merge_mode
                )
            return finish(merged, flat.nbytes)
    program, _spec = run(False)
    with _collective_guard():
        out = program(codes_d, *measures_d)
        _block_ready(out)
        with _fetch_phase(timer):
            result = jax.device_get(out)
    if latch_pending:
        _packed_fetch_broken = True
        _packed_transient_count = 0
        import logging

        logging.getLogger("bqueryd_tpu").warning(
            "packed fetch unavailable on this backend (per-leaf fetch "
            "succeeded where the packed program failed); using per-leaf "
            "device_get for the process lifetime"
        )
    fetched = sum(
        np.asarray(leaf).nbytes for leaf in jax.tree_util.tree_leaves(result)
    )
    return finish(result, fetched)
