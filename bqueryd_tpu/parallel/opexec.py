"""Operator-DAG executor: per-shard scheduling of the relational operators.

The scheduler half of :mod:`bqueryd_tpu.plan.dag`: a worker hands each
CalcMessage's compiled :class:`~bqueryd_tpu.plan.dag.OperatorDAG` to
:class:`DagExecutor`, which schedules the per-shard operator pipeline on
the PR-4 stage pool (shard i+1's scan/join overlaps shard i's kernels) and
merges the per-shard partial states host-side — the same value-keyed merge
(and therefore the same PR-8 failover and PR-10 autopsy story) the
classic path uses for non-psum-mergeable aggregations.

Per-shard pipeline::

    mask(pushdown) -> join probe (gather after factorizing the join key)
      -> window rollup (datetime-bucket derived key)
      -> post-derivation filter -> composite key codes
      -> per-node partials: GroupAgg (existing kernels, unchanged routing)
                            TopK (sort route, per-shard top-k)
                            QuantileSketch (DDSketch-style log buckets)
      -> ResultPayload (kind="partials", extended agg part kinds)

Extended partial part kinds (inside ``payload["aggs"][i]``, exactly like
the flat ``distinct_values``/``distinct_offsets`` sets):

* ``topk_values`` / ``topk_offsets`` — group ``g``'s best-first top-k
  values are ``topk_values[o[g]:o[g+1]]``; cross-payload merge is a k-way
  re-select over the concatenation (:func:`merge_topk_parts`).
* ``sketch_keys`` / ``sketch_counts`` / ``sketch_offsets`` — group ``g``'s
  occupied sketch buckets (ascending key order) and their counts; the
  cross-payload merge is bucket-count ADDITION (:func:`merge_sketch_parts`)
  — exactly the mergeable-histogram property the PR-2 metric histograms
  ride.

Sketch layout (DDSketch-style): ``gamma = (1+alpha)/(1-alpha)``; a
positive value ``v`` lands in bucket ``i = ceil(log(v)/log(gamma))``
(clamped to magnitudes in [SKETCH_MIN_MAGNITUDE, SKETCH_MAX_MAGNITUDE]),
carried as the signed key ``i - imin + 1`` (negated for negative values,
0 for zeros/tiny values); the bucket representative ``2*gamma^i/(gamma+1)``
is within relative error ``alpha`` of any value in the bucket.  The
quantile estimate returns the representative of the bucket holding the
LOWER order statistic at rank ``floor(q*(n-1))``, so its relative error vs
the exact ``quantile(..., interpolation='lower')`` is <= alpha inside the
clamped magnitude range (the documented bound; README "Relational
operators").

This module is import-light (NumPy only): the CLIENT uses its merge /
finalize helpers through :mod:`bqueryd_tpu.parallel.hostmerge`, so nothing
here may import JAX at module scope — device kernels live in
:mod:`bqueryd_tpu.ops.relops` and are imported lazily on the worker's
device route only.
"""

import contextlib
import math

import numpy as np

from bqueryd_tpu.models.query import (
    MERGEABLE_OPS,
    ResultPayload,
    _group_distinct_flat,
    _segment_local_arange,
    _value_kind_for,
)
from bqueryd_tpu.plan.dag import DagValidationError, parse_op

#: datetime null sentinel (NaT as int64)
NAT_SENTINEL = np.iinfo(np.int64).min

#: sketch magnitude clamp: values below the min collapse into the zero
#: bucket, values above the max into the edge bucket (error bound holds
#: only inside the range — documented in the README)
SKETCH_MIN_MAGNITUDE = 1e-12
SKETCH_MAX_MAGNITUDE = 1e15


# -- sketch math (shared by the host kernels, the device twins' wrappers,
# -- and the client-side merge/finalize) --------------------------------------

def sketch_layout(alpha):
    """``(gamma, log_gamma, imin, imax)`` of the fixed bucket layout for a
    given relative accuracy — a pure function of ``alpha``, so every shard
    and worker bins into the SAME buckets and the merge is key-aligned
    addition with no coordination."""
    alpha = float(alpha)
    gamma = (1.0 + alpha) / (1.0 - alpha)
    lg = math.log(gamma)
    imin = math.floor(math.log(SKETCH_MIN_MAGNITUDE) / lg)
    imax = math.ceil(math.log(SKETCH_MAX_MAGNITUDE) / lg)
    return gamma, lg, imin, imax


def sketch_keys_host(values, alpha):
    """Signed bucket key per value (int64; caller excludes NaN/null rows).
    Key 0 = zero/tiny bucket; +/-(i - imin + 1) for positive/negative
    magnitudes in bucket ``i``."""
    _gamma, lg, imin, imax = sketch_layout(alpha)
    v = np.asarray(values, dtype=np.float64)
    mag = np.abs(v)
    tiny = mag < SKETCH_MIN_MAGNITUDE
    with np.errstate(divide="ignore", invalid="ignore"):
        i = np.ceil(np.log(np.where(tiny, 1.0, mag)) / lg)
    i = np.clip(i, imin, imax).astype(np.int64)
    unsigned = i - np.int64(imin) + 1
    return np.where(
        tiny, np.int64(0), np.where(v < 0, -unsigned, unsigned)
    )


def sketch_key_values(keys, alpha):
    """Representative value per signed bucket key (float64)."""
    gamma, _lg, imin, _imax = sketch_layout(alpha)
    keys = np.asarray(keys, dtype=np.int64)
    i = np.abs(keys) - 1 + imin
    mag = 2.0 * np.power(float(gamma), i.astype(np.float64)) / (gamma + 1.0)
    return np.where(keys == 0, 0.0, np.where(keys < 0, -mag, mag))


def sketch_flat(codes, values, n_groups, mask=None, alpha=0.01,
                keys=None):
    """Per-(group, bucket) counts in flat form ``(keys, counts, offsets)``:
    group ``g`` occupies ``keys[o[g]:o[g+1]]`` (ascending) with counts
    aligned.  ``keys=`` lets the device route pass pre-binned keys (the
    jitted elementwise kernel); NaN values are dropped (pandas quantile
    skipna)."""
    codes = np.asarray(codes)
    v = np.asarray(values, dtype=np.float64)
    valid = codes >= 0
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=bool)
    valid = valid & ~np.isnan(v)
    g = codes[valid].astype(np.int64)
    k = (
        sketch_keys_host(v[valid], alpha)
        if keys is None
        else np.asarray(keys, dtype=np.int64)[valid]
    )
    _gamma, _lg, imin, imax = sketch_layout(alpha)
    span = np.int64(2 * (imax - imin + 1) + 1)
    kmin = np.int64(-(imax - imin + 1))
    pair = g * span + (k - kmin)
    uniq, counts = np.unique(pair, return_counts=True)
    g_of = uniq // span
    k_of = uniq % span + kmin
    offsets = np.searchsorted(g_of, np.arange(n_groups + 1)).astype(np.int64)
    return k_of.astype(np.int64), counts.astype(np.int64), offsets


def sketch_grid_layout(alpha):
    """``(width, kmin)`` of the DENSE signed-bucket grid for one alpha:
    column ``j`` of a ``[groups, width]`` grid holds bucket key
    ``kmin + j`` (negative magnitudes, the zero bucket, positive
    magnitudes).  A pure function of ``alpha`` — every device of the mesh
    fast path scatters into the SAME static grid, so the cross-device
    merge is one reduce-scatter of bucket-count additions."""
    _gamma, _lg, imin, imax = sketch_layout(alpha)
    half = imax - imin + 1
    return 2 * half + 1, -half


def sketch_grid_to_flat(grid, kmin):
    """Dense ``[groups, width]`` bucket-count grid -> the flat mergeable
    form ``(keys, counts, offsets)``.  Row-major ``nonzero`` yields each
    group's occupied buckets in ascending key order — exactly the layout
    :func:`sketch_flat` / :func:`merge_sketch_parts` emit, so a device-
    merged grid converts to a flat part bit-identical to the host path's
    (zero cells simply vanish)."""
    grid = np.asarray(grid)
    g, col = np.nonzero(grid)
    keys = col.astype(np.int64) + np.int64(kmin)
    counts = grid[g, col].astype(np.int64)
    offsets = np.searchsorted(
        g, np.arange(grid.shape[0] + 1)
    ).astype(np.int64)
    return keys, counts, offsets


def merge_sketch_parts(parts, n_global):
    """Bucket-count ADDITION across payloads.  ``parts`` is
    ``[(local_map, keys, counts, offsets), ...]``; returns the merged flat
    ``(keys, counts, offsets)`` over ``n_global`` aligned groups."""
    gid_chunks, key_chunks, cnt_chunks = [], [], []
    for local_map, keys, counts, offsets in parts:
        keys = np.asarray(keys, dtype=np.int64)
        if len(keys) == 0:
            continue
        per_group = np.diff(np.asarray(offsets))
        gid_chunks.append(
            np.repeat(np.asarray(local_map, dtype=np.int64), per_group)
        )
        key_chunks.append(keys)
        cnt_chunks.append(np.asarray(counts, dtype=np.int64))
    if not gid_chunks:
        return (
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.int64),
            np.zeros(n_global + 1, dtype=np.int64),
        )
    gids = np.concatenate(gid_chunks)
    keys = np.concatenate(key_chunks)
    counts = np.concatenate(cnt_chunks)
    kmin = np.int64(keys.min())
    span = np.int64(keys.max()) - kmin + 1
    pair = gids * span + (keys - kmin)
    uniq, inv = np.unique(pair, return_inverse=True)
    summed = np.zeros(len(uniq), dtype=np.int64)
    np.add.at(summed, inv, counts)
    g_of = uniq // span
    k_of = uniq % span + kmin
    offsets = np.searchsorted(g_of, np.arange(n_global + 1)).astype(np.int64)
    return k_of.astype(np.int64), summed, offsets


def sketch_quantiles(keys, counts, offsets, q, alpha):
    """Per-group quantile estimates from a merged flat sketch (float64;
    NaN for empty groups).  Targets the LOWER order statistic at rank
    ``floor(q*(n-1))`` — the comparator the documented <= alpha relative
    error bound is stated against."""
    keys = np.asarray(keys, dtype=np.int64)
    counts = np.asarray(counts, dtype=np.int64)
    offsets = np.asarray(offsets, dtype=np.int64)
    n_groups = len(offsets) - 1
    out = np.full(n_groups, np.nan)
    if len(keys) == 0:
        return out
    cc = np.cumsum(counts)
    starts, ends = offsets[:-1], offsets[1:]
    nonempty = ends > starts
    base = np.where(starts > 0, cc[np.maximum(starts, 1) - 1], 0)
    tot = np.where(nonempty, cc[np.maximum(ends, 1) - 1] - base, 0)
    rank = np.floor(float(q) * np.maximum(tot - 1, 0)).astype(np.int64)
    target = base + rank + 1
    j = np.searchsorted(cc, target, side="left")
    j = np.minimum(j, len(keys) - 1)
    vals = sketch_key_values(keys, alpha)
    out[nonempty] = vals[j[nonempty]]
    return out


# -- top-k math ---------------------------------------------------------------

def topk_select(gids, values, k, largest, n_groups):
    """Per-group top-k of (group id, value) pairs, flat form: ``(values,
    offsets)`` with group ``g``'s values BEST-FIRST (descending for
    largest, ascending for smallest).  The same selection serves the
    per-shard partial and the cross-payload k-way re-select, so a merge of
    merges is associative by construction."""
    gids = np.asarray(gids, dtype=np.int64)
    values = np.asarray(values)
    order = np.lexsort((values, gids))
    g = gids[order]
    v = values[order]
    counts = np.bincount(g, minlength=n_groups)
    take = np.minimum(counts, int(k))
    ends = np.cumsum(counts)
    rep = np.repeat(np.arange(n_groups, dtype=np.int64), take)
    loc = _segment_local_arange(take)
    if largest:
        idx = ends[rep] - 1 - loc
    else:
        idx = (ends - counts)[rep] + loc
    offsets = np.zeros(n_groups + 1, dtype=np.int64)
    np.cumsum(take, out=offsets[1:])
    return v[idx], offsets


def topk_flat(codes, values, k, largest, n_groups, mask=None, sentinel=None):
    """Per-shard top-k partial over raw rows: drops null keys, masked
    rows, NaNs, and sentinel nulls (datetime NaT), then selects."""
    codes = np.asarray(codes)
    v = np.asarray(values)
    valid = codes >= 0
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=bool)
    if sentinel is not None:
        valid = valid & (v != np.asarray(sentinel, dtype=v.dtype))
    if np.issubdtype(v.dtype, np.floating):
        valid = valid & ~np.isnan(v)
    return topk_select(
        codes[valid].astype(np.int64), v[valid], k, largest, n_groups
    )


def dense_topk_to_flat(dense, counts):
    """Dense best-first ``[groups, k]`` + per-group counts -> the flat
    mergeable form ``(values, offsets)``: group ``g`` keeps its first
    ``counts[g]`` slots.  Shared by the device kernel's host compaction
    (``ops.relops.topk_partials``) and the mesh fast path's collect."""
    dense = np.asarray(dense)
    take = np.asarray(counts, dtype=np.int64)
    n = len(take)
    rep = np.repeat(np.arange(n, dtype=np.int64), take)
    loc = _segment_local_arange(take)
    flat = dense[rep, loc] if len(rep) else dense[:0, 0]
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(take, out=offsets[1:])
    return flat, offsets


def merge_topk_parts(parts, k, largest, n_global):
    """K-way re-select across payloads: concatenate each group's flat
    top-k lists and re-select the global top-k."""
    gid_chunks, val_chunks = [], []
    for local_map, values, offsets in parts:
        values = np.asarray(values)
        if len(values) == 0:
            continue
        per_group = np.diff(np.asarray(offsets))
        gid_chunks.append(
            np.repeat(np.asarray(local_map, dtype=np.int64), per_group)
        )
        val_chunks.append(values)
    if not gid_chunks:
        return np.empty(0), np.zeros(n_global + 1, dtype=np.int64)
    return topk_select(
        np.concatenate(gid_chunks), np.concatenate(val_chunks),
        k, largest, n_global,
    )


def dim_measure_kind(dtype):
    """``(null_sentinel, value_kind)`` of a join-selected measure column by
    dtype — the ONE copy of the dtype rules ('datetime'/NaT, 'uint64',
    'uint') shared by the per-shard route (:meth:`DagExecutor.
    _measure_values`) and the mesh fast path (``executor.execute_dag``):
    bit-parity between the two legs depends on these agreeing."""
    dtype = np.dtype(dtype)
    if dtype.kind == "M":
        return NAT_SENTINEL, "datetime"
    if dtype == np.dtype(np.uint64):
        return None, "uint64"
    if dtype.kind == "u":
        return None, "uint"
    return None, None


def gathered_dim_values(dim_column, row_pos):
    """Dimension column broadcast onto fact rows via the probe gather
    (garbage where unmatched — those rows carry null codes and drop from
    every reduction); datetime rides as raw int64 with the NaT sentinel.
    Shared by both DAG routes, like :func:`dim_measure_kind`."""
    v = np.asarray(dim_column)[np.maximum(row_pos, 0)]
    if v.dtype.kind == "M":
        v = v.astype("datetime64[ns]").view(np.int64)
    return v


def filter_flat(values_by_key, offsets, present):
    """Row-filter flat per-group arrays to the ``present`` groups (the
    generic form of ``models.query.filter_distinct_part``, shared by every
    flat part kind)."""
    offsets = np.asarray(offsets)
    counts = np.diff(offsets)
    sel = counts[present]
    starts = offsets[:-1][present]
    idx = np.repeat(starts, sel) + _segment_local_arange(sel)
    new_offsets = np.zeros(len(sel) + 1, dtype=np.int64)
    np.cumsum(sel, out=new_offsets[1:])
    return (
        {key: np.asarray(v)[idx] for key, v in values_by_key.items()},
        new_offsets,
    )


# -- finalize (client-side, via hostmerge.finalize_table) --------------------

def finalize_topk(agg, vkind=None):
    """Flat top-k part -> object array of per-group best-first value
    arrays (datetime measures ride as int64 and re-view here)."""
    values = np.asarray(agg["topk_values"])
    offsets = np.asarray(agg["topk_offsets"])
    if vkind == "datetime":
        values = values.astype(np.int64).view("datetime64[ns]")
    n = len(offsets) - 1
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = values[offsets[i]:offsets[i + 1]]
    return out


def finalize_quantile(agg, op):
    """Flat sketch part -> per-group quantile estimates for the op string
    ``quantile:<q>:<alpha>``."""
    parsed = parse_op(op)
    return sketch_quantiles(
        agg["sketch_keys"], agg["sketch_counts"], agg["sketch_offsets"],
        parsed[1], parsed[2],
    )


# -- per-shard execution ------------------------------------------------------

class _ShardState:
    """Resolved derivations of one shard: the join gather positions and
    the window bucket ints, plus memoized value/code views per column."""

    __slots__ = ("table", "dag", "row_pos", "window_ints", "_values", "_codes")

    def __init__(self, table, dag):
        self.table = table
        self.dag = dag
        self.row_pos = None       # int64[n] dim-row per fact row, -1 = miss
        self.window_ints = None   # int64[n] bucket ns, NAT_SENTINEL = null
        self._values = {}
        self._codes = {}


class DagExecutor:
    """Executes extended operator DAGs per shard and merges host-side.

    Plain DAGs never reach this class — the worker routes them through
    the unchanged engine path (``OperatorDAG.plain_groupby_query``), which
    is what keeps plain groupbys bit-identical.  The executor shares the
    engine's factorize cache (join keys and fact group keys factorize
    once per shard per column, like any groupby)."""

    def __init__(self, engine):
        self.engine = engine
        self.timer = None
        #: post-guard kernel route of the classic GroupAgg partials
        #: ("host" or the device route), surfaced as effective_strategy
        self.last_effective_strategy = None
        #: "none" (single payload) or "host" (value-keyed cross-shard merge)
        self.last_merge_mode = None
        #: per-shard (decoded, skipped) chunk-prune counts of the last
        #: execute() (list.append is atomic — shards run on the pool);
        #: the worker folds the totals into its chunk counters
        self._prune_counts = []

    def _phase(self, name):
        if self.timer is None:
            return contextlib.nullcontext()
        return self.timer.phase(name)

    # -- public -------------------------------------------------------------
    def execute(self, tables, dag, timer=None):
        """One payload per CalcMessage: per-shard operator pipelines on the
        PR-4 stage pool, host value-keyed merge across shards."""
        from bqueryd_tpu.parallel import hostmerge, pipeline

        self.timer = timer
        self.last_effective_strategy = None
        self._prune_counts = []
        payloads = pipeline.map_ordered(
            lambda t: self.execute_shard(t, dag), tables
        )
        if len(payloads) == 1:
            self.last_merge_mode = "none"
            return payloads[0]
        self.last_merge_mode = "host"
        with self._phase("hostmerge"):
            merged = hostmerge.merge_payloads(payloads)
        return ResultPayload(merged)

    # -- derivations --------------------------------------------------------
    def _device_eligible(self, n_rows):
        from bqueryd_tpu.models.query import host_kernel_rows
        from bqueryd_tpu.utils import devicehealth

        return not devicehealth.backend_wedged() and n_rows > host_kernel_rows()

    def _probe_join(self, state, mask):
        """Factorize the fact join key, hash the (small) dimension key
        once, and probe per row as a gather — device-routed behind the
        same latency guards as every kernel."""
        join = state.dag.join
        table = state.table
        if join.on not in table:
            raise DagValidationError(
                f"join key {join.on!r} is not a column of the fact shard"
            )
        codes, uniques = self.engine._key_codes(table, join.on)
        codes = np.asarray(codes)
        uniques = np.asarray(uniques)
        dim_keys = np.asarray(join.table[join.right_on])
        if uniques.dtype == object or dim_keys.dtype == object:
            lookup = {v: i for i, v in enumerate(dim_keys.tolist())}
            pos_of_unique = np.fromiter(
                (lookup.get(v, -1) for v in uniques.tolist()),
                dtype=np.int64, count=len(uniques),
            )
        else:
            order = np.argsort(dim_keys, kind="stable")
            skeys = dim_keys[order]
            at = np.searchsorted(skeys, uniques)
            at = np.minimum(at, len(skeys) - 1)
            hit = skeys[at] == uniques
            pos_of_unique = np.where(hit, order[at], np.int64(-1))
        if len(pos_of_unique) == 0:
            pos_of_unique = np.zeros(1, dtype=np.int64) - 1
        if self._device_eligible(len(codes)):
            from bqueryd_tpu.ops import relops

            row_pos = relops.gather_positions(pos_of_unique, codes)
        else:
            row_pos = np.where(
                codes >= 0,
                pos_of_unique[np.maximum(codes, 0)],
                np.int64(-1),
            )
        state.row_pos = np.asarray(row_pos)
        matched = state.row_pos >= 0
        return matched if mask is None else (mask & matched)

    def _derive_window(self, state):
        window = state.dag.window
        table = state.table
        if window.column not in table:
            raise DagValidationError(
                f"window column {window.column!r} is not a column of the "
                f"fact shard"
            )
        if table.kind(window.column) != "datetime":
            raise DagValidationError(
                f"window column {window.column!r} is not a datetime column"
            )
        ints = np.asarray(table.column_raw(window.column)).astype(np.int64)
        null = ints == NAT_SENTINEL
        every = np.int64(window.every_ns)
        origin = np.int64(window.origin_ns)
        bucket = (ints - origin) // every * every + origin
        state.window_ints = np.where(null, np.int64(NAT_SENTINEL), bucket)

    # -- column resolution ---------------------------------------------------
    def _is_join_col(self, state, col):
        return state.dag.join is not None and col in state.dag.join.select

    def _is_window_col(self, state, col):
        return state.dag.window is not None and col == state.dag.window.alias

    def _gathered(self, state, col):
        """Dimension column broadcast onto fact rows via the probe gather
        (garbage where unmatched — those rows are masked out)."""
        hit = state._values.get(("join", col))
        if hit is None:
            dim = np.asarray(state.dag.join.table[col])
            pos = np.maximum(state.row_pos, 0)
            hit = dim[pos]
            state._values[("join", col)] = hit
        return hit

    def _measure_values(self, state, col):
        """Raw per-row measure values + null sentinel (datetime NaT)."""
        if self._is_window_col(state, col):
            return state.window_ints, NAT_SENTINEL, "datetime"
        if self._is_join_col(state, col):
            v = self._gathered(state, col)
            sentinel, kind = dim_measure_kind(v.dtype)
            if kind == "datetime":
                return (
                    v.astype("datetime64[ns]").view(np.int64),
                    sentinel, kind,
                )
            return v, sentinel, kind
        table = state.table
        if col not in table:
            raise DagValidationError(
                f"column {col!r} is not a fact column, a join-selected "
                f"column, or the window alias"
            )
        sentinel = (
            NAT_SENTINEL if table.kind(col) == "datetime" else None
        )
        return (
            np.asarray(table.column_raw(col)),
            sentinel,
            _value_kind_for(table, col),
        )

    def _key_codes_for(self, state, col):
        """``(codes, key_values)`` for one group-key column, any source."""
        hit = state._codes.get(col)
        if hit is not None:
            return hit
        if self._is_window_col(state, col):
            codes, uniq = _factorize_values(
                state.window_ints, null_value=NAT_SENTINEL
            )
            result = (codes, uniq.astype(np.int64).view("datetime64[ns]"))
        elif self._is_join_col(state, col):
            dim = np.asarray(state.dag.join.table[col])
            dcodes, duniq = _factorize_values(dim)
            codes = np.where(
                state.row_pos >= 0,
                dcodes[np.maximum(state.row_pos, 0)],
                np.int64(-1),
            )
            result = (codes, duniq)
        else:
            if col not in state.table:
                raise DagValidationError(
                    f"group key {col!r} is not a fact column, a "
                    f"join-selected column, or the window alias"
                )
            codes, uniq = self.engine._key_codes(state.table, col)
            result = (np.asarray(codes), np.asarray(uniq))
        state._codes[col] = result
        return result

    def _post_filter_values(self, state, col):
        """Per-row values for a post-derivation filter term (actual
        values, not physical codes — derived columns have no table
        dictionary to translate against)."""
        if self._is_window_col(state, col):
            return state.window_ints.view("datetime64[ns]")
        if self._is_join_col(state, col):
            return self._gathered(state, col)
        raise DagValidationError(
            f"post-derivation filter column {col!r} is neither "
            f"join-selected nor the window alias"
        )

    # -- shard execution -----------------------------------------------------
    def execute_shard(self, table, dag):
        from bqueryd_tpu import ops

        for in_col, op, _out in dag.aggs:
            kind = parse_op(op)[0]
            if kind in ("sum", "mean") and (
                in_col in table and table.kind(in_col) == "datetime"
            ):
                raise ValueError(
                    f"{kind!r} is not defined for datetime column {in_col!r}"
                )

        with self._phase("prune"):
            if dag.scan.pushdown:
                if not ops.shard_can_match(table, dag.scan.pushdown):
                    return ResultPayload.empty()
                # chunk-granular zone-map pruning on the PUSHDOWN terms
                # (pre-join fact predicates): joins/top-k/windows only
                # narrow rows further, so a chunk no pushdown row survives
                # contributes nothing to any downstream operator
                table, decoded, skipped = ops.chunk_pruned_table(
                    table, dag.scan.pushdown
                )
                if decoded or skipped:
                    self._prune_counts.append((decoded, skipped))
        state = _ShardState(table, dag)
        with self._phase("mask"):
            mask = ops.build_mask(table, dag.scan.pushdown)
            mask = None if mask is None else np.asarray(mask, dtype=bool)
        if dag.join is not None:
            with self._phase("join"):
                mask = self._probe_join(state, mask)
        if dag.window is not None:
            with self._phase("rollup"):
                self._derive_window(state)
        if dag.filter is not None and dag.filter.terms:
            with self._phase("mask"):
                for col, op, value in dag.filter.terms:
                    m = _eval_post_term(
                        self._post_filter_values(state, col), op, value
                    )
                    mask = m if mask is None else (mask & m)

        with self._phase("factorize"):
            per_key = [self._key_codes_for(state, c) for c in dag.group_keys]
            code_arrays = [np.asarray(c) for c, _ in per_key]
            key_values = [v for _, v in per_key]
            stacked = np.stack(
                [c.astype(np.int64) for c in code_arrays], axis=1
            )
            valid = (stacked >= 0).all(axis=1)
            view = np.ascontiguousarray(stacked[valid]).view(
                [("", np.int64)] * stacked.shape[1]
            ).ravel()
            uniq, inv = np.unique(view, return_inverse=True)
            dense = np.full(len(stacked), np.int64(-1))
            dense[valid] = inv
            combo_cols = uniq.view(np.int64).reshape(
                len(uniq), stacked.shape[1]
            )
            n_groups = max(len(uniq), 1)

        with self._phase("aggregate"):
            rows, agg_parts = self._aggregate(state, dense, n_groups, mask)

        with self._phase("collect"):
            present = rows > 0
            combos_present = np.flatnonzero(present)
            keys = {}
            for ci, (col, values) in enumerate(
                zip(dag.group_keys, key_values)
            ):
                idx = combo_cols[combos_present, ci]
                keys[col] = np.asarray(values)[idx]
            aggs = []
            for part in agg_parts:
                if "topk_offsets" in part:
                    vals, offs = filter_flat(
                        {"topk_values": part["topk_values"]},
                        part["topk_offsets"], present,
                    )
                    aggs.append({**vals, "topk_offsets": offs})
                elif "sketch_offsets" in part:
                    vals, offs = filter_flat(
                        {
                            "sketch_keys": part["sketch_keys"],
                            "sketch_counts": part["sketch_counts"],
                        },
                        part["sketch_offsets"], present,
                    )
                    aggs.append({**vals, "sketch_offsets": offs})
                elif "distinct_offsets" in part:
                    from bqueryd_tpu.models.query import filter_distinct_part

                    aggs.append(filter_distinct_part(part, present))
                else:
                    aggs.append({k: v[present] for k, v in part.items()})
            return ResultPayload.partials(
                key_cols=list(dag.group_keys),
                keys=keys,
                rows=np.asarray(rows)[present],
                aggs=aggs,
                ops=[a[1] for a in dag.aggs],
                out_cols=[a[2] for a in dag.aggs],
                value_kinds=self._value_kinds(state, dag),
            )

    def _value_kinds(self, state, dag):
        kinds = []
        for in_col, op, _out in dag.aggs:
            _v, _sentinel, kind = self._measure_values(state, in_col)
            parsed = parse_op(op)
            if parsed[0] == "quantile":
                kinds.append(None)  # sketches estimate in float64
            else:
                kinds.append(kind)
        return kinds

    def _aggregate(self, state, dense, n_groups, mask):
        """Per-node partial states: the classic GroupAgg rides the
        EXISTING kernels (host/device routed exactly like the engine);
        TopK and QuantileSketch ride their dedicated kernels (device twins
        in ops.relops behind the same guards)."""
        import jax

        from bqueryd_tpu import ops
        from bqueryd_tpu.models.query import host_kernel_rows

        dag = state.dag
        agg_parts = [None] * len(dag.aggs)
        device_ok = self._device_eligible(len(dense))

        mergeable, resolved = [], {}
        for i, (in_col, op, _out) in enumerate(dag.aggs):
            parsed = parse_op(op)
            values, sentinel, _kind = self._measure_values(state, in_col)
            resolved[i] = (values, sentinel)
            if parsed[0] in MERGEABLE_OPS:
                mergeable.append((i, parsed[0]))

        if mergeable:
            measures = tuple(resolved[i][0] for i, _ in mergeable)
            mops = tuple(op for _, op in mergeable)
            sentinels = tuple(resolved[i][1] for i, _ in mergeable)
            if device_ok:
                n_prog = ops.program_bucket(n_groups)
                np_measures = [np.asarray(m) for m in measures]
                self.last_effective_strategy = ops.kernel_route(
                    None, np_measures, mops, len(dense), n_prog
                )
                partials = jax.device_get(
                    ops.partial_tables(
                        dense.astype(np.int32), measures, mops, n_prog,
                        mask, null_sentinels=sentinels,
                    )
                )
                if n_prog != n_groups:
                    partials = jax.tree_util.tree_map(
                        lambda a: a[:n_groups], partials
                    )
            else:
                self.last_effective_strategy = "host"
                partials = ops.host_partial_tables(
                    dense.astype(np.int32), measures, mops, n_groups,
                    mask, null_sentinels=sentinels,
                )
            rows = np.asarray(partials["rows"])[:n_groups]
            for (i, _op), part in zip(mergeable, partials["aggs"]):
                agg_parts[i] = {
                    k: np.asarray(v)[:n_groups] for k, v in dict(part).items()
                }
        else:
            self.last_effective_strategy = "host"
            rows = np.asarray(
                ops.host_partial_tables(
                    dense.astype(np.int32), (), (), n_groups, mask
                )["rows"]
            )[:n_groups]

        for i, (in_col, op, _out) in enumerate(dag.aggs):
            parsed = parse_op(op)
            values, sentinel = resolved[i]
            if parsed[0] == "topk":
                _k, largest = parsed[1], parsed[2]
                v = np.asarray(values)
                if v.dtype == object or (
                    in_col in state.table
                    and state.table.kind(in_col) == "dict"
                ):
                    # dict columns surface as unordered dictionary CODES
                    # here — a top-k over them would rank ingestion order
                    raise DagValidationError(
                        f"topk measure {in_col!r} must be numeric or "
                        f"datetime, not strings"
                    )
                if device_ok:
                    from bqueryd_tpu.ops import relops

                    tvals, toffs = relops.topk_partials(
                        dense, v, parsed[1], largest, n_groups,
                        mask=mask, sentinel=sentinel,
                    )
                else:
                    tvals, toffs = topk_flat(
                        dense, v, parsed[1], largest, n_groups,
                        mask=mask, sentinel=sentinel,
                    )
                agg_parts[i] = {
                    "topk_values": tvals, "topk_offsets": toffs
                }
            elif parsed[0] == "quantile":
                _q, alpha = parsed[1], parsed[2]
                v = np.asarray(values)
                if (
                    v.dtype == object
                    or sentinel is not None
                    or (
                        in_col in state.table
                        and state.table.kind(in_col) == "dict"
                    )
                ):
                    raise DagValidationError(
                        f"quantile measure {in_col!r} must be numeric "
                        f"(strings/datetimes have no sketch ordering)"
                    )
                keys = None
                if device_ok:
                    from bqueryd_tpu.ops import relops

                    keys = relops.sketch_bin(v, alpha)
                skeys, scounts, soffs = sketch_flat(
                    dense, v, n_groups, mask=mask, alpha=alpha, keys=keys
                )
                agg_parts[i] = {
                    "sketch_keys": skeys,
                    "sketch_counts": scounts,
                    "sketch_offsets": soffs,
                }
            elif parsed[0] == "count_distinct":
                vcodes, vuniques = self._key_codes_for_values(state, in_col)
                dvalues, doffsets = _group_distinct_flat(
                    np.asarray(dense), np.asarray(vcodes),
                    np.asarray(vuniques), n_groups, mask,
                )
                agg_parts[i] = {
                    "distinct_values": dvalues,
                    "distinct_offsets": doffsets,
                }
            elif agg_parts[i] is None:
                raise DagValidationError(f"unsupported DAG op {op!r}")
        return rows, agg_parts

    def _key_codes_for_values(self, state, col):
        """Value codes for count_distinct over any column source (the
        group-key factorization machinery doubles as the value space)."""
        return self._key_codes_for(state, col)


# -- helpers ------------------------------------------------------------------

def _factorize_values(arr, null_value=None):
    """First-class-value factorize with pandas-style null poisoning:
    ``(codes[-1 for null], uniques)``.  Handles object arrays (None/NaN
    nulls), float NaN, datetime64 NaT, and an explicit int sentinel."""
    arr = np.asarray(arr)
    if arr.dtype == object:
        null = np.fromiter(
            (
                v is None or (isinstance(v, float) and math.isnan(v))
                for v in arr.tolist()
            ),
            dtype=bool, count=len(arr),
        )
    elif arr.dtype.kind == "f":
        null = np.isnan(arr)
    elif arr.dtype.kind == "M":
        null = np.isnat(arr)
    elif null_value is not None:
        null = arr == null_value
    else:
        null = None
    if null is not None and null.any():
        work = arr[~null]
        uniq, inv = np.unique(work, return_inverse=True)
        codes = np.full(len(arr), np.int64(-1))
        codes[~null] = inv.astype(np.int64)
        return codes, uniq
    uniq, inv = np.unique(arr, return_inverse=True)
    return inv.astype(np.int64), uniq


def _eval_post_term(values, op, value):
    """NumPy twin of ops.predicates.term_mask for derived columns (actual
    values; datetime comparisons coerce via numpy)."""
    values = np.asarray(values)
    if values.dtype.kind == "M" and not isinstance(value, np.datetime64):
        if isinstance(value, (list, tuple, set, frozenset)):
            value = [np.datetime64(v, "ns") for v in value]
        else:
            value = np.datetime64(value, "ns")
    if op == "==":
        return values == value
    if op == "!=":
        return values != value
    if op == "<":
        return values < value
    if op == "<=":
        return values <= value
    if op == ">":
        return values > value
    if op == ">=":
        return values >= value
    if op in ("in", "not in"):
        if values.dtype == object:
            members = set(value)
            hit = np.fromiter(
                (v in members for v in values.tolist()),
                dtype=bool, count=len(values),
            )
        else:
            hit = np.isin(values, np.asarray(list(value)))
        return hit if op == "in" else ~hit
    raise DagValidationError(f"unsupported where op {op!r}")
