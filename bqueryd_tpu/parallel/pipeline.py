"""Bounded shard-stage pipeline: one shared thread pool + stage accounting.

The per-shard data path is a sequence of host stages feeding one device
stage — storage decode -> factorize/align -> H2D ``device_put`` -> kernel
dispatch.  JAX's async dispatch already overlaps the device side for free;
what the serial code paths never exploited is that the HOST stages of shard
(or column) *i+1* can run while the device computes on *i*.  This module is
the shared substrate both exploit sites use:

* :func:`map_ordered` — run a stage function over shards on the bounded
  pool, results in input order (the contract
  ``hostmerge.merge_payloads`` and the mesh alignment both rely on);
* :func:`submit` / :func:`pool` — double-buffering seams (the executor
  keeps one column build in flight ahead of its H2D loop, and prefetches
  storage decode while alignment runs);
* :func:`stage` — wall-clock busy accounting per stage name (thread-safe,
  process-global).  Busy time sums across all pool threads, so a busy/wall
  ratio above the serial share proves CONCURRENT execution of stage work —
  intra-stage fan-out and cross-stage overlap both count; the clocks cannot
  distinguish the two.  bench.py's ``pipeline`` section reports the ratio
  (``overlap_ratio = host busy / wall``, the ISSUE's definition) alongside
  the serialized-vs-pipelined walls, which is the measurement that actually
  isolates what the pipeline buys; workers export the same clocks as
  ``bqueryd_tpu_pipeline_busy_seconds`` gauges.

One pool per process, sized by ``BQUERYD_TPU_PIPELINE_THREADS`` (default
``min(16, cpu)``; ``1`` serializes every stage — the bench's
serialized-stage baseline).  The env var is read per call and the pool transparently rebuilt
on a size change, so a live worker can be re-tuned (and the bench can
compare 1 vs default in one process) without restarts.  All stage work is
host-side decode/factorize/NumPy/H2D — the C++ chunk decode and numpy
release the GIL, so the pool scales on real cores without fighting the
interpreter.
"""

import contextlib
import os
import threading
import time

#: stages the busy clocks track (fixed so the worker can register one gauge
#: per stage up front; unknown names still accumulate, they just aren't
#: exported as metrics until added here)
STAGES = ("decode", "align", "h2d", "kernel", "merge")

#: matches the pre-pipeline alignment fan-out ceiling (the old _map_shards
#: capped at 16): the shared pool must not narrow cold alignment on big hosts
_DEFAULT_THREADS = min(16, os.cpu_count() or 4)


def pipeline_threads():
    """Pool width from ``BQUERYD_TPU_PIPELINE_THREADS`` (default
    ``min(16, cpu)``); 1 disables every pipeline overlap (serial stages),
    0/negative and unparseable values fall back to the default."""
    raw = os.environ.get("BQUERYD_TPU_PIPELINE_THREADS")
    if raw is None:
        return _DEFAULT_THREADS
    try:
        n = int(raw)
    except ValueError:
        import logging

        logging.getLogger("bqueryd_tpu").warning(
            "unparseable BQUERYD_TPU_PIPELINE_THREADS=%r, using default %d",
            raw, _DEFAULT_THREADS,
        )
        return _DEFAULT_THREADS
    return n if n >= 1 else _DEFAULT_THREADS


_pool_lock = threading.Lock()
_pool = None
_pool_width = None


def pool():
    """The process-wide pipeline ThreadPoolExecutor, (re)built to the
    current ``pipeline_threads()`` width.

    A replaced pool is NOT shut down: an in-flight ``map_ordered`` may
    still submit to it, and ``shutdown()`` would make that submit raise
    mid-query.  Its idle threads cost only memory until process exit
    (interpreter shutdown wakes and joins them), and resizes are rare
    operator events — width 1 never builds a pool at all, so the common
    serial<->default toggle leaks nothing."""
    global _pool, _pool_width
    width = pipeline_threads()
    with _pool_lock:
        if _pool is None or _pool_width != width:
            from concurrent.futures import ThreadPoolExecutor

            _pool = ThreadPoolExecutor(
                max_workers=width, thread_name_prefix="bq-pipeline"
            )
            _pool_width = width
        return _pool


def submit(fn, *args, **kwargs):
    """Submit one stage job; serial fallback (immediate call wrapped in a
    completed future) when the pipeline is pinned to one thread, so callers
    never build a one-thread pool just to preserve their code shape."""
    if pipeline_threads() <= 1:
        from concurrent.futures import Future

        f = Future()
        try:
            f.set_result(fn(*args, **kwargs))
        except BaseException as exc:  # Future carries it to .result()
            f.set_exception(exc)
        return f
    return pool().submit(fn, *args, **kwargs)


def map_ordered(fn, items, max_workers=None):
    """Map ``fn`` over ``items`` on the pipeline pool, returning results in
    input order (the deterministic-payload contract).  Runs serially when
    the effective width or the item count is 1.  ``max_workers`` only caps
    concurrency-in-flight; the shared pool itself is never resized here."""
    items = list(items)
    width = pipeline_threads()
    if max_workers is not None:
        width = min(width, int(max_workers))
    if len(items) <= 1 or width <= 1:
        return [fn(it) for it in items]
    # bound in-flight jobs to the effective width so one giant fan-out
    # cannot monopolize the shared pool against other queries' stages:
    # prime a window of `width` submissions, then collect sequentially,
    # launching the next item as each result is taken
    futures = {}
    results = [None] * len(items)
    next_idx = iter(range(len(items)))
    executor = pool()

    def launch():
        for i in next_idx:
            futures[i] = executor.submit(fn, items[i])
            return

    for _ in range(min(width, len(items))):
        launch()
    try:
        for i in range(len(items)):
            results[i] = futures.pop(i).result()
            launch()
    except BaseException:
        # the query already failed: still-queued shards must not burn the
        # SHARED pool against other queries (already-running ones finish —
        # cancel() cannot interrupt them)
        for fut in futures.values():
            fut.cancel()
        raise
    return results


class StageClock:
    """Thread-safe per-stage busy-seconds + call counts (process-global).

    Busy time is the SUM of wall time spent inside each stage across all
    threads — under overlap it legitimately exceeds the query wall, which is
    the measurement: ``overlap = busy(host stages) / wall`` > the serial
    share proves stages ran concurrently."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    #: (lock-unguarded-attr)
    _bqtpu_guarded_ = {"_lock": ("_busy", "_calls")}

    def __init__(self):
        self._lock = threading.Lock()
        self._busy = {}    # stage -> seconds
        self._calls = {}   # stage -> count

    def add(self, stage_name, seconds):
        with self._lock:
            self._busy[stage_name] = (
                self._busy.get(stage_name, 0.0) + float(seconds)
            )
            self._calls[stage_name] = self._calls.get(stage_name, 0) + 1

    def busy_seconds(self, stage_name):
        with self._lock:
            return self._busy.get(stage_name, 0.0)

    def snapshot(self):
        with self._lock:
            return {
                "busy_seconds": dict(self._busy),
                "calls": dict(self._calls),
            }

    def reset(self):
        """Bench/test seam: zero the clocks for a bracketed measurement."""
        with self._lock:
            self._busy.clear()
            self._calls.clear()


_clock = StageClock()


def clock():
    """The process-global :class:`StageClock`."""
    return _clock


@contextlib.contextmanager
def stage(name):
    """Time one stage occurrence into the global clock (always on — two
    dict updates under a lock per stage, far below the metrics hot-path
    budget; the obs kill switch gates span recording, not this)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _clock.add(name, time.perf_counter() - t0)
