"""Device-resident distributed merge over the ICI mesh.

The mesh executor's merge used to be an all-reduce: every device psummed the
FULL merged table and the host fetched one replicated copy — and its kill
path (and every non-mesh route) shipped whole per-shard partial tables to a
host for :func:`bqueryd_tpu.parallel.hostmerge.merge_payloads`.  Both shapes
move table-sized data for every participant.  This module is the
partition-then-collective replacement (*Theseus*' minimize-data-movement
rule; the partition-based cross-node aggregation of *A Fast, Scalable,
Universal Approach For Distributed Data Aggregations*):

* **key-span partitioning** — the global dense group codes are already one
  shared key space (the executor's host alignment), so the bucket layout is
  a static slice: device ``d`` of an ``n``-device mesh owns the contiguous
  span ``[d * span, (d + 1) * span)`` of the (padded) group axis
  (:func:`bucket_span`).  ``ops.bucketize_partials`` emits partial tables
  padded onto that layout behind the existing kernel guards.
* **collective merge** — inside the compiled mesh program, sum/count leaves
  merge with ``lax.psum_scatter`` (one reduce-scatter over the ``shards``
  axis: each device receives exactly its span, half the ICI traffic of the
  psum all-reduce) and min/max leaves with ``pmin``/``pmax`` + an own-span
  slice (:func:`scatter_merge_partials`).
* **D2H of the final table only** — the program's outputs are span-sized
  per device, so the only bytes that ever cross PCIe (or the tunnel) are
  the final merged table, fetched in parallel from all devices.  Per-shard
  partial tables never leave HBM.

``BQUERYD_TPU_DEVICE_MERGE=0`` is the kill switch: the executor then fetches
every device's partial table and merges them on the worker host with
``hostmerge.merge_payloads`` (the always-correct fallback), and the
controller stops batching shard groups so partials ride ZeroMQ per shard —
the reference's host-gather architecture, preserved as a measurable
baseline.  Multi-host meshes (``jax.process_count() > 1``) pin the
replicated-psum contract regardless: a span-sharded output is not
host-fetchable across processes.

Byte movement is accounted in :class:`MergeStats` (exported as the
``bqueryd_tpu_merge_*`` worker gauges and bench.py's ``merge`` section):
``bytes_fetched`` per mode, and ``d2h_bytes_saved`` — the per-device table
bytes the device-resident merge kept out of the fetch.

Import-light on purpose: the controller consults :func:`device_merge_enabled`
for its batching decision, so this module (like ``hostmerge``) must import
without JAX; collectives import it lazily inside the traced functions.
"""

import os
import threading

#: merge modes the mesh program traces (part of its cache key)
MODE_DEVICE = "device"   # reduce-scatter span ownership, span-only fetch
MODE_HOST = "host"       # fetch every device's partials, hostmerge on host
MODE_PSUM = "psum"       # all-reduce + replicated fetch (multi-host pods)


def device_merge_enabled():
    """The ``BQUERYD_TPU_DEVICE_MERGE`` kill switch (default on).  Off, the
    merge stays host-side end to end: the executor falls back to
    ``hostmerge.merge_payloads`` over per-device partials and the controller
    dispatches per shard instead of batching shard groups."""
    return os.environ.get("BQUERYD_TPU_DEVICE_MERGE", "1") != "0"


def resolve_mode():
    """The merge mode the mesh executor should trace for this query.

    ``device`` (default) / ``host`` (kill switch) on single-process
    backends; multi-host JAX jobs always get ``psum`` — each process can
    only fetch its addressable shards, so a span-sharded (or per-device)
    output is not host-materializable there and the replicated all-reduce
    remains the multi-host contract."""
    import jax

    if jax.process_count() > 1:
        return MODE_PSUM
    return MODE_DEVICE if device_merge_enabled() else MODE_HOST


def bucket_span(n_groups, n_devices):
    """Key-span partitioner: ``(span, padded_groups)`` for laying a
    ``n_groups``-wide table over ``n_devices`` contiguous owners.  Device
    ``d`` owns ``[d * span, (d + 1) * span)``; ``padded_groups ==
    span * n_devices >= n_groups`` and the pad tail holds no real group."""
    n_groups = max(int(n_groups), 1)
    n_devices = max(int(n_devices), 1)
    span = -(-n_groups // n_devices)
    return span, span * n_devices


def scatter_merge_partials(partials, axis_name, n_devices, span):
    """Merge bucketized partial tables across a mesh axis, span-owned.

    Runs INSIDE the shard_map program, per device: ``partials`` leaves are
    the padded flat ``[n_devices * span]`` tables from
    ``ops.bucketize_partials``.  Sum/count leaves reduce-scatter
    (``lax.psum_scatter``: one collective, each device keeps only its
    span's totals); min/max leaves have no scatter collective, so they
    all-reduce (``pmin``/``pmax``) and each device slices its own span —
    the OUTPUT is span-sized either way, which is what keeps the D2H fetch
    to exactly one final table.  Extends the ``ops.psum_partials``
    contract: elementwise merge rules per partial kind, now with placement.
    """
    from jax import lax

    idx = lax.axis_index(axis_name)

    def merge_leaf(kind, value):
        if kind in ("min", "max"):
            reduced = (lax.pmin if kind == "min" else lax.pmax)(
                value, axis_name
            )
            return lax.dynamic_slice(reduced, (idx * span,), (span,))
        return lax.psum_scatter(
            value, axis_name, scatter_dimension=0, tiled=True
        )

    rows = merge_leaf("rows", partials["rows"])
    aggs = tuple(
        {kind: merge_leaf(kind, value) for kind, value in part.items()}
        for part in partials["aggs"]
    )
    return {"rows": rows, "aggs": aggs}


def allgather_topk_merge(values, counts, axis_name, span, largest,
                         float_neg):
    """Cross-device merge of dense per-group top-k partials INSIDE the mesh
    program: all-gather the ``[padded_groups, k]`` dense tables + per-group
    counts, re-select the best ``k`` per group over the ``n_dev * k``
    candidates, and keep this device's own key span — ``[span, k]`` +
    ``[span]`` outputs, so (like the reduce-scattered classic partials)
    only final-table bytes ever leave HBM.

    The re-select is MULTISET-equal to the host k-way merge
    (``opexec.merge_topk_parts``): top-k payloads carry VALUES only, so
    which of several equal-valued candidates survives is unobservable.
    Validity rides a lexsort primary key (a dense slot is live iff its
    rank < its device's count), which is what lets the gathered zero-pad
    slots never shadow a real candidate.  ``span=None`` (the multi-host
    psum contract) skips the own-span slice and returns the full
    replicated merged table."""
    import jax.numpy as jnp
    from jax import lax

    k = int(values.shape[1])
    gathered = lax.all_gather(values, axis_name)     # [n_dev, G, k]
    gcounts = lax.all_gather(counts, axis_name)      # [n_dev, G]
    n_dev = int(gathered.shape[0])
    n_groups = int(gathered.shape[1])
    rank = jnp.arange(k, dtype=jnp.int64)
    valid = rank[None, None, :] < gcounts[:, :, None]
    cand = jnp.moveaxis(gathered, 0, 1).reshape(n_groups, n_dev * k)
    vmask = jnp.moveaxis(valid, 0, 1).reshape(n_groups, n_dev * k)
    if largest:
        sort_v = -cand if float_neg else ~cand
    else:
        sort_v = cand
    # primary key: validity (valid first); secondary: best-first value
    order = jnp.lexsort((sort_v, ~vmask), axis=-1)
    top = jnp.take_along_axis(cand, order[:, :k], axis=-1)
    cnt = jnp.minimum(gcounts.sum(axis=0), k)
    if span is None:
        return top, cnt
    start = lax.axis_index(axis_name) * span
    zero = jnp.zeros((), dtype=start.dtype)
    return (
        lax.dynamic_slice(top, (start, zero), (span, k)),
        lax.dynamic_slice(cnt, (start,), (span,)),
    )


def scatter_merge_grid(grid, axis_name, span):
    """Bucket-count ADDITION of dense per-(group, bucket) sketch grids
    across the mesh axis: one reduce-scatter over the padded group axis
    (span ownership, the :func:`scatter_merge_partials` contract) —
    ``[span, width]`` out per device.  ``span=None`` (multi-host) psums to
    the replicated full grid instead."""
    from jax import lax

    if span is None:
        return lax.psum(grid, axis_name)
    return lax.psum_scatter(grid, axis_name, scatter_dimension=0, tiled=True)


class MergeStats:
    """Process-wide merge byte-movement accounting (thread-safe): D2H bytes
    fetched per merge mode, queries per mode, and the per-device partial
    bytes the device-resident merge kept out of the fetch.  Process-global
    like the pipeline stage clocks — the worker owns the process's data
    path and exports these as the ``bqueryd_tpu_merge_*`` gauges."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    #: (lock-unguarded-attr)
    _bqtpu_guarded_ = {"_lock": ("_bytes_fetched", "_bytes_saved", "_queries")}

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes_fetched = {MODE_DEVICE: 0, MODE_HOST: 0}
        self._bytes_saved = 0
        self._queries = {MODE_DEVICE: 0, MODE_HOST: 0}

    def record(self, mode, fetched, saved=0):
        """One merged query: ``fetched`` D2H bytes under ``mode``; ``saved``
        is the counterfactual host-gather fetch minus the actual one
        (device-resident modes only).  The psum mode counts as ``device`` —
        the merge is device-resident, only the fetch is replicated."""
        key = MODE_HOST if mode == MODE_HOST else MODE_DEVICE
        with self._lock:
            self._bytes_fetched[key] += int(fetched)
            self._bytes_saved += max(int(saved), 0)
            self._queries[key] += 1

    def fetched(self, mode):
        with self._lock:
            return self._bytes_fetched.get(mode, 0)

    def saved(self):
        with self._lock:
            return self._bytes_saved

    def count(self, mode):
        with self._lock:
            return self._queries.get(mode, 0)

    def snapshot(self):
        with self._lock:
            return {
                "bytes_fetched": dict(self._bytes_fetched),
                "d2h_bytes_saved": self._bytes_saved,
                "queries": dict(self._queries),
            }

    def reset(self):
        """Bench/test seam: zero the counters for a bracketed measurement."""
        with self._lock:
            self._bytes_fetched = {MODE_DEVICE: 0, MODE_HOST: 0}
            self._bytes_saved = 0
            self._queries = {MODE_DEVICE: 0, MODE_HOST: 0}


_stats = MergeStats()


def stats():
    """The process-global :class:`MergeStats`."""
    return _stats
