"""Host-side (NumPy-only, JAX-free) merge of value-keyed result payloads.

This is the cross-worker half of the merge architecture: within a worker,
shard partials merge on-device over the ICI mesh (``ops.psum_partials``);
across workers — the DCN boundary — payloads carry actual key values, and this
module aligns and combines them on the host.  It deliberately imports no JAX
so the client and controller processes stay accelerator-free.

Replaces the reference's merge pipeline (controller tar-of-tars at reference
bqueryd/controller.py:186-211 + client-side re-groupby with every op forced to
'sum' at reference bqueryd/rpc.py:159-173), with two semantic fixes, flagged
per SURVEY.md §7.4:

* ``mean`` merges as (sum, count) -> weighted mean, not sum-of-shard-means;
* ``min``/``max`` merge as min/max, which the reference's forced-'sum' merge
  silently corrupted.

* ``count_distinct`` partials carry the per-group distinct VALUE SETS and
  merge by union, so values spanning shards/workers are counted once — the
  reference's forced-'sum' merge double-counts them.  (The deliberately
  additive exception is ``sorted_count_distinct``: run counts are local to
  each shard's sort order by definition.)
"""

import numpy as np

_MERGE_RULES = {
    "sum": np.add,
    "count": np.add,
    "distinct": np.add,
    "min": np.minimum,
    "max": np.maximum,
    "distinct_sets": "union",  # handled specially in _merge_partials
}


def merge_payloads(payloads):
    """Merge a list of ResultPayload dicts into one.

    Mixed kinds: 'empty' payloads are dropped; remaining payloads must agree
    on kind.  Returns a single payload dict (kind 'empty' if all were).
    """
    live = [p for p in payloads if p.get("kind") != "empty"]
    if not live:
        return {"format": "bqueryd-tpu-result-1", "kind": "empty"}
    kinds = {p["kind"] for p in live}
    if kinds == {"rows"}:
        return _merge_rows(live)
    if kinds == {"partials"}:
        return _merge_partials(live)
    raise ValueError(f"cannot merge mixed payload kinds: {sorted(kinds)}")


def _merge_rows(payloads):
    order = payloads[0]["order"]
    for p in payloads[1:]:
        if p["order"] != order:
            raise ValueError("row payloads have mismatched columns")
    columns = {
        col: np.concatenate([p["columns"][col] for p in payloads])
        for col in order
    }
    return {
        "format": payloads[0]["format"],
        "kind": "rows",
        "columns": columns,
        "order": order,
    }


def _merge_partials(payloads):
    first = payloads[0]
    key_cols = first["key_cols"]
    ops = first["ops"]
    out_cols = first["out_cols"]
    for p in payloads[1:]:
        if p["key_cols"] != key_cols or p["ops"] != ops or p["out_cols"] != out_cols:
            raise ValueError("partial payloads disagree on query shape")
    if len(payloads) == 1:
        return dict(first)

    # Align groups by key tuple: global index = first-seen order.
    index = {}
    group_of = []  # per payload: array mapping local group -> global group
    for p in payloads:
        key_arrays = [np.asarray(p["keys"][c]) for c in key_cols]
        local = np.empty(len(p["rows"]), dtype=np.int64)
        for g, key in enumerate(zip(*key_arrays)) if key_arrays else []:
            local[g] = index.setdefault(key, len(index))
        group_of.append(local)
    n_global = len(index)

    def scatter(rule, parts, dtype):
        if rule in (np.minimum, np.maximum):
            fill = (
                np.inf if rule is np.minimum else -np.inf
            ) if np.issubdtype(dtype, np.floating) else (
                np.iinfo(dtype).max if rule is np.minimum else np.iinfo(dtype).min
            )
            out = np.full(n_global, fill, dtype=dtype)
        else:
            out = np.zeros(n_global, dtype=dtype)
        for local_map, arr in parts:
            rule.at(out, local_map, arr)
        return out

    rows = scatter(
        np.add, [(g, np.asarray(p["rows"])) for g, p in zip(group_of, payloads)],
        np.int64,
    )
    aggs = []
    for ai in range(len(ops)):
        part_names = first["aggs"][ai].keys()
        merged = {}
        for pname in part_names:
            rule = _MERGE_RULES[pname]
            parts = [
                (g, np.asarray(p["aggs"][ai][pname]))
                for g, p in zip(group_of, payloads)
            ]
            if rule == "union":
                # bucket every payload's set per global group, then ONE
                # unique per group (incremental pairwise unions would re-sort
                # the accumulated set payload-count times)
                buckets = [[] for _ in range(n_global)]
                for local_map, arr in parts:
                    for g_local, g_global in enumerate(local_map):
                        buckets[g_global].append(arr[g_local])
                out = np.empty(n_global, dtype=object)
                for g, bucket in enumerate(buckets):
                    out[g] = (
                        np.unique(np.concatenate(bucket))
                        if bucket
                        else np.empty(0)
                    )
                merged[pname] = out
            else:
                merged[pname] = scatter(rule, parts, parts[0][1].dtype)
        aggs.append(merged)

    # global key arrays in first-seen order
    keys = {}
    key_tuples = list(index.keys())
    for ci, col in enumerate(key_cols):
        sample = np.asarray(first["keys"][col])
        keys[col] = np.array(
            [t[ci] for t in key_tuples],
            dtype=sample.dtype if sample.dtype != object else object,
        )
    return {
        "format": first["format"],
        "kind": "partials",
        "key_cols": key_cols,
        "keys": keys,
        "rows": rows,
        "aggs": aggs,
        "ops": ops,
        "out_cols": out_cols,
    }


def finalize_table(merged):
    """Finalize a merged payload into plain arrays:
    ``(order, {col: np.ndarray})``.  NumPy mirror of ``ops.finalize`` (kept
    in lockstep by tests/test_query_model.py::test_host_finalize_matches_device).

    (Callers wanting the reference's legacy sum-of-shard-means quirk finalize
    each payload separately and sum the means — see RPC's legacy_merge flag.)"""
    if merged["kind"] == "empty":
        return [], {}
    if merged["kind"] == "rows":
        return merged["order"], merged["columns"]

    out_cols = merged["out_cols"]
    order = list(merged["key_cols"]) + list(out_cols)
    columns = dict(merged["keys"])
    rows = merged["rows"]
    for agg, op, out_col in zip(merged["aggs"], merged["ops"], out_cols):
        if op == "mean":
            count = agg["count"]
            with np.errstate(invalid="ignore", divide="ignore"):
                values = np.where(
                    count > 0, agg["sum"] / np.maximum(count, 1), np.nan
                )
        elif op == "sum":
            values = agg["sum"]
        elif op in ("count", "count_na"):
            values = agg["count"]
        elif op == "count_distinct":
            values = np.fromiter(
                (len(s) for s in agg["distinct_sets"]),
                dtype=np.int64,
                count=len(agg["distinct_sets"]),
            )
        elif op == "sorted_count_distinct":
            values = agg["distinct"]
        elif op in ("min", "max"):
            values = agg[op]
            empty = agg["count"] == 0
            if np.issubdtype(values.dtype, np.floating):
                values = np.where(empty, np.nan, values)
            else:
                values = np.where(empty, 0, values)
        else:
            raise ValueError(f"cannot finalize op {op!r}")
        columns[out_col] = values

    present = rows > 0
    if not present.all():
        columns = {c: v[present] for c, v in columns.items()}
    return order, columns


def payload_to_dataframe(merged):
    """Final client-side conversion (pandas import isolated here)."""
    import pandas as pd

    order, columns = finalize_table(merged)
    if not order:
        return pd.DataFrame()
    return pd.DataFrame({c: columns[c] for c in order}, columns=order)
