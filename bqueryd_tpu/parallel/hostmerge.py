"""Host-side (NumPy-only, JAX-free) merge of value-keyed result payloads.

This is the cross-worker half of the merge architecture: within a worker,
shard partials merge on-device over the ICI mesh (``ops.psum_partials``);
across workers — the DCN boundary — payloads carry actual key values, and this
module aligns and combines them on the host.  It deliberately imports no JAX
so the client and controller processes stay accelerator-free.

Replaces the reference's merge pipeline (controller tar-of-tars at reference
bqueryd/controller.py:186-211 + client-side re-groupby with every op forced to
'sum' at reference bqueryd/rpc.py:159-173), with two semantic fixes, flagged
per SURVEY.md §7.4:

* ``mean`` merges as (sum, count) -> weighted mean, not sum-of-shard-means;
* ``min``/``max`` merge as min/max, which the reference's forced-'sum' merge
  silently corrupted.

* ``count_distinct`` partials carry the per-group distinct VALUE SETS and
  merge by union, so values spanning shards/workers are counted once — the
  reference's forced-'sum' merge double-counts them.  (The deliberately
  additive exception is ``sorted_count_distinct``: run counts are local to
  each shard's sort order by definition.)

Extended DAG part kinds (top-k flat lists, sketch bucket vectors) merge
here too — the k-way re-select and bucket-count addition below are the
documented FALLBACK the mesh fast path's device merge is parity-pinned
against (PR 15): batched DAG dispatches merge the same states on-device
(``parallel.devicemerge.allgather_topk_merge`` /
``scatter_merge_grid``), while per-shard dispatches
(``BQUERYD_TPU_DAG_BATCH=0``, count_distinct shapes, sub-threshold row
counts) and every cross-WORKER combine keep using this module.
"""

import numpy as np

from bqueryd_tpu.models.query import extremum_fill

_MERGE_RULES = {
    "sum": np.add,
    "count": np.add,
    "distinct": np.add,
    "min": np.minimum,
    "max": np.maximum,
    # ("distinct_values", "distinct_offsets") pairs — per-group distinct
    # value sets in flat form — are handled specially in _merge_partials
}


def merge_payloads(payloads):
    """Merge a list of ResultPayload dicts into one.

    Mixed kinds: 'empty' payloads are dropped; remaining payloads must agree
    on kind.  Returns a single payload dict (kind 'empty' if all were).
    """
    from bqueryd_tpu.utils.tracing import trace_span

    live = [p for p in payloads if p.get("kind") != "empty"]
    if not live:
        return {"format": "bqueryd-tpu-result-1", "kind": "empty"}
    kinds = {p["kind"] for p in live}
    # profiler-visible under BQUERYD_TPU_PROFILE=1 (tagged with the active
    # trace_id): the host-side half of the merge architecture shows up on
    # the same timeline as the device kernels it complements
    with trace_span("hostmerge"):
        if kinds == {"rows"}:
            return _merge_rows(live)
        if kinds == {"partials"}:
            return _merge_partials(live)
    raise ValueError(f"cannot merge mixed payload kinds: {sorted(kinds)}")


def _merge_rows(payloads):
    order = payloads[0]["order"]
    for p in payloads[1:]:
        if p["order"] != order:
            raise ValueError("row payloads have mismatched columns")
    columns = {
        col: np.concatenate([p["columns"][col] for p in payloads])
        for col in order
    }
    return {
        "format": payloads[0]["format"],
        "kind": "rows",
        "columns": columns,
        "order": order,
    }


def _align_groups(payloads, key_cols):
    """Vectorized global key alignment.

    Factorizes each key column over the concatenation of all payloads
    (``np.unique`` handles ints, floats, and string/object keys alike), folds
    the per-column codes into one composite code pairwise (re-factorizing
    after each fold keeps codes bounded by the row count, so the mixed-radix
    products cannot overflow int64), then renumbers the composite codes into
    first-seen order — the same global ordering the previous per-group
    Python-dict loop produced, at NumPy speed.

    Returns ``(group_of, n_global, global_keys)`` where ``group_of[i]`` maps
    payload *i*'s local groups to global group ids and ``global_keys`` are the
    per-column key values of each global group.
    """
    lengths = [len(p["rows"]) for p in payloads]
    offsets = np.cumsum([0] + lengths)
    total = offsets[-1]

    col_values = [   # concatenated raw key values per column
        np.concatenate([np.asarray(p["keys"][c]) for p in payloads])
        for c in key_cols
    ]
    combined = _pack_int_keys(col_values) if total else None
    if combined is not None:
        # all-integer keys with packable ranges: ONE unique over the packed
        # composite instead of a sort per column
        _uniq, combined = np.unique(combined, return_inverse=True)
        combined = combined.astype(np.int64, copy=False)
        n_comb = len(_uniq)
    else:
        for allv in col_values:
            uniq, inv = np.unique(allv, return_inverse=True)
            inv = inv.astype(np.int64, copy=False)
            if combined is None:
                combined, n_comb = inv, len(uniq)
            else:
                pair = combined * np.int64(len(uniq)) + inv
                uniq_pair, combined = np.unique(pair, return_inverse=True)
                combined = combined.astype(np.int64, copy=False)
                n_comb = len(uniq_pair)
        if combined is None:  # no key columns: everything is one group
            combined, n_comb = np.zeros(total, dtype=np.int64), min(1, total)

    # renumber into first-seen order (deterministic, matches dict semantics)
    first_pos = np.full(n_comb, total, dtype=np.int64)
    np.minimum.at(first_pos, combined, np.arange(total, dtype=np.int64))
    seen_order = np.argsort(first_pos, kind="stable")
    rank = np.empty(n_comb, dtype=np.int64)
    rank[seen_order] = np.arange(n_comb, dtype=np.int64)
    global_codes = rank[combined]

    rep_rows = first_pos[seen_order]  # one representative row per global group
    global_keys = {
        c: col_values[ci][rep_rows] for ci, c in enumerate(key_cols)
    }
    group_of = [
        global_codes[offsets[i]:offsets[i + 1]] for i in range(len(payloads))
    ]
    return group_of, n_comb, global_keys


def _pack_int_keys(col_values):
    """Mixed-radix-pack all-integer key columns into one int64 code array, or
    None when any column is non-integer or the range product could overflow."""
    if not col_values or not all(
        np.issubdtype(v.dtype, np.integer) for v in col_values
    ):
        return None
    mins = [int(v.min()) for v in col_values]
    maxs = [int(v.max()) for v in col_values]
    if any(m < -(1 << 63) or x >= (1 << 63) for m, x in zip(mins, maxs)):
        return None  # uint64 beyond int64 range: np.unique fallback handles it
    spans = [x - m + 1 for x, m in zip(maxs, mins)]
    capacity = 1
    for s in spans:
        capacity *= s
        if capacity >= (1 << 62):
            return None
    packed = np.zeros(len(col_values[0]), dtype=np.int64)
    for v, m, s in zip(col_values, mins, spans):
        packed *= np.int64(s)
        packed += v.astype(np.int64) - np.int64(m)
    return packed


def _merge_partials(payloads):
    first = payloads[0]
    key_cols = first["key_cols"]
    ops = first["ops"]
    out_cols = first["out_cols"]
    def _merge_kinds(a, b):
        # Shards may store the same column at different widths.  A uint64
        # shard merging with a NARROWER UNSIGNED sibling ('uint') keeps the
        # unsigned view — all sums are the same mod-2^64 bits.  A signed or
        # float sibling (None) makes the unsigned reinterpretation unsound
        # (pandas widens those mixes to float/int64), so that mix is
        # refused rather than silently corrupted.  'uint' next to a plain
        # numeric sibling needs no special finalize at all.  Datetime never
        # mixes with non-datetime (validated at execution).
        if a == b:
            return a
        pair = {a, b}
        if pair == {"uint64", "uint"}:
            return "uint64"
        if pair == {"uint", None}:
            return None
        raise ValueError("partial payloads disagree on query shape")

    value_kinds = first.get("value_kinds")
    for p in payloads[1:]:
        if (
            p["key_cols"] != key_cols
            or p["ops"] != ops
            or p["out_cols"] != out_cols
        ):
            raise ValueError("partial payloads disagree on query shape")
        theirs = p.get("value_kinds")
        if theirs != value_kinds:
            # a payload with no value_kinds at all (a worker running a
            # pre-kinds build during a rolling restart) means "no special
            # finalize anywhere" — merge as all-None and let _merge_kinds
            # decide per column, raising only on genuinely incompatible
            # kinds (uint64/datetime next to a plain numeric)
            if value_kinds is None:
                value_kinds = [None] * len(out_cols)
            if theirs is None:
                theirs = [None] * len(out_cols)
            value_kinds = [
                _merge_kinds(a, b) for a, b in zip(value_kinds, theirs)
            ]
    if len(payloads) == 1:
        return dict(first)

    return _merge_aligned(payloads, key_cols, ops, out_cols, value_kinds)


def collapse_partials(payload):
    """Collapse duplicate key tuples inside ONE partials payload.

    A freshly-executed partial has unique keys, but a payload whose key
    columns were *rewritten* — a window re-floored onto a coarser grid, a
    group-key column dropped (serve.subsume folds) — maps several stored
    groups onto the same key tuple.  Re-aggregating them is exactly the
    cross-shard merge with one payload, so this routes through the same
    value-kinds rules (_MERGE_RULES, extremum fills, distinct unions).
    """
    if payload.get("kind") != "partials" or not len(payload.get("rows", ())):
        return payload
    return _merge_aligned(
        [payload],
        payload["key_cols"],
        payload["ops"],
        payload["out_cols"],
        payload.get("value_kinds"),
    )


def _merge_aligned(payloads, key_cols, ops, out_cols, value_kinds):
    """Shape-validated merge core: align key tuples globally and combine
    every aggregation part under its merge rule."""
    first = payloads[0]
    group_of, n_global, global_keys = _align_groups(payloads, key_cols)

    def scatter(rule, parts, dtype):
        if rule in (np.minimum, np.maximum):
            fill = extremum_fill(
                dtype, "min" if rule is np.minimum else "max"
            )
            out = np.full(n_global, fill, dtype=dtype)
        else:
            out = np.zeros(n_global, dtype=dtype)
        for local_map, arr in parts:
            rule.at(out, local_map, arr)
        return out

    rows = scatter(
        np.add, [(g, np.asarray(p["rows"])) for g, p in zip(group_of, payloads)],
        np.int64,
    )
    aggs = []
    for ai in range(len(ops)):
        part_names = first["aggs"][ai].keys()
        merged = {}
        if "topk_offsets" in part_names:
            # per-group top-k lists merge by k-way RE-SELECT over the
            # concatenation (plan.dag TopK nodes; parallel.opexec owns the
            # selection so shard partials and this merge stay associative)
            from bqueryd_tpu.parallel import opexec
            from bqueryd_tpu.plan.dag import parse_op

            _kind, k, largest = parse_op(ops[ai])
            values, offsets = opexec.merge_topk_parts(
                [
                    (g, p["aggs"][ai]["topk_values"],
                     p["aggs"][ai]["topk_offsets"])
                    for g, p in zip(group_of, payloads)
                ],
                k, largest, n_global,
            )
            merged["topk_values"] = values
            merged["topk_offsets"] = offsets
            aggs.append(merged)
            continue
        if "sketch_offsets" in part_names:
            # quantile sketches merge by bucket-count ADDITION — the
            # mergeable-histogram property (plan.dag QuantileSketch)
            from bqueryd_tpu.parallel import opexec

            keys, counts, offsets = opexec.merge_sketch_parts(
                [
                    (g, p["aggs"][ai]["sketch_keys"],
                     p["aggs"][ai]["sketch_counts"],
                     p["aggs"][ai]["sketch_offsets"])
                    for g, p in zip(group_of, payloads)
                ],
                n_global,
            )
            merged["sketch_keys"] = keys
            merged["sketch_counts"] = counts
            merged["sketch_offsets"] = offsets
            aggs.append(merged)
            continue
        if "distinct_offsets" in part_names:
            flat_parts = [
                (g, p["aggs"][ai]["distinct_values"],
                 p["aggs"][ai]["distinct_offsets"])
                for g, p in zip(group_of, payloads)
            ]
            values, offsets = _union_distinct_flat(flat_parts, n_global)
            merged["distinct_values"] = values
            merged["distinct_offsets"] = offsets
        for pname in part_names:
            if pname in ("distinct_values", "distinct_offsets"):
                continue
            rule = _MERGE_RULES[pname]
            parts = [
                (g, np.asarray(p["aggs"][ai][pname]))
                for g, p in zip(group_of, payloads)
            ]
            # widen across payloads: shards may store the same column at
            # different widths, and adopting parts[0]'s dtype would
            # truncate a wider sibling's extrema into the fill range
            dtype = np.result_type(*[arr.dtype for _g, arr in parts])
            merged[pname] = scatter(rule, parts, dtype)
        aggs.append(merged)

    return {
        "format": first["format"],
        "kind": "partials",
        "key_cols": key_cols,
        "keys": global_keys,
        "rows": rows,
        "aggs": aggs,
        "ops": ops,
        "out_cols": out_cols,
        "value_kinds": value_kinds,
    }


def _union_distinct_flat(parts, n_global):
    """Union per-group distinct value sets across payloads, fully vectorized.

    ``parts`` is ``[(local_map, values, offsets), ...]`` in the flat
    per-group representation.  Expands each payload's offsets into global
    group ids, factorizes the values once (``np.unique`` also covers string
    values), dedupes (group, value) pairs via composite codes, and re-splits
    into one merged flat (values, offsets) — no per-group Python loop.
    """
    vals_chunks, gid_chunks = [], []
    for local_map, values, offsets in parts:
        values = np.asarray(values)
        if len(values) == 0:
            continue
        counts = np.diff(np.asarray(offsets))
        vals_chunks.append(values)
        gid_chunks.append(np.repeat(np.asarray(local_map), counts))
    if not vals_chunks:
        return np.empty(0), np.zeros(n_global + 1, dtype=np.int64)
    all_vals = np.concatenate(vals_chunks)
    all_gids = np.concatenate(gid_chunks)
    span = None
    if np.issubdtype(all_vals.dtype, np.integer):
        vmin = int(all_vals.min())
        vmax = int(all_vals.max())
        span = vmax - vmin + 1
        if n_global * span >= (1 << 62) or vmax >= (1 << 63):
            span = None  # overflow (incl. uint64 beyond int64): unique path
    if span is not None:
        # integer values with a packable range: dedupe (group, value) pairs
        # with ONE unique over packed codes, no value factorization sort
        pair = all_gids.astype(np.int64) * np.int64(span) + (
            all_vals.astype(np.int64) - np.int64(vmin)
        )
        uniq_pairs = np.unique(pair)
        merged_vals = (uniq_pairs % span + vmin).astype(all_vals.dtype)
        counts = np.bincount(uniq_pairs // span, minlength=n_global)
    else:
        uniq_vals, vinv = np.unique(all_vals, return_inverse=True)
        pair = all_gids.astype(np.int64) * np.int64(len(uniq_vals)) + vinv
        uniq_pairs = np.unique(pair)
        merged_vals = uniq_vals[uniq_pairs % len(uniq_vals)]
        counts = np.bincount(uniq_pairs // len(uniq_vals), minlength=n_global)
    offsets = np.zeros(n_global + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return merged_vals, offsets


def finalize_table(merged):
    """Finalize a merged payload into plain arrays:
    ``(order, {col: np.ndarray})``.  NumPy mirror of ``ops.finalize`` (kept
    in lockstep by tests/test_query_model.py::test_host_finalize_matches_device).

    (Callers wanting the reference's legacy sum-of-shard-means quirk finalize
    each payload separately and sum the means — see RPC's legacy_merge flag.)"""
    if merged["kind"] == "empty":
        return [], {}
    if merged["kind"] == "rows":
        return merged["order"], merged["columns"]

    out_cols = merged["out_cols"]
    order = list(merged["key_cols"]) + list(out_cols)
    columns = dict(merged["keys"])
    rows = merged["rows"]
    value_kinds = merged.get("value_kinds") or [None] * len(out_cols)
    for agg, op, out_col, vkind in zip(
        merged["aggs"], merged["ops"], out_cols, value_kinds
    ):
        if op == "mean":
            count = agg["count"]
            with np.errstate(invalid="ignore", divide="ignore"):
                values = np.where(
                    count > 0, agg["sum"] / np.maximum(count, 1), np.nan
                )
        elif op == "sum":
            values = agg["sum"]
            if vkind == "uint64":
                # every kernel accumulates mod 2^64; unsigned columns just
                # re-view the same bits (pandas keeps uint64 sums unsigned)
                values = np.asarray(values).astype(np.int64).view(np.uint64)
        elif op in ("count", "count_na"):
            values = agg["count"]
        elif op == "count_distinct":
            if "distinct" in agg:
                # sole-payload result: final counts computed on device
                values = np.asarray(agg["distinct"])
            else:
                values = np.diff(np.asarray(agg["distinct_offsets"]))
        elif op == "sorted_count_distinct":
            values = agg["distinct"]
        elif isinstance(op, str) and op.startswith("topk:"):
            # object array of per-group best-first value arrays
            from bqueryd_tpu.parallel import opexec

            values = opexec.finalize_topk(agg, vkind=vkind)
        elif isinstance(op, str) and op.startswith("quantile:"):
            from bqueryd_tpu.parallel import opexec

            values = opexec.finalize_quantile(agg, op)
        elif op in ("min", "max"):
            values = agg[op]
            empty = agg["count"] == 0
            if vkind == "datetime":
                # partials merged as raw int64; NaT (int64 min) for groups
                # whose values were all-NaT, then back to datetime64[ns]
                values = np.where(
                    empty, np.iinfo(np.int64).min, values.astype(np.int64)
                ).view("datetime64[ns]")
            elif np.issubdtype(values.dtype, np.floating):
                values = np.where(empty, np.nan, values)
            else:
                values = np.where(empty, 0, values)
        else:
            raise ValueError(f"cannot finalize op {op!r}")
        columns[out_col] = values

    present = rows > 0
    if not present.all():
        columns = {c: v[present] for c, v in columns.items()}
    return order, columns


def payload_to_dataframe(merged):
    """Final client-side conversion (pandas import isolated here).

    String data and the column index are built at OBJECT dtype explicitly:
    pandas 3 otherwise infers arrow-backed str arrays, whose construction
    (``ArrowStringArray._from_sequence``) null-derefs inside libarrow 25.0
    on some environments (observed: single-core hosts under this repo's
    benchmark) — and the reference returned object-dtype strings anyway."""
    import pandas as pd

    order, columns = finalize_table(merged)
    if not order:
        return pd.DataFrame()
    data = {}
    for c in order:
        v = columns[c]
        if getattr(v, "dtype", None) == object:
            data[c] = pd.Series(v, dtype=object)
        else:
            data[c] = v
    return pd.DataFrame(data, columns=pd.Index(order, dtype=object))
