"""Pallas TPU kernel for the groupby one-hot contraction.

The MXU groupby path (:mod:`bqueryd_tpu.ops.groupby`) reduces stacked bf16
rows (count flags, value limbs, float hi/lo pairs) against the one-hot of the
group codes.  XLA already fuses the one-hot formation into the dot operand;
this Pallas kernel makes that explicit and keeps the whole contraction in
VMEM: per grid step it DMAs one ``[R, K]`` row block plus one ``[K]`` code
block, forms ``[KT, G]`` one-hot tiles on the fly (broadcasted-iota compare —
never materialized to HBM), feeds the MXU, and accumulates the block's
``[R, G]`` partial in a float32 VMEM scratch.  Per-block partials stay below
2^24 (the caller bounds K * max-row-value), so the float32 accumulation is
exact and the caller's uint64 block reduction preserves bit-exact int64
sums — identical numerics to the XLA path by construction.

The kernel is traced with x64 disabled (Mosaic rejects the i64 loop/index
constants that x64 mode inserts) — safe because every operand is explicitly
i32/bf16/f32.

Usage is opt-in via ``BQUERYD_TPU_PALLAS=1`` (auto-interpret on CPU, where the
same kernel runs under the Pallas interpreter for test coverage).  On the
tunneled single-chip dev backend the XLA path measures within ~2x of the HBM
bandwidth floor already, so the default stays XLA; the Pallas path exists for
real multi-chip deployments where the fused formation saves the one-hot
regeneration VPU pass per dot and for cardinalities where the ``[nb, K, G]``
operand would otherwise spill.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: rows per grid block; must match ops.groupby._MATMUL_BLOCK so the caller's
#: exactness bound (block sums < 2^24) applies unchanged
BLOCK_K = 32768

#: sublane multiple for the stacked-rows operand.  16, not 8: the lhs block
#: is bf16, whose native Mosaic tile is (16, 128) — an 8-sublane bf16 block
#: relies on small-tile support that an older Mosaic may lack, and one row
#: of zero padding costs nothing
_SUBLANE = 16


def pallas_enabled():
    """Opt-in flag: BQUERYD_TPU_PALLAS=1 routes the groupby contraction
    through the Pallas kernel (interpreted on CPU backends)."""
    return os.environ.get("BQUERYD_TPU_PALLAS", "0") == "1"


def _enable_x64(flag):
    """Version-portable x64-mode context: ``jax.enable_x64`` (jax >= 0.5)
    with a fallback to its pre-0.5 ``jax.experimental`` home."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(flag)
    from jax.experimental import enable_x64 as legacy_enable_x64

    return legacy_enable_x64(flag)


def _round_up(x, mult):
    return -(-x // mult) * mult


def _make_kernel(n_rows, n_groups, tile_k):
    def kernel(codes_ref, lhs_ref, out_ref, acc_ref):
        acc_ref[:] = jnp.zeros_like(acc_ref)

        def body(kt, carry):
            off = kt * jnp.int32(tile_k)
            c = codes_ref[pl.ds(off, tile_k)]  # [KT] i32
            iota = lax.broadcasted_iota(jnp.int32, (tile_k, n_groups), 1)
            one_hot = (c[:, None] == iota).astype(jnp.bfloat16)  # [KT, G]
            lhs = lhs_ref[:, pl.ds(off, tile_k)]  # [R, KT] bf16
            acc_ref[:] += lax.dot_general(
                lhs,
                one_hot,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return carry

        lax.fori_loop(
            jnp.int32(0), jnp.int32(BLOCK_K // tile_k), body, jnp.int32(0)
        )
        out_ref[0] = acc_ref[:]

    return kernel


#: smallest inner K tile worth feeding the MXU; also sets the group-count
#: ceiling of the Pallas route (see :func:`pallas_groups_limit`)
_MIN_TILE = 128

#: bf16 one-hot tile budget in elements (~4 MB of the ~16 MB VMEM)
_ONEHOT_BUDGET = 1 << 21


def pallas_groups_limit():
    """Max group count the kernel can run without its smallest one-hot tile
    overflowing the VMEM budget: above this the caller must stay on the XLA
    path (which it does anyway past ``matmul_groups_limit`` unless the env
    knob raised it — the round-3 VMEM hole was exactly that combination)."""
    return _ONEHOT_BUDGET // _MIN_TILE


#: total VMEM the kernel may plan for (v5e has ~16 MB; leave headroom for
#: Mosaic's own buffers)
_VMEM_BUDGET_BYTES = 12 << 20


def fits_vmem(n_rows, n_groups):
    """Whether the kernel's working set fits the VMEM budget for this shape.

    The group ceiling alone is not enough: the f32 accumulator scratch and
    output block scale with ``n_rows * n_groups`` (many stacked limb rows at
    high cardinality can exhaust VMEM even under the one-hot ceiling), and
    the double-buffered lhs block with ``n_rows * BLOCK_K``."""
    if n_groups > pallas_groups_limit():
        return False
    rpad = _round_up(max(n_rows, 1), _SUBLANE)
    gpad = _round_up(max(n_groups, 1), 128)
    tile = _tile_k(gpad)
    need = (
        tile * gpad * 2            # bf16 one-hot tile
        + 2 * rpad * gpad * 4      # f32 accumulator scratch + output block
        + 2 * rpad * BLOCK_K * 2   # double-buffered bf16 lhs block
        + 2 * BLOCK_K * 4          # double-buffered i32 codes block
    )
    return need <= _VMEM_BUDGET_BYTES


def _tile_k(n_groups):
    """Largest inner K tile whose bf16 one-hot stays within ~4 MB of VMEM,
    shrinking to ``_MIN_TILE`` at high group counts.

    Restricted to powers of two so the tile always divides ``BLOCK_K`` —
    a non-divisor would truncate the block loop and silently drop rows."""
    budget = _ONEHOT_BUDGET // max(n_groups, 128)
    tile = _MIN_TILE
    while tile * 2 <= min(budget, 2048):
        tile *= 2
    return tile


def _call(codes_flat, lhs, n_rows, n_groups, interpret):
    nb = codes_flat.shape[0] // BLOCK_K
    tile = _tile_k(n_groups)
    return pl.pallas_call(
        _make_kernel(n_rows, n_groups, tile),
        out_shape=jax.ShapeDtypeStruct((nb, n_rows, n_groups), jnp.float32),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((BLOCK_K,), lambda b: (b,), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (n_rows, BLOCK_K), lambda b: (0, b), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, n_rows, n_groups),
            lambda b: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[pltpu.VMEM((n_rows, n_groups), jnp.float32)],
        interpret=interpret,
    )(codes_flat, lhs)


#: group-tile width of the high-cardinality kernel: one lane-multiple of
#: output groups computed per outer grid step.  Env-tunable for hardware
#: sweeps (a fresh process per setting: the values freeze into each traced
#: program signature).
def _hicard_gt():
    return int(os.environ.get("BQUERYD_TPU_PALLAS_HICARD_GT", 2048))


#: inner K tile of the high-cardinality kernel ([KT, GT] bf16 one-hot =
#: 2 MB VMEM at the defaults)
def _hicard_kt():
    return int(os.environ.get("BQUERYD_TPU_PALLAS_HICARD_KT", 512))

#: uint32 accumulator bound: every 8-bit limb row's TOTAL sum must stay
#: below 2^32 (limb values <= 255), so rows beyond this need the caller to
#: split the call or take another path
HICARD_MAX_ROWS = (1 << 32) // 256


def hicard_groups_limit():
    """Group-count ceiling of the high-cardinality kernel.  The one-hot
    contraction costs ``rows * groups`` MXU MACs; past a few hundred
    thousand groups the sort path wins back.  Tunable for hardware A/B
    (BQUERYD_TPU_PALLAS_HICARD_GROUPS)."""
    return int(
        os.environ.get("BQUERYD_TPU_PALLAS_HICARD_GROUPS", 1 << 18)
    )


def hicard_fits_vmem(n_rows):
    """Whether ``n_rows`` stacked reduction rows fit the high-cardinality
    kernel's VMEM plan under the current (env-tunable) tile sizes — the
    double-buffered lhs blocks dominate as the row count grows."""
    rpad = _round_up(max(n_rows, 1), _SUBLANE)
    kt, gt = _hicard_kt(), _hicard_gt()
    need = (
        kt * gt * 2                      # bf16 one-hot tile
        + rpad * gt * 4 * 2              # i32 out block (+revisit headroom)
        + 2 * rpad * BLOCK_K * 2         # double-buffered bf16 lhs block
        + 2 * BLOCK_K * 4                # double-buffered i32 codes block
    )
    return need <= _VMEM_BUDGET_BYTES


def _make_hicard_kernel(tile_k, gt):
    def kernel(codes_ref, lhs_ref, out_ref):
        # out block revisited across the inner (row-block) grid dim:
        # zero once, accumulate each block's exact f32 partial in int32
        @pl.when(pl.program_id(1) == 0)
        def _zero():
            out_ref[...] = jnp.zeros_like(out_ref)

        g0 = pl.program_id(0) * jnp.int32(gt)

        def body(kt, carry):
            off = kt * jnp.int32(tile_k)
            c = codes_ref[pl.ds(off, tile_k)]  # [KT] i32
            iota = g0 + lax.broadcasted_iota(jnp.int32, (tile_k, gt), 1)
            one_hot = (c[:, None] == iota).astype(jnp.bfloat16)
            lhs = lhs_ref[:, pl.ds(off, tile_k)]  # [R, KT] bf16
            part = lax.dot_general(
                lhs,
                one_hot,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # the K-tile partial is < 2^24 (tile_k * limb max 255), exact
            # in f32 and in the i32 convert; i32 accumulation wraps mod
            # 2^32, which the caller's uint32 bitcast recombination
            # absorbs (limb totals bounded by HICARD_MAX_ROWS * 255)
            out_ref[...] += part.astype(jnp.int32)
            return carry

        lax.fori_loop(
            jnp.int32(0), jnp.int32(BLOCK_K // tile_k), body, jnp.int32(0)
        )

    return kernel


@functools.partial(
    jax.jit, static_argnames=("n_rows", "n_groups", "interpret")
)
def onehot_rows_dot_hicard(codes, rows, n_rows, n_groups, interpret=False):
    """High-cardinality variant: ``out[r, g] = sum_k rows[r, k] *
    (codes[k] == g)`` with the block reduction performed IN-KERNEL in
    int32 (mod 2^32), so the output is ``[R, G]`` instead of the base
    kernel's per-block ``[nb, R, G]`` — at 70k+ groups the per-block
    partials would otherwise materialize gigabytes in HBM.

    INT rows only (count flags and 8-bit limbs, values <= 255): the mod-2^32
    accumulation is exact for them below ``HICARD_MAX_ROWS`` rows; float
    Dekker limbs have no wrap-free encoding here and must stay off this path.

    codes: int32[n] folded group codes (negative = contributes nowhere)
    rows:  bf16[R, n] stacked int reduction rows
    Returns uint32[R16, G128] limb totals mod 2^32 (R16/G128 rounded up to
    tile multiples — callers slice ``[:R, :G]`` and zero-extend to uint64).
    """
    n = codes.shape[0]
    if n > HICARD_MAX_ROWS:
        raise ValueError(
            f"n={n} exceeds HICARD_MAX_ROWS={HICARD_MAX_ROWS}: a limb "
            "total could wrap twice; split the call or use the sort path"
        )
    if not hicard_fits_vmem(n_rows):
        # the invariant lives here, not only in the dispatcher's boolean
        # (same rule as onehot_rows_dot): past this row count the lhs
        # double-buffer overflows VMEM and Mosaic's failure mode is an
        # opaque exhaustion
        raise ValueError(
            f"n_rows={n_rows} exceeds the hicard kernel's VMEM budget; "
            "use the scatter path"
        )
    npad = _round_up(max(n, 1), BLOCK_K)
    rpad = _round_up(n_rows, _SUBLANE)
    gt, kt = _hicard_gt(), _hicard_kt()
    if (
        kt < 128
        or gt < 128
        or BLOCK_K % kt != 0
        or gt % 128 != 0
    ):
        # sweep-knob hygiene: a non-divisor KT silently drops rows in the
        # inner loop; a non-lane-multiple GT breaks the output tiling.
        # Positivity first: the modulo checks themselves divide by kt
        raise ValueError(
            f"invalid hicard tiles KT={kt} (must divide {BLOCK_K}, "
            f">=128) / GT={gt} (must be a positive multiple of 128)"
        )
    gpad = _round_up(n_groups, gt)
    codes_p = jnp.pad(
        codes.astype(jnp.int32), (0, npad - n), constant_values=-1
    )
    rows_p = jnp.pad(
        rows.astype(jnp.bfloat16), ((0, rpad - n_rows), (0, npad - n))
    )
    nb = npad // BLOCK_K
    ngt = gpad // gt
    with _enable_x64(False):
        out = pl.pallas_call(
            _make_hicard_kernel(kt, gt),
            out_shape=jax.ShapeDtypeStruct((rpad, gpad), jnp.int32),
            # row-block dim innermost: the output block stays resident in
            # VMEM while the whole row range accumulates into it
            grid=(ngt, nb),
            in_specs=[
                pl.BlockSpec(
                    (BLOCK_K,), lambda g, b: (b,), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (rpad, BLOCK_K),
                    lambda g, b: (0, b),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (rpad, gt),
                lambda g, b: (0, g),
                memory_space=pltpu.VMEM,
            ),
            interpret=interpret,
        )(codes_p, rows_p)
    return lax.bitcast_convert_type(out, jnp.uint32)


@functools.partial(
    jax.jit, static_argnames=("n_rows", "n_groups", "interpret")
)
def onehot_rows_dot(codes, rows, n_rows, n_groups, interpret=False):
    """``out[b, r, g] = sum_k rows[r, b*K+k] * (codes[b*K+k] == g)``.

    codes: int32[n] folded group codes (negative = contributes nowhere)
    rows:  bf16[R, n] stacked reduction rows (R == n_rows)
    Returns float32[nb, R16, G128] where R16/G128 are R and n_groups rounded
    up to hardware tile multiples — callers slice ``[:, :R, :G]``.
    """
    if not fits_vmem(n_rows, n_groups):
        # the invariant lives here, not only in the dispatcher's boolean:
        # past this shape the working set overflows the VMEM budget, and
        # Mosaic's failure mode is an opaque exhaustion
        raise ValueError(
            f"n_rows={n_rows} x n_groups={n_groups} exceeds the Pallas "
            "kernel's VMEM budget; use the XLA path"
        )
    n = codes.shape[0]
    npad = _round_up(max(n, 1), BLOCK_K)
    rpad = _round_up(n_rows, _SUBLANE)
    gpad = _round_up(n_groups, 128)
    codes_p = jnp.pad(
        codes.astype(jnp.int32), (0, npad - n), constant_values=-1
    )
    rows_p = jnp.pad(
        rows.astype(jnp.bfloat16), ((0, rpad - n_rows), (0, npad - n))
    )
    with _enable_x64(False):
        return _call(codes_p, rows_p, rpad, gpad, interpret)


# compile/call accounting (obs.profile): the Pallas entry points land in the
# same jit-cache hit/miss counters and compile-seconds histogram as the XLA
# paths — the purity lint's jit-uninstrumented rule cross-checks this.  The
# wrapper passes straight through when called under an outer trace (the
# use_pallas route inside _partial_tables_mm), so instrumenting here never
# double-counts.
from bqueryd_tpu.obs import profile as _obsprofile  # noqa: E402

onehot_rows_dot = _obsprofile.instrument(
    "ops.pallas_onehot", onehot_rows_dot
)
onehot_rows_dot_hicard = _obsprofile.instrument(
    "ops.pallas_onehot_hicard", onehot_rows_dot_hicard
)
