"""Device kernels for the relational operators (plan.dag / parallel.opexec).

Three jitted entry points, each the device twin of a NumPy host kernel in
:mod:`bqueryd_tpu.parallel.opexec` (the host kernels are the reference
semantics, exactly like ``host_partial_tables`` is for the groupby
kernels) and each behind the SAME guards as every other kernel: the
executor routes here only above ``models.query.host_kernel_rows`` and
never on a wedged backend.

* :func:`gather_positions` — the broadcast hash-join probe: one gather of
  per-distinct-key dimension positions onto rows (the join's per-row work
  is exactly a gather once the join key is factorized).
* :func:`topk_partials` — per-group top-k via the sort route: one
  ``lexsort`` by (group, value-desc), within-group rank from a
  ``searchsorted`` against the sorted codes, rank-< k scatter into a
  dense ``[groups, k]`` buffer (group dimension bucketed through
  ``program_bucket`` for program reuse), compacted host-side into the
  flat mergeable form.
* :func:`sketch_bin` — the quantile sketch's elementwise bucket-key
  computation (the only per-row work a sketch does); the per-(group,
  bucket) pairing stays host-side in ``opexec.sketch_flat``.

All three are compile-profiled (PR-3 ``profile.instrument``) so their
programs land in the per-shape registry and jit-cache accounting like
every other kernel.

The mesh fast path (PR 15, ``executor._mesh_dag_program``) composes the
TRACE-TIME bodies directly inside one compiled program:
:func:`topk_dense_emit` (a static route over the ``lax.top_k``-free
matrix-argmax, segment k-pass, and lexsort emissions — all value-multiset
identical) and :func:`sketch_grid_block` (the dense per-(group, bucket)
count grid whose cross-device merge is one reduce-scatter addition).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from bqueryd_tpu.obs import profile as _obsprofile
from bqueryd_tpu.parallel.opexec import (
    SKETCH_MIN_MAGNITUDE,
    sketch_layout,
)
from bqueryd_tpu.ops.groupby import program_bucket


@jax.jit
def _gather_positions(pos_of_unique, codes):
    safe = jnp.maximum(codes, 0)
    return jnp.where(
        codes >= 0, pos_of_unique[safe], jnp.int64(-1)
    )


_gather_positions = _obsprofile.instrument(
    "ops.relops_gather", _gather_positions
)


def gather_positions(pos_of_unique, codes):
    """Join probe on device: ``row_pos[i] = pos_of_unique[codes[i]]`` with
    null codes mapped to -1 (miss)."""
    return np.asarray(
        jax.device_get(
            _gather_positions(
                jnp.asarray(pos_of_unique, dtype=jnp.int64),
                jnp.asarray(codes),
            )
        )
    )


#: per-group k at or below which the top-k emission takes the k-pass
#: segment route (k linear segment reductions) instead of the rows-scale
#: lexsort — the crossover where O(k*n) beats O(n log n) with sort's
#: constant factor
TOPK_KPASS_MAX_K = 32


def _topk_validity(codes, values, mask, drop_nan, sentinel):
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    if sentinel is not None:
        valid = valid & (values != sentinel)
    if drop_nan:
        valid = valid & ~jnp.isnan(values)
    return valid


def topk_kpass_block(codes, values, mask, k, largest, n_groups, drop_nan,
                     sentinel):
    """Dense per-group top-k via k SEGMENT passes — O(k*n), no rows-scale
    sort: each round takes the per-group extremum of the still-alive rows
    (masked rows carry the reduction identity), then retires exactly one
    occurrence per group (the min row index among that group's extremal
    rows, so ties retire deterministically).  Same dense output contract
    as :func:`topk_dense_block`: best-first values in the first
    ``counts[g]`` slots, and since top-k partials carry VALUES only, the
    two routes are indistinguishable (equal-valued ties have no identity).
    The small-k route of the emission — see :data:`TOPK_KPASS_MAX_K`."""
    from bqueryd_tpu.models.query import extremum_fill

    valid = _topk_validity(codes, values, mask, drop_nan, sentinel)
    n = values.shape[0]
    safe = jnp.where(codes >= 0, codes, 0).astype(jnp.int32)
    fill = np.dtype(values.dtype).type(
        extremum_fill(values.dtype, "max" if largest else "min")
    )
    seg_best = jax.ops.segment_max if largest else jax.ops.segment_min
    row_idx = jnp.arange(n, dtype=jnp.int64)
    alive = valid
    slots = []
    for _round in range(int(k)):
        cur = jnp.where(alive, values, fill)
        best = seg_best(cur, safe, num_segments=n_groups)
        slots.append(best)
        is_best = alive & (values == best[safe])
        kill = jax.ops.segment_min(
            jnp.where(is_best, row_idx, jnp.int64(n)),
            safe, num_segments=n_groups,
        )
        alive = alive & (row_idx != kill[safe])
    dense = jnp.stack(slots, axis=1)
    counts = jnp.minimum(
        jax.ops.segment_sum(
            valid.astype(jnp.int64), safe, num_segments=n_groups
        ),
        jnp.int64(k),
    )
    return dense, counts


#: matrix-route cell budget: the [groups, chunk] masked matrix is bounded
#: to this many cells per scan step (2^24 * 8 B = 128 MiB transient)
TOPK_MATRIX_CELLS = 1 << 24


def topk_matrix_block(codes, values, mask, k, largest, n_groups, drop_nan,
                      sentinel):
    """Dense per-group top-k via k argmax passes over a ``[groups, chunk]``
    masked value matrix — the fastest route when the group count is small
    (vectorized row reductions instead of a rows-scale sort or segment
    scatters; measured ~2.5x over the segment k-pass and ~4x over the
    lexsort at bench shapes on a single CPU device).  Per round: the
    per-group argmax row is retired and its value recorded; a group whose
    best equals the masked-cell fill is either exhausted or holds only
    fill-valued rows — in BOTH cases recording the fill value is exactly
    right (all remaining candidates equal it), so the presence test needs
    no extra pass.  Rows chunk so the matrix never exceeds
    :data:`TOPK_MATRIX_CELLS` cells (``lax.scan`` over chunks, per-chunk
    [groups, k] candidates re-selected by a final per-row sort).
    "Smallest" rides a monotone transform (float negation / integer
    bitwise-not) so the descending selection serves both directions; the
    transforms are exact bijections, inverted on the dense output.  Same
    dense contract as the other routes: best-first values in the first
    ``counts[g]`` slots, slots past the count unread — and top-k partials
    carry VALUES only, so fill/tie choices are unobservable."""
    from bqueryd_tpu.models.query import extremum_fill

    valid = _topk_validity(codes, values, mask, drop_nan, sentinel)
    n = int(values.shape[0])
    k = int(k)
    floating = jnp.issubdtype(jnp.dtype(values.dtype), jnp.floating)
    if largest:
        tvals = values
    elif floating:
        tvals = -values
    else:
        tvals = ~values
    fill = np.dtype(str(tvals.dtype)).type(
        extremum_fill(np.dtype(str(tvals.dtype)), "max")
    )
    gids_dt = codes.dtype if jnp.issubdtype(
        codes.dtype, jnp.integer
    ) else jnp.int32
    gids = jnp.arange(int(n_groups), dtype=gids_dt)

    def chunk_top(c, v, ok):
        """k argmax rounds over one [groups, chunk] masked matrix."""
        nloc = int(v.shape[0])
        gmat = ok[None, :] & (c[None, :] == gids[:, None])
        alive = jnp.ones(nloc, dtype=bool)
        slots = []
        for _round in range(min(k, nloc)):
            m = jnp.where(gmat & alive[None, :], v[None, :], fill)
            kill = jnp.argmax(m, axis=-1)
            best = jnp.take_along_axis(m, kill[:, None], axis=-1)[:, 0]
            slots.append(best)
            # a best equal to the fill means every remaining candidate of
            # that group also equals it: skipping the kill cannot change
            # any later round's recorded value
            alive = alive.at[
                jnp.where(best > fill, kill, nloc)
            ].set(False, mode="drop")
        top = jnp.stack(slots, axis=1)
        if top.shape[1] < k:
            top = jnp.pad(
                top, ((0, 0), (0, k - top.shape[1])), constant_values=fill
            )
        cnt = jnp.minimum(
            gmat.sum(axis=-1).astype(jnp.int64), jnp.int64(k)
        )
        return top, cnt

    chunk = max(int(TOPK_MATRIX_CELLS // max(int(n_groups), 1)), k)
    chunk = min(chunk, n)
    nc = -(-n // chunk)
    if nc == 1:
        cand, counts = chunk_top(codes, tvals, valid)
    else:
        pad = nc * chunk - n
        codes_p = jnp.pad(
            codes, (0, pad), constant_values=codes.dtype.type(-1)
            if jnp.issubdtype(codes.dtype, jnp.signedinteger) else 0
        ).reshape(nc, chunk)
        vals_p = jnp.pad(tvals, (0, pad)).reshape(nc, chunk)
        valid_p = jnp.pad(valid, (0, pad)).reshape(nc, chunk)
        _carry, out = jax.lax.scan(
            lambda carry, xs: (carry, chunk_top(*xs)),
            None, (codes_p, vals_p, valid_p),
        )
        tops, cnts = out
        # [nc, G, k] -> best k of each group's nc*k candidates: one
        # per-row sort of the small candidate matrix, best (largest in
        # transformed space) first — fill values sort last
        cand = jnp.sort(
            jnp.moveaxis(tops, 0, 1).reshape(int(n_groups), -1), axis=-1
        )[:, ::-1][:, :k]
        counts = jnp.minimum(cnts.sum(axis=0), jnp.int64(k))
    if largest:
        dense = cand
    elif floating:
        dense = -cand
    else:
        dense = ~cand
    return dense, counts


def topk_dense_emit(codes, values, mask, k, largest, n_groups, drop_nan,
                    sentinel, float_neg):
    """Route the dense top-k emission by static shape: the ``lax.top_k``
    matrix route when the [groups, chunk] matrix affords a useful chunk
    (small group counts — every bench/production shape), the k-pass
    segment route for small k at high group cardinality (O(k*n), no
    rows-scale sort), the lexsort route past :data:`TOPK_KPASS_MAX_K` or
    for bool measures (whose extremum identities degenerate).  All three
    emit the same value multisets, so the choice is invisible in results.
    ``k``/``largest``/``n_groups``/dtype are static at trace time — the
    route is baked into the compiled program like every other kernel
    dispatch."""
    if jnp.dtype(values.dtype) != jnp.bool_:
        chunk = TOPK_MATRIX_CELLS // max(int(n_groups), 1)
        if chunk >= 4096 and int(k) <= chunk:
            return topk_matrix_block(
                codes, values, mask, k, largest, n_groups, drop_nan,
                sentinel,
            )
        if int(k) <= TOPK_KPASS_MAX_K:
            return topk_kpass_block(
                codes, values, mask, k, largest, n_groups, drop_nan,
                sentinel,
            )
    return topk_dense_block(
        codes, values, mask, k, largest, n_groups, drop_nan, sentinel,
        float_neg,
    )


def topk_dense_block(codes, values, mask, k, largest, n_groups, drop_nan,
                     sentinel, float_neg):
    """Dense per-group top-k: ``(values[n_groups, k], counts[n_groups])``
    with group g's best-first values in row g's first ``counts[g]`` slots.
    Sort route: one lexsort, ranks via searchsorted, rank-bounded scatter.
    ``float_neg`` is the STATIC dtype decision (computed by the wrapper):
    the monotone-decreasing sort key is negation for floats (NaNs already
    excluded) and bitwise-not for ints/bools (~x = -x-1, wrap-free).

    Trace-time body, shared by the jitted per-shard kernel below and the
    mesh fast path's DAG program (``executor._mesh_dag_program``), so both
    routes emit bit-identical dense partials."""
    valid = _topk_validity(codes, values, mask, drop_nan, sentinel)
    if largest:
        sort_v = -values if float_neg else ~values
    else:
        sort_v = values
    key = jnp.where(valid, codes.astype(jnp.int64), n_groups)
    order = jnp.lexsort((sort_v, key))
    sk = key[order]
    sv = values[order]
    first = jnp.searchsorted(sk, sk, side="left")
    rank = jnp.arange(sk.shape[0], dtype=jnp.int64) - first
    sel = (sk < n_groups) & (rank < k)
    gidx = jnp.where(sel, sk, n_groups)      # out-of-range -> mode="drop"
    ridx = jnp.where(sel, rank, 0)
    out = jnp.zeros((n_groups, k), dtype=values.dtype)
    out = out.at[gidx, ridx].set(sv, mode="drop")
    counts = jnp.zeros(n_groups, dtype=jnp.int64).at[gidx].add(
        jnp.where(sel, 1, 0), mode="drop"
    )
    return out, counts


#: the jitted per-shard kernel rides the same routed emission as the mesh
#: program: k-pass segment selection for small k, lexsort past the
#: crossover — the flat per-shard partial is identical either way
_topk_dense = _obsprofile.instrument(
    "ops.relops_topk",
    functools.partial(
        jax.jit,
        static_argnames=(
            "k", "largest", "n_groups", "drop_nan", "sentinel", "float_neg"
        ),
    )(topk_dense_emit),
)


def topk_partials(codes, values, k, largest, n_groups, mask=None,
                  sentinel=None):
    """Per-shard top-k partial on device, compacted to the flat mergeable
    form ``(values, offsets)`` — bit-identical to
    ``opexec.topk_flat`` (the host twin)."""
    values = np.asarray(values)
    n_prog = program_bucket(n_groups)
    dense, cnt = jax.device_get(
        _topk_dense(
            jnp.asarray(np.asarray(codes), dtype=jnp.int64),
            jnp.asarray(values),
            None if mask is None else jnp.asarray(mask, dtype=bool),
            k=int(k),
            largest=bool(largest),
            n_groups=int(n_prog),
            drop_nan=bool(np.issubdtype(values.dtype, np.floating)),
            sentinel=None if sentinel is None else int(sentinel),
            float_neg=bool(np.issubdtype(values.dtype, np.floating)),
        )
    )
    from bqueryd_tpu.parallel.opexec import dense_topk_to_flat

    return dense_topk_to_flat(
        np.asarray(dense)[:n_groups], np.asarray(cnt)[:n_groups]
    )


def sketch_bin_block(values, log_gamma, imin, imax):
    """Trace-time body of the elementwise signed-bucket-key computation,
    shared by the jitted kernel below and the mesh fast path's dense grid
    emission — one implementation, so every route bins identically.  NaN
    rows produce garbage keys and MUST be excluded by the caller."""
    v = values.astype(jnp.float64)
    mag = jnp.abs(v)
    tiny = mag < SKETCH_MIN_MAGNITUDE
    i = jnp.ceil(jnp.log(jnp.where(tiny, 1.0, mag)) / log_gamma)
    i = jnp.clip(i, imin, imax).astype(jnp.int64)
    unsigned = i - jnp.int64(imin) + 1
    return jnp.where(
        tiny, jnp.int64(0), jnp.where(v < 0, -unsigned, unsigned)
    )


_sketch_bin = _obsprofile.instrument(
    "ops.relops_sketch_bin",
    functools.partial(
        jax.jit, static_argnames=("log_gamma", "imin", "imax")
    )(sketch_bin_block),
)


def sketch_grid_block(codes, values, n_groups, log_gamma, imin, imax,
                      kmin, width):
    """Trace-time dense per-(group, signed-bucket) count grid for the mesh
    fast path: ``int64[n_groups, width]`` where column ``j`` holds bucket
    key ``kmin + j``'s count for that group.  One scatter-add over
    (code, bucket) pairs — the dense twin of ``opexec.sketch_flat``'s
    pair-unique, emitted on the static grid so the cross-device merge is a
    single reduce-scatter of bucket-count ADDITIONS
    (``devicemerge.scatter_merge_grid``).  NaN values and null/masked-out
    codes (< 0) drop here, matching the host kernel's validity mask; the
    host converts the fetched grid back to the flat mergeable form with
    ``opexec.sketch_grid_to_flat`` (zero cells vanish, so the flat form is
    bit-identical to the host path's)."""
    v = values.astype(jnp.float64)
    valid = (codes >= 0) & ~jnp.isnan(v)
    keys = sketch_bin_block(v, log_gamma, imin, imax)
    col = jnp.where(valid, keys - jnp.int64(kmin), 0)
    gidx = jnp.where(valid, codes.astype(jnp.int64), n_groups)
    grid = jnp.zeros((n_groups, width), dtype=jnp.int64)
    return grid.at[gidx, col].add(
        jnp.where(valid, jnp.int64(1), jnp.int64(0)), mode="drop"
    )


def sketch_bin(values, alpha):
    """Elementwise signed bucket key per row (device twin of
    ``opexec.sketch_keys_host``).  NaN rows produce garbage keys and MUST
    be excluded by the caller's validity mask (``opexec.sketch_flat``
    does), same contract as the host kernel."""
    _gamma, lg, imin, imax = sketch_layout(alpha)
    return np.asarray(
        jax.device_get(
            _sketch_bin(
                jnp.asarray(np.asarray(values)),
                log_gamma=float(lg), imin=int(imin), imax=int(imax),
            )
        )
    )
