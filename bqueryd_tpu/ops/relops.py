"""Device kernels for the relational operators (plan.dag / parallel.opexec).

Three jitted entry points, each the device twin of a NumPy host kernel in
:mod:`bqueryd_tpu.parallel.opexec` (the host kernels are the reference
semantics, exactly like ``host_partial_tables`` is for the groupby
kernels) and each behind the SAME guards as every other kernel: the
executor routes here only above ``models.query.host_kernel_rows`` and
never on a wedged backend.

* :func:`gather_positions` — the broadcast hash-join probe: one gather of
  per-distinct-key dimension positions onto rows (the join's per-row work
  is exactly a gather once the join key is factorized).
* :func:`topk_partials` — per-group top-k via the sort route: one
  ``lexsort`` by (group, value-desc), within-group rank from a
  ``searchsorted`` against the sorted codes, rank-< k scatter into a
  dense ``[groups, k]`` buffer (group dimension bucketed through
  ``program_bucket`` for program reuse), compacted host-side into the
  flat mergeable form.
* :func:`sketch_bin` — the quantile sketch's elementwise bucket-key
  computation (the only per-row work a sketch does); the per-(group,
  bucket) pairing stays host-side in ``opexec.sketch_flat``.

All three are compile-profiled (PR-3 ``profile.instrument``) so their
programs land in the per-shape registry and jit-cache accounting like
every other kernel.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from bqueryd_tpu.obs import profile as _obsprofile
from bqueryd_tpu.parallel.opexec import (
    SKETCH_MIN_MAGNITUDE,
    sketch_layout,
)
from bqueryd_tpu.models.query import _segment_local_arange
from bqueryd_tpu.ops.groupby import program_bucket


@jax.jit
def _gather_positions(pos_of_unique, codes):
    safe = jnp.maximum(codes, 0)
    return jnp.where(
        codes >= 0, pos_of_unique[safe], jnp.int64(-1)
    )


_gather_positions = _obsprofile.instrument(
    "ops.relops_gather", _gather_positions
)


def gather_positions(pos_of_unique, codes):
    """Join probe on device: ``row_pos[i] = pos_of_unique[codes[i]]`` with
    null codes mapped to -1 (miss)."""
    return np.asarray(
        jax.device_get(
            _gather_positions(
                jnp.asarray(pos_of_unique, dtype=jnp.int64),
                jnp.asarray(codes),
            )
        )
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "largest", "n_groups", "drop_nan", "sentinel", "float_neg"
    ),
)
def _topk_dense(codes, values, mask, k, largest, n_groups, drop_nan,
                sentinel, float_neg):
    """Dense per-group top-k: ``(values[n_groups, k], counts[n_groups])``
    with group g's best-first values in row g's first ``counts[g]`` slots.
    Sort route: one lexsort, ranks via searchsorted, rank-bounded scatter.
    ``float_neg`` is the STATIC dtype decision (computed by the wrapper):
    the monotone-decreasing sort key is negation for floats (NaNs already
    excluded) and bitwise-not for ints/bools (~x = -x-1, wrap-free)."""
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    if sentinel is not None:
        valid = valid & (values != sentinel)
    if drop_nan:
        valid = valid & ~jnp.isnan(values)
    if largest:
        sort_v = -values if float_neg else ~values
    else:
        sort_v = values
    key = jnp.where(valid, codes.astype(jnp.int64), n_groups)
    order = jnp.lexsort((sort_v, key))
    sk = key[order]
    sv = values[order]
    first = jnp.searchsorted(sk, sk, side="left")
    rank = jnp.arange(sk.shape[0], dtype=jnp.int64) - first
    sel = (sk < n_groups) & (rank < k)
    gidx = jnp.where(sel, sk, n_groups)      # out-of-range -> mode="drop"
    ridx = jnp.where(sel, rank, 0)
    out = jnp.zeros((n_groups, k), dtype=values.dtype)
    out = out.at[gidx, ridx].set(sv, mode="drop")
    counts = jnp.zeros(n_groups, dtype=jnp.int64).at[gidx].add(
        jnp.where(sel, 1, 0), mode="drop"
    )
    return out, counts


_topk_dense = _obsprofile.instrument("ops.relops_topk", _topk_dense)


def topk_partials(codes, values, k, largest, n_groups, mask=None,
                  sentinel=None):
    """Per-shard top-k partial on device, compacted to the flat mergeable
    form ``(values, offsets)`` — bit-identical to
    ``opexec.topk_flat`` (the host twin)."""
    values = np.asarray(values)
    n_prog = program_bucket(n_groups)
    dense, cnt = jax.device_get(
        _topk_dense(
            jnp.asarray(np.asarray(codes), dtype=jnp.int64),
            jnp.asarray(values),
            None if mask is None else jnp.asarray(mask, dtype=bool),
            k=int(k),
            largest=bool(largest),
            n_groups=int(n_prog),
            drop_nan=bool(np.issubdtype(values.dtype, np.floating)),
            sentinel=None if sentinel is None else int(sentinel),
            float_neg=bool(np.issubdtype(values.dtype, np.floating)),
        )
    )
    dense = np.asarray(dense)[:n_groups]
    take = np.asarray(cnt, dtype=np.int64)[:n_groups]
    rep = np.repeat(np.arange(n_groups, dtype=np.int64), take)
    loc = _segment_local_arange(take)
    flat = dense[rep, loc] if len(rep) else dense[:0, 0]
    offsets = np.zeros(n_groups + 1, dtype=np.int64)
    np.cumsum(take, out=offsets[1:])
    return flat, offsets


@functools.partial(
    jax.jit, static_argnames=("log_gamma", "imin", "imax")
)
def _sketch_bin(values, log_gamma, imin, imax):
    v = values.astype(jnp.float64)
    mag = jnp.abs(v)
    tiny = mag < SKETCH_MIN_MAGNITUDE
    i = jnp.ceil(jnp.log(jnp.where(tiny, 1.0, mag)) / log_gamma)
    i = jnp.clip(i, imin, imax).astype(jnp.int64)
    unsigned = i - jnp.int64(imin) + 1
    return jnp.where(
        tiny, jnp.int64(0), jnp.where(v < 0, -unsigned, unsigned)
    )


_sketch_bin = _obsprofile.instrument("ops.relops_sketch_bin", _sketch_bin)


def sketch_bin(values, alpha):
    """Elementwise signed bucket key per row (device twin of
    ``opexec.sketch_keys_host``).  NaN rows produce garbage keys and MUST
    be excluded by the caller's validity mask (``opexec.sketch_flat``
    does), same contract as the host kernel."""
    _gamma, lg, imin, imax = sketch_layout(alpha)
    return np.asarray(
        jax.device_get(
            _sketch_bin(
                jnp.asarray(np.asarray(values)),
                log_gamma=float(lg), imin=int(imin), imax=int(imax),
            )
        )
    )
