"""Group-key factorization: values -> dense codes + dictionary.

The TPU equivalent of bquery's factorize (the cached factorization opened with
``auto_cache=True`` at reference bqueryd/worker.py:291).  Three layers:

* :func:`factorize` — host-side, any dtype, dynamic cardinality (C++ hash map
  for int64, NumPy otherwise).  Used at ingest and query planning.
* :func:`factorize_device` — device-side, fixed capacity (static shapes for
  XLA), for fully-jitted single-shard paths.
* :func:`pack_codes` / :func:`unpack_codes` — composite multi-key codes: with
  global per-key cardinalities ``(K1..Kn)``, a key tuple becomes one int
  ``c1*K2*...*Kn + c2*K3*...*Kn + ... + cn``.  Tables indexed by packed code
  are index-aligned across shards, which is what makes the
  ``psum``-over-mesh merge legal.
"""

import numpy as np

from bqueryd_tpu.storage import codec as storage_codec


def factorize(values):
    """Host factorize in first-seen order -> (codes int (n,), uniques).

    int64/int32 go through the native hash factorizer; other dtypes through
    NumPy.  NaNs (floats) factorize as ordinary keys (NaN != NaN is ignored:
    all NaNs map to one group, matching pandas' dropna=False behaviour only
    for the non-NaN part — callers on the groupby path pre-filter NaNs if the
    reference semantics require it).
    """
    values = np.asarray(values)
    if values.dtype.kind in "iu" and values.dtype.itemsize <= 8:
        codes, uniques = storage_codec.factorize_i64(values.astype(np.int64))
        return codes, uniques.astype(values.dtype)
    # float / other: NumPy unique (sorted) remapped to first-seen order
    uniques, inverse = np.unique(values, return_inverse=True)
    return storage_codec.first_seen_order(uniques, inverse, len(values))


def factorize_device(keys, capacity, fill_value=None):
    """Device-side fixed-capacity factorize (jit-safe, static shapes).

    Returns ``(uniques[capacity], codes[n], n_uniques)``; slots past
    ``n_uniques`` hold ``fill_value`` (default: max dtype value).  Raises at
    trace time only for bad capacity; overflow past capacity is detectable by
    the caller via ``n_uniques == capacity``.
    """
    import jax.numpy as jnp

    if fill_value is None:
        fill_value = jnp.iinfo(keys.dtype).max if jnp.issubdtype(
            keys.dtype, jnp.integer
        ) else jnp.inf
    uniques, codes = jnp.unique(
        keys, return_inverse=True, size=capacity, fill_value=fill_value
    )
    # count uniques from the codes, not by comparing against fill_value —
    # real data may contain the fill value itself
    if codes.size:
        n_uniques = (codes.max() + 1).astype(jnp.int32)
    else:
        n_uniques = jnp.int32(0)
    return uniques, codes.astype(jnp.int32), n_uniques


#: composite key spaces at or past this product cannot be radix-packed in
#: int64; the single definition every overflow check compares against
MAX_COMPOSITE = 2**63


class CompositeOverflow(ValueError):
    """The product of key cardinalities exceeds int64: radix-packed
    composite codes would wrap and silently merge unrelated groups.
    Callers degrade to tuple-wise factorization (engine path) or refuse
    (mesh path, whose cross-shard alignment needs the radix order)."""


def pack_codes(code_arrays, cardinalities):
    """Combine per-key dense codes into one composite code array.

    Works on NumPy or JAX arrays (pure arithmetic).  ``cardinalities[i]`` must
    bound ``code_arrays[i]`` (codes in ``[0, K_i)``); negative codes (nulls)
    poison the whole composite to -1.  Raises :class:`CompositeOverflow`
    when the composite space does not fit int64 (wrapping would corrupt
    group identities, not just waste space).
    """
    assert len(code_arrays) == len(cardinalities) and code_arrays
    if total_cardinality(cardinalities) >= MAX_COMPOSITE:  # py ints: no wrap
        raise CompositeOverflow(
            "composite group-key space "
            f"{'x'.join(str(int(c)) for c in cardinalities)} exceeds int64"
        )
    np_like = np if isinstance(code_arrays[0], np.ndarray) else _jnp()
    total = code_arrays[0].astype(np_like.int64)
    negative = code_arrays[0] < 0
    for codes, card in zip(code_arrays[1:], cardinalities[1:]):
        total = total * int(card) + codes.astype(np_like.int64)
        negative = negative | (codes < 0)
    return np_like.where(negative, np_like.int64(-1), total)


def unpack_codes(packed, cardinalities):
    """Inverse of :func:`pack_codes`: composite codes -> list of per-key codes.
    Null composites (-1) unpack to -1 for every key."""
    np_like = np if isinstance(packed, np.ndarray) else _jnp()
    packed = packed.astype(np_like.int64)
    null = packed < 0
    out = []
    rest = np_like.where(null, 0, packed)
    for card in reversed(cardinalities[1:]):
        out.append(np_like.where(null, np_like.int64(-1), rest % int(card)))
        rest = rest // int(card)
    out.append(np_like.where(null, np_like.int64(-1), rest))
    return list(reversed(out))


def total_cardinality(cardinalities):
    total = 1
    for k in cardinalities:
        total *= int(k)
    return total


def _jnp():
    import jax.numpy as jnp

    return jnp
