"""JAX columnar kernels (the compute role bquery's Cython kernels play in the
reference, used at reference bqueryd/worker.py:291-323).

Importing this package enables JAX 64-bit mode: the north-star acceptance
criterion is bit-for-bit int64 aggregates, and without ``jax_enable_x64``
int64 inputs silently degrade to int32.  Control-plane modules never import
this package, so pure controller/downloader processes stay JAX-free.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Honor an explicitly requested platform even when the machine's sitecustomize
# pre-registered a TPU-tunnel ("axon") backend factory: jax initializes every
# registered factory on first use, so a CPU-only worker would still touch (and
# potentially hang on) the tunnel.  When the requested platform list excludes
# the tunnel, drop its factory outright.
_requested = os.environ.get("BQUERYD_TPU_PLATFORM") or os.environ.get(
    "JAX_PLATFORMS"
)
if _requested and "axon" not in _requested and "tpu" not in _requested:
    jax.config.update("jax_platforms", _requested)
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
del _requested

from bqueryd_tpu.ops.factorize import (  # noqa: E402
    factorize,
    factorize_device,
    pack_codes,
    total_cardinality,
    unpack_codes,
)
from bqueryd_tpu.ops.groupby import (  # noqa: E402
    AGG_OPS,
    MERGEABLE_OPS,
    combine_partials,
    expand_mask_by_group,
    finalize,
    groupby_aggregate,
    groupby_count_distinct,
    groupby_sorted_count_distinct,
    partial_tables,
    psum_partials,
)
from bqueryd_tpu.ops.predicates import (  # noqa: E402
    WHERE_OPS,
    build_mask,
    shard_can_match,
    term_mask,
    translate_value,
)

__all__ = [
    "factorize",
    "factorize_device",
    "pack_codes",
    "unpack_codes",
    "total_cardinality",
    "AGG_OPS",
    "MERGEABLE_OPS",
    "groupby_aggregate",
    "groupby_count_distinct",
    "groupby_sorted_count_distinct",
    "expand_mask_by_group",
    "partial_tables",
    "combine_partials",
    "psum_partials",
    "finalize",
    "WHERE_OPS",
    "build_mask",
    "shard_can_match",
    "term_mask",
    "translate_value",
]
