"""JAX columnar kernels (the compute role bquery's Cython kernels play in the
reference, used at reference bqueryd/worker.py:291-323).

Importing this package enables JAX 64-bit mode: the north-star acceptance
criterion is bit-for-bit int64 aggregates, and without ``jax_enable_x64``
int64 inputs silently degrade to int32.  Control-plane modules never import
this package, so pure controller/downloader processes stay JAX-free.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Honor an explicitly requested platform even when the machine's sitecustomize
# pre-registered a TPU-tunnel ("axon") backend factory: jax initializes every
# registered factory on first use, so a CPU-only worker would still touch (and
# potentially hang on) the tunnel.  When the requested platform list excludes
# the tunnel, drop its factory outright.
_requested = os.environ.get("BQUERYD_TPU_PLATFORM") or os.environ.get(
    "JAX_PLATFORMS"
)
if _requested and "axon" not in _requested and "tpu" not in _requested:
    jax.config.update("jax_platforms", _requested)
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
del _requested

# Persistent compilation cache: first-query-per-shape XLA compiles (20-40 s
# per program through a tunneled TPU backend, plus tunnel round-trips)
# survive process restarts.  Multi-process safe (atomic renames); every
# (ops, dtypes, n_groups-bucket) signature a worker has ever served warms
# the whole fleet's next restart.  TPU-ish platforms only: reloading
# XLA:CPU AOT artifacts logs machine-feature-mismatch errors and documents
# SIGILL risk on heterogeneous fleets, and CPU first-compiles are cheap
# enough to just pay.  The platform is sniffed from env, NOT
# jax.default_backend() — touching the backend at import time can hang on
# a dead tunnel.  BQUERYD_TPU_COMPILE_CACHE=0 disables; =<path> relocates
# (and also opts a CPU platform in, for tests).
_cc = os.environ.get("BQUERYD_TPU_COMPILE_CACHE", "1")
# same override precedence as the platform block above; with neither set,
# fail CLOSED unless the axon tunnel boot already registered itself — a
# bare CPU box must not persist XLA:CPU AOT artifacts by default (shared
# homes across heterogeneous CPUs risk the SIGILL scenario above)
_platf = (
    os.environ.get("BQUERYD_TPU_PLATFORM")
    or os.environ.get("JAX_PLATFORMS")
    or ""
)
_tpuish = (
    "tpu" in _platf
    or "axon" in _platf
    or (not _platf and "_AXON_REGISTERED" in os.environ)
)
if _cc != "0" and (_tpuish or _cc not in ("", "1")):
    _cc_dir = _cc if _cc not in ("", "1") else os.path.join(
        os.path.expanduser("~"), ".cache", "bqueryd_tpu", "jax_cache"
    )
    try:
        os.makedirs(_cc_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cc_dir)
        # cache every compile (the default 1 s floor would skip most of the
        # small per-shape programs whose aggregate warmup this kills)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
    except Exception:
        pass  # unwritable home: first-compile cost stays, nothing breaks
del _cc, _platf, _tpuish


_distributed_initialized = False


def maybe_init_distributed(logger=None):
    """Join a multi-host JAX job when configured; no-op otherwise.

    Set ``BQUERYD_TPU_DIST_COORDINATOR=host:port`` on every host of a pod
    slice (plus ``BQUERYD_TPU_DIST_NPROCS`` / ``BQUERYD_TPU_DIST_PROC_ID``
    off-TPU, where they can't be inferred) and the calc worker becomes one
    process of a single multi-host JAX runtime: ``jax.devices()`` spans the
    slice, the mesh executor's 1-D shard mesh covers every chip, and the
    ``psum`` merge rides ICI within a host and DCN across hosts — the
    framework's answer to the reference's one-process-per-core scaling
    (reference misc/supervisor.conf:19-20).

    Must run before the first JAX backend touch; the worker calls it at
    construction time."""
    global _distributed_initialized
    coordinator = os.environ.get("BQUERYD_TPU_DIST_COORDINATOR")
    if not coordinator or _distributed_initialized:
        return False
    kwargs = {"coordinator_address": coordinator}
    if os.environ.get("BQUERYD_TPU_DIST_NPROCS"):
        kwargs["num_processes"] = int(os.environ["BQUERYD_TPU_DIST_NPROCS"])
    if os.environ.get("BQUERYD_TPU_DIST_PROC_ID"):
        kwargs["process_id"] = int(os.environ["BQUERYD_TPU_DIST_PROC_ID"])
    jax.distributed.initialize(**kwargs)
    _distributed_initialized = True
    if logger is not None:
        logger.info(
            "joined multi-host JAX job: process %d/%d, %d/%d devices local",
            jax.process_index(), jax.process_count(),
            len(jax.local_devices()), len(jax.devices()),
        )
    return True


from bqueryd_tpu.ops.factorize import (  # noqa: E402
    MAX_COMPOSITE,
    CompositeOverflow,
    factorize,
    factorize_device,
    pack_codes,
    total_cardinality,
    unpack_codes,
)
from bqueryd_tpu.ops.groupby import (  # noqa: E402
    AGG_OPS,
    MERGEABLE_OPS,
    bucketize_partials,
    bundle_partial_tables,
    combine_partials,
    expand_mask_by_group,
    finalize,
    groupby_aggregate,
    groupby_count_distinct,
    groupby_sorted_count_distinct,
    host_partial_tables,
    host_sorted_count_distinct,
    kernel_route,
    partial_tables,
    partial_tables_bucketized,
    program_bucket,
    psum_partials,
)
from bqueryd_tpu.ops.predicates import (  # noqa: E402
    WHERE_OPS,
    build_mask,
    chunk_pruned_table,
    chunk_selection,
    shard_can_match,
    term_mask,
    translate_value,
)

__all__ = [
    "CompositeOverflow",
    "MAX_COMPOSITE",
    "factorize",
    "factorize_device",
    "pack_codes",
    "unpack_codes",
    "total_cardinality",
    "AGG_OPS",
    "MERGEABLE_OPS",
    "groupby_aggregate",
    "groupby_count_distinct",
    "groupby_sorted_count_distinct",
    "expand_mask_by_group",
    "host_partial_tables",
    "host_sorted_count_distinct",
    "kernel_route",
    "partial_tables",
    "partial_tables_bucketized",
    "program_bucket",
    "bucketize_partials",
    "bundle_partial_tables",
    "combine_partials",
    "psum_partials",
    "finalize",
    "WHERE_OPS",
    "build_mask",
    "chunk_pruned_table",
    "chunk_selection",
    "shard_can_match",
    "term_mask",
    "translate_value",
]
