"""JAX columnar kernels (the compute role bquery's Cython kernels play in the
reference, used at reference bqueryd/worker.py:291-323).

Importing this package enables JAX 64-bit mode: the north-star acceptance
criterion is bit-for-bit int64 aggregates, and without ``jax_enable_x64``
int64 inputs silently degrade to int32.  Control-plane modules never import
this package, so pure controller/downloader processes stay JAX-free.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Honor an explicitly requested platform even when the machine's sitecustomize
# pre-registered a TPU-tunnel ("axon") backend factory: jax initializes every
# registered factory on first use, so a CPU-only worker would still touch (and
# potentially hang on) the tunnel.  When the requested platform list excludes
# the tunnel, drop its factory outright.
_requested = os.environ.get("BQUERYD_TPU_PLATFORM") or os.environ.get(
    "JAX_PLATFORMS"
)
if _requested and "axon" not in _requested and "tpu" not in _requested:
    jax.config.update("jax_platforms", _requested)
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
del _requested


_distributed_initialized = False


def maybe_init_distributed(logger=None):
    """Join a multi-host JAX job when configured; no-op otherwise.

    Set ``BQUERYD_TPU_DIST_COORDINATOR=host:port`` on every host of a pod
    slice (plus ``BQUERYD_TPU_DIST_NPROCS`` / ``BQUERYD_TPU_DIST_PROC_ID``
    off-TPU, where they can't be inferred) and the calc worker becomes one
    process of a single multi-host JAX runtime: ``jax.devices()`` spans the
    slice, the mesh executor's 1-D shard mesh covers every chip, and the
    ``psum`` merge rides ICI within a host and DCN across hosts — the
    framework's answer to the reference's one-process-per-core scaling
    (reference misc/supervisor.conf:19-20).

    Must run before the first JAX backend touch; the worker calls it at
    construction time."""
    global _distributed_initialized
    coordinator = os.environ.get("BQUERYD_TPU_DIST_COORDINATOR")
    if not coordinator or _distributed_initialized:
        return False
    kwargs = {"coordinator_address": coordinator}
    if os.environ.get("BQUERYD_TPU_DIST_NPROCS"):
        kwargs["num_processes"] = int(os.environ["BQUERYD_TPU_DIST_NPROCS"])
    if os.environ.get("BQUERYD_TPU_DIST_PROC_ID"):
        kwargs["process_id"] = int(os.environ["BQUERYD_TPU_DIST_PROC_ID"])
    jax.distributed.initialize(**kwargs)
    _distributed_initialized = True
    if logger is not None:
        logger.info(
            "joined multi-host JAX job: process %d/%d, %d/%d devices local",
            jax.process_index(), jax.process_count(),
            len(jax.local_devices()), len(jax.devices()),
        )
    return True


from bqueryd_tpu.ops.factorize import (  # noqa: E402
    factorize,
    factorize_device,
    pack_codes,
    total_cardinality,
    unpack_codes,
)
from bqueryd_tpu.ops.groupby import (  # noqa: E402
    AGG_OPS,
    MERGEABLE_OPS,
    combine_partials,
    expand_mask_by_group,
    finalize,
    groupby_aggregate,
    groupby_count_distinct,
    groupby_sorted_count_distinct,
    host_partial_tables,
    partial_tables,
    psum_partials,
)
from bqueryd_tpu.ops.predicates import (  # noqa: E402
    WHERE_OPS,
    build_mask,
    shard_can_match,
    term_mask,
    translate_value,
)

__all__ = [
    "factorize",
    "factorize_device",
    "pack_codes",
    "unpack_codes",
    "total_cardinality",
    "AGG_OPS",
    "MERGEABLE_OPS",
    "groupby_aggregate",
    "groupby_count_distinct",
    "groupby_sorted_count_distinct",
    "expand_mask_by_group",
    "host_partial_tables",
    "partial_tables",
    "combine_partials",
    "psum_partials",
    "finalize",
    "WHERE_OPS",
    "build_mask",
    "shard_can_match",
    "term_mask",
    "translate_value",
]
