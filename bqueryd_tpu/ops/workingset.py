"""Device-resident working-set cache: codes + measure blocks + alignment.

The executor's steady-state serving story is cache residency: host key
alignment (dictionary-sized), factorized+folded group codes (HBM), and
wire-dtype measure blocks (HBM).  Before this module those were three
ad-hoc ``BytesCappedCache`` instances with wholesale eviction and no
telemetry; this promotes them into one named working-set layer:

* **content-keyed segments** — ``align`` (host: dense codes + global
  dictionaries per (table set, groupby columns)), ``codes`` (device:
  packed+folded group codes per (table set, groupby columns, filter)),
  ``blocks`` (device: packed wire-dtype measure columns per (table set,
  column)).  Keys carry the shard identity (rootdir + meta.json
  inode/mtime + rows, :func:`bqueryd_tpu.storage.ctable.table_cache_key`),
  so activation invalidates naturally and a repeat query with a DIFFERENT
  measure or filter still hits the codes/alignment segments — it skips
  decode, factorize and the codes H2D entirely instead of requiring an
  exact serialized-result hit.
* **LRU byte budgets per segment** (see the env vars below), with
  hit/miss/eviction counters exported as worker gauges
  (``bqueryd_tpu_workingset_*{segment=...}``) and into bench.py's
  ``pipeline`` section.
* **eviction under device-memory pressure** —
  :meth:`WorkingSet.evict_under_pressure` reads the PR-3 HBM watermark
  sample (``obs.profile.profiler().memory_sample()``) and evicts LRU
  device entries until usage projects below
  ``BQUERYD_TPU_HBM_EVICT_WATERMARK`` x ``bytes_limit`` — shedding cache
  before the allocator hits RESOURCE_EXHAUSTED and ``DeviceHealth``
  latches the backend as wedged.

A :class:`WorkingSet` is per-executor (the worker owns one mesh executor),
not process-global: in-process test clusters and bench workers must not
bleed cached device blocks into each other, same per-node rule as the
metrics registries.
"""

import os

from bqueryd_tpu.utils.cache import BytesCappedCache

#: segments holding DEVICE buffers, in memory-pressure eviction order —
#: blocks first: they are the biggest and the cheapest to rebuild from the
#: still-cached host alignment
DEVICE_SEGMENTS = ("blocks", "codes")

#: every segment, in eviction-preference order (device blocks first: they
#: are the biggest and the cheapest to rebuild from the still-cached host
#: alignment)
SEGMENTS = ("blocks", "codes", "align")

_DEFAULT_BUDGETS = {
    # host alignment cache (dense codes + combos + dictionaries)
    "align": ("BQUERYD_TPU_ALIGN_CACHE_BYTES", 512 * 1024**2),
    # HBM folded group codes (one entry per (table set, keys, filter))
    "codes": ("BQUERYD_TPU_CODES_CACHE_BYTES", 256 * 1024**2),
    # HBM packed measure blocks (one entry per (table set, column))
    "blocks": ("BQUERYD_TPU_HBM_CACHE_BYTES", 1024 * 1024**2),
}


def _budget(segment):
    env, default = _DEFAULT_BUDGETS[segment]
    try:
        # bqtpu: allow[config-dynamic-env-key] keys come from _DEFAULT_BUDGETS above; all three are in ENV_REGISTRY
        return int(os.environ.get(env, default))
    except ValueError:
        import logging

        logging.getLogger("bqueryd_tpu").warning(
            "unparseable %s, using default %d", env, default
        )
        return default


def evict_watermark():
    """Fraction of ``bytes_limit`` above which device cache is shed
    (``BQUERYD_TPU_HBM_EVICT_WATERMARK``, default 0.9; <=0 disables)."""
    try:
        return float(os.environ.get("BQUERYD_TPU_HBM_EVICT_WATERMARK", 0.9))
    except ValueError:
        return 0.9


def _device_nbytes(value):
    """Accounted size of a device array (jax.Array exposes nbytes)."""
    return getattr(value, "nbytes", 0)


class WorkingSet:
    """Named LRU cache segments + the device-memory-pressure eviction policy
    (module docstring)."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    #: (lock-unguarded-attr).  ``_segments`` is read-only after __init__
    #: (the per-segment caches carry their own locks), so only the
    #: pressure-eviction counter is guarded.
    _bqtpu_guarded_ = {"_pressure_lock": ("pressure_evictions",)}

    def __init__(self, budgets=None):
        import threading

        budgets = budgets or {}
        self._segments = {
            name: BytesCappedCache(
                budgets.get(name, _budget(name)), sizeof=_device_nbytes
            )
            for name in SEGMENTS
        }
        self.pressure_evictions = 0  # entries shed by the watermark policy
        self._pressure_lock = threading.Lock()

    def segment(self, name):
        return self._segments[name]

    def clear(self):
        for cache in self._segments.values():
            cache.clear()

    def stats(self):
        """Per-segment counters + the pressure-eviction total (JSON-safe,
        feeds the worker gauges and bench's ``pipeline`` section)."""
        out = {
            name: cache.stats() for name, cache in self._segments.items()
        }
        with self._pressure_lock:
            out["pressure_evictions"] = self.pressure_evictions
        return out

    # -- memory pressure -----------------------------------------------------
    def evict_under_pressure(self, sample=None, watermark=None):
        """Shed LRU device-segment entries while HBM usage sits above the
        watermark.  ``sample`` is a ``{"bytes_in_use", "bytes_limit", ...}``
        dict (default: the live profiler sample; None — CPU backends,
        unproven tunnels — is a no-op).  Returns bytes freed (accounted
        cache bytes, a proxy for the HBM the dropped references release at
        the allocator's next sweep).

        Eviction order is ``blocks`` before ``codes``: measure blocks are
        the bulk of residency and rebuild from the still-cached host
        alignment with one decode+H2D, while codes rebuilding also re-runs
        mask folding."""
        if watermark is None:
            watermark = evict_watermark()
        if watermark <= 0:
            return 0
        if sample is None:
            from bqueryd_tpu.obs import profile

            sample = profile.profiler().memory_sample()
        if not sample:
            return 0
        limit = sample.get("bytes_limit") or 0
        in_use = sample.get("bytes_in_use") or 0
        if limit <= 0 or in_use <= watermark * limit:
            return 0
        target = int(in_use - watermark * limit)
        freed = 0
        for name in DEVICE_SEGMENTS:
            cache = self._segments[name]
            seg_freed, seg_count = cache.evict_bytes(target - freed)
            freed += seg_freed
            with self._pressure_lock:
                self.pressure_evictions += seg_count
            if freed >= target:
                break
        if freed:
            import logging

            logging.getLogger("bqueryd_tpu").info(
                "HBM watermark pressure: shed %d cached device bytes "
                "(in_use %d > %.0f%% of limit %d)",
                freed, in_use, watermark * 100, limit,
            )
        return freed


# -- delta-maintained hot aggregates (streaming ingest) ---------------------
#
# The serving-layer upgrade of the working set: a cached groupby result for
# a shard group whose ctables only GREW (the streaming-append signature) is
# refreshed by running the kernels over the appended chunks alone and
# merging the delta partial into the cached partial through the same
# value-keyed hostmerge forms every cross-shard merge uses — sum/count/
# count_na/min/max merge exactly, mean merges through its (sum, count)
# partials.  Non-mergeable shapes (distinct counts, basket expansion, raw
# rows) never enter; the existing identity-keyed (meta inode + row count)
# invalidation of every other cache remains the correctness backstop: any
# non-append change (reshard, activation, rewrite) fails the chunk-prefix
# validation below and drops the entry to a full recompute.

def delta_serve_enabled():
    """Delta maintenance kill switch (``BQUERYD_TPU_DELTA_SERVE``,
    default on)."""
    return os.environ.get("BQUERYD_TPU_DELTA_SERVE", "1") == "1"


def _delta_budget():
    try:
        return int(
            os.environ.get(
                "BQUERYD_TPU_DELTA_CACHE_BYTES", 128 * 1024**2
            )
        )
    except ValueError:
        return 128 * 1024**2


def table_growth_base(table):
    """The append-diff base of one table INSTANCE: its committed per-column
    chunk indexes + row count, captured from the snapshot the computation
    actually read.  None when the table exposes no committed chunk grid
    (legacy formats, torn state) — such tables never delta-serve."""
    committed = getattr(table, "committed_chunks", None)
    if committed is None:
        return None
    cols = {}
    for name in table.names:
        snap = committed(name)
        if snap is None:
            return None
        cols[name] = [dict(c) for c in snap]
    return {
        "rows": int(table.nrows),
        "names": list(table.names),
        "cols": cols,
    }


def growth_since(base, table):
    """The NEW committed chunk ids of ``table`` relative to ``base``
    (possibly empty), or None when the table is not an append-only growth
    of the base.  Validation is exact: the base's chunk dicts (offset,
    csize, crc, zone map) must be a verbatim prefix of the current index
    for EVERY column — any rewrite mismatches and the caller recomputes."""
    if base is None or not isinstance(base, dict):
        return None
    committed = getattr(table, "committed_chunks", None)
    if committed is None:
        return None
    if list(table.names) != base.get("names"):
        return None
    if int(table.nrows) < base.get("rows", 0):
        return None
    new_ids = None
    grown_rows = None
    for name, bchunks in base.get("cols", {}).items():
        cur = committed(name)
        if cur is None or len(cur) < len(bchunks):
            return None
        if cur[: len(bchunks)] != bchunks:
            return None
        ids = list(range(len(bchunks), len(cur)))
        rows = sum(int(c["nrows"]) for c in cur[len(bchunks):])
        if new_ids is None:
            new_ids, grown_rows = ids, rows
        elif ids != new_ids or rows != grown_rows:
            return None  # desynchronized chunk grid: not a clean append
    if new_ids is None:
        new_ids, grown_rows = [], 0
    if grown_rows != int(table.nrows) - base["rows"]:
        return None
    return new_ids


class DeltaAggCache:
    """Byte-bounded cache of delta-maintainable aggregate results.

    Entries are keyed by (table identity tuple, query signature) —
    supplied by the worker — and hold the serialized merged
    :class:`~bqueryd_tpu.models.query.ResultPayload` plus the growth base
    of every table it covers.  ``refresh_ids`` validates a later lookup
    against live tables and names the appended chunks to re-aggregate."""

    def __init__(self, max_bytes=None):
        self._cache = BytesCappedCache(
            _delta_budget() if max_bytes is None else max_bytes
        )
        #: cached results refreshed by aggregating only appended chunks
        self.refreshes = 0
        #: rows the delta kernels aggregated instead of the full tables
        self.delta_rows = 0

    def get(self, key):
        return self._cache.get(key)

    def discard(self, key):
        self._cache.delete(key)

    def store(self, key, tables, data):
        """Record ``data`` (serialized payload bytes) as the delta base for
        ``tables`` — a no-op when any table exposes no growth base."""
        bases = [table_growth_base(t) for t in tables]
        if any(b is None for b in bases):
            return False
        # refreshes REPLACE the entry (put() keeps an existing key)
        self._cache.delete(key)
        self._cache.put(
            key, {"bases": bases, "data": data}, nbytes=len(data)
        )
        return True

    def refresh_ids(self, entry, tables):
        """Per-table NEW chunk ids for a cached entry against live tables,
        or None when any table is not an append-only growth of its base
        (the caller drops the entry and recomputes)."""
        bases = entry.get("bases") or []
        if len(bases) != len(tables):
            return None
        out = []
        for base, table in zip(bases, tables):
            ids = growth_since(base, table)
            if ids is None:
                return None
            out.append(ids)
        return out

    @property
    def nbytes(self):
        return self._cache.nbytes

    def clear(self):
        self._cache.clear()

    def stats(self):
        out = self._cache.stats()
        out["refreshes"] = self.refreshes
        out["delta_rows"] = self.delta_rows
        return out
