"""Device-resident working-set cache: codes + measure blocks + alignment.

The executor's steady-state serving story is cache residency: host key
alignment (dictionary-sized), factorized+folded group codes (HBM), and
wire-dtype measure blocks (HBM).  Before this module those were three
ad-hoc ``BytesCappedCache`` instances with wholesale eviction and no
telemetry; this promotes them into one named working-set layer:

* **content-keyed segments** — ``align`` (host: dense codes + global
  dictionaries per (table set, groupby columns)), ``codes`` (device:
  packed+folded group codes per (table set, groupby columns, filter)),
  ``blocks`` (device: packed wire-dtype measure columns per (table set,
  column)).  Keys carry the shard identity (rootdir + meta.json
  inode/mtime + rows, :func:`bqueryd_tpu.storage.ctable.table_cache_key`),
  so activation invalidates naturally and a repeat query with a DIFFERENT
  measure or filter still hits the codes/alignment segments — it skips
  decode, factorize and the codes H2D entirely instead of requiring an
  exact serialized-result hit.
* **LRU byte budgets per segment** (see the env vars below), with
  hit/miss/eviction counters exported as worker gauges
  (``bqueryd_tpu_workingset_*{segment=...}``) and into bench.py's
  ``pipeline`` section.
* **eviction under device-memory pressure** —
  :meth:`WorkingSet.evict_under_pressure` reads the PR-3 HBM watermark
  sample (``obs.profile.profiler().memory_sample()``) and evicts LRU
  device entries until usage projects below
  ``BQUERYD_TPU_HBM_EVICT_WATERMARK`` x ``bytes_limit`` — shedding cache
  before the allocator hits RESOURCE_EXHAUSTED and ``DeviceHealth``
  latches the backend as wedged.

A :class:`WorkingSet` is per-executor (the worker owns one mesh executor),
not process-global: in-process test clusters and bench workers must not
bleed cached device blocks into each other, same per-node rule as the
metrics registries.
"""

import os

from bqueryd_tpu.utils.cache import BytesCappedCache

#: segments holding DEVICE buffers, in memory-pressure eviction order —
#: blocks first: they are the biggest and the cheapest to rebuild from the
#: still-cached host alignment
DEVICE_SEGMENTS = ("blocks", "codes")

#: every segment, in eviction-preference order (device blocks first: they
#: are the biggest and the cheapest to rebuild from the still-cached host
#: alignment)
SEGMENTS = ("blocks", "codes", "align")

_DEFAULT_BUDGETS = {
    # host alignment cache (dense codes + combos + dictionaries)
    "align": ("BQUERYD_TPU_ALIGN_CACHE_BYTES", 512 * 1024**2),
    # HBM folded group codes (one entry per (table set, keys, filter))
    "codes": ("BQUERYD_TPU_CODES_CACHE_BYTES", 256 * 1024**2),
    # HBM packed measure blocks (one entry per (table set, column))
    "blocks": ("BQUERYD_TPU_HBM_CACHE_BYTES", 1024 * 1024**2),
}


def _budget(segment):
    env, default = _DEFAULT_BUDGETS[segment]
    try:
        # bqtpu: allow[config-dynamic-env-key] keys come from _DEFAULT_BUDGETS above; all three are in ENV_REGISTRY
        return int(os.environ.get(env, default))
    except ValueError:
        import logging

        logging.getLogger("bqueryd_tpu").warning(
            "unparseable %s, using default %d", env, default
        )
        return default


def evict_watermark():
    """Fraction of ``bytes_limit`` above which device cache is shed
    (``BQUERYD_TPU_HBM_EVICT_WATERMARK``, default 0.9; <=0 disables)."""
    try:
        return float(os.environ.get("BQUERYD_TPU_HBM_EVICT_WATERMARK", 0.9))
    except ValueError:
        return 0.9


def _device_nbytes(value):
    """Accounted size of a device array (jax.Array exposes nbytes)."""
    return getattr(value, "nbytes", 0)


class WorkingSet:
    """Named LRU cache segments + the device-memory-pressure eviction policy
    (module docstring)."""

    #: lock discipline, statically checked by bqueryd_tpu.analysis
    #: (lock-unguarded-attr).  ``_segments`` is read-only after __init__
    #: (the per-segment caches carry their own locks), so only the
    #: pressure-eviction counter is guarded.
    _bqtpu_guarded_ = {"_pressure_lock": ("pressure_evictions",)}

    def __init__(self, budgets=None):
        import threading

        budgets = budgets or {}
        self._segments = {
            name: BytesCappedCache(
                budgets.get(name, _budget(name)), sizeof=_device_nbytes
            )
            for name in SEGMENTS
        }
        self.pressure_evictions = 0  # entries shed by the watermark policy
        self._pressure_lock = threading.Lock()

    def segment(self, name):
        return self._segments[name]

    def clear(self):
        for cache in self._segments.values():
            cache.clear()

    def stats(self):
        """Per-segment counters + the pressure-eviction total (JSON-safe,
        feeds the worker gauges and bench's ``pipeline`` section)."""
        out = {
            name: cache.stats() for name, cache in self._segments.items()
        }
        with self._pressure_lock:
            out["pressure_evictions"] = self.pressure_evictions
        return out

    # -- memory pressure -----------------------------------------------------
    def evict_under_pressure(self, sample=None, watermark=None):
        """Shed LRU device-segment entries while HBM usage sits above the
        watermark.  ``sample`` is a ``{"bytes_in_use", "bytes_limit", ...}``
        dict (default: the live profiler sample; None — CPU backends,
        unproven tunnels — is a no-op).  Returns bytes freed (accounted
        cache bytes, a proxy for the HBM the dropped references release at
        the allocator's next sweep).

        Eviction order is ``blocks`` before ``codes``: measure blocks are
        the bulk of residency and rebuild from the still-cached host
        alignment with one decode+H2D, while codes rebuilding also re-runs
        mask folding."""
        if watermark is None:
            watermark = evict_watermark()
        if watermark <= 0:
            return 0
        if sample is None:
            from bqueryd_tpu.obs import profile

            sample = profile.profiler().memory_sample()
        if not sample:
            return 0
        limit = sample.get("bytes_limit") or 0
        in_use = sample.get("bytes_in_use") or 0
        if limit <= 0 or in_use <= watermark * limit:
            return 0
        target = int(in_use - watermark * limit)
        freed = 0
        for name in DEVICE_SEGMENTS:
            cache = self._segments[name]
            seg_freed, seg_count = cache.evict_bytes(target - freed)
            freed += seg_freed
            with self._pressure_lock:
                self.pressure_evictions += seg_count
            if freed >= target:
                break
        if freed:
            import logging

            logging.getLogger("bqueryd_tpu").info(
                "HBM watermark pressure: shed %d cached device bytes "
                "(in_use %d > %.0f%% of limit %d)",
                freed, in_use, watermark * 100, limit,
            )
        return freed
