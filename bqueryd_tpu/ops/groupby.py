"""Segment-reduction groupby kernels.

The TPU replacement for bquery's Cython ``ctable.groupby`` (the only place
real computation happens in the reference, reference bqueryd/worker.py:311-314).
Design:

* group keys arrive as dense int codes (see :mod:`bqueryd_tpu.ops.factorize`);
  the kernel is pure segment arithmetic — ``segment_sum`` / ``segment_min`` /
  ``segment_max`` over static ``num_segments`` — so XLA sees static shapes and
  fuses the mask/NaN handling into the scatter pass;
* results are produced as **partial tables** (pytrees of fixed-width arrays,
  e.g. mean = {sum, count}) that are closed under elementwise merge: merging
  shard partials is ``combine_partials`` on host/device or ``psum_partials``
  over a mesh axis, and only :func:`finalize` turns partials into final
  values.  This is what moves the reference's tar-merge + client re-groupby
  (reference bqueryd/controller.py:186-211, rpc.py:150-173) onto the
  interconnect — and fixes the reference's sum-of-shard-means quirk
  (reference bqueryd/rpc.py:171), since mean partials carry (sum, count).

Aggregation ops supported: the bquery set (sum, mean, count, count_na,
count_distinct, sorted_count_distinct) plus min/max.
"""

import functools

import jax
import jax.numpy as jnp

# canonical definitions live JAX-free in models.query (the controller needs
# them to decide shard batching without importing jax); re-exported here
from bqueryd_tpu.models.query import AGG_OPS, MERGEABLE_OPS  # noqa: F401


def _accum_dtype(dtype):
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer) or jnp.issubdtype(dtype, jnp.bool_):
        return jnp.int64
    return dtype  # float32 stays float32, float64 stays float64


def _null_mask(values):
    if jnp.issubdtype(values.dtype, jnp.floating):
        return jnp.isnan(values)
    return jnp.zeros(values.shape, dtype=bool)


#: rows per scatter block in the exact-int64 segment sum; bounds every block
#: partial below 2^16 (max limb) * 2^14 = 2^30 < int32 overflow
_SUM_BLOCK = 16384

#: above this many scatter buckets (blocks x groups) the blocked decomposition
#: stops paying for itself in HBM; fall back to the direct s64 scatter
_MAX_BLOCK_SEGMENTS = 1 << 25


def _int64_segment_sum(values, valid, safe, n_groups):
    """Exact per-group int64 sums of integer ``values`` without any int64
    scatter.

    TPUs emulate s64 (`jax's x64 mode <https://docs.jax.dev>`_) and the
    emulated scatter-add behind ``segment_sum`` dominates the whole query
    (~5x the cost of the s32 scatter at 10 M rows, measured on v5e).  Instead:
    split values into 16-bit limbs (elementwise s64 ops are cheap — only the
    scatter is not), scatter each limb in int32 over ``blocks x groups``
    buckets so no bucket can overflow, then reduce the per-block tables in
    int64 and recombine limbs with shifts.  Bit-exact for the full int64
    range."""
    n = values.shape[0]
    v = jnp.where(valid, values, 0)
    nbits = values.dtype.itemsize * 8
    n_blocks = -(-n // _SUM_BLOCK)
    if n_blocks * n_groups > _MAX_BLOCK_SEGMENTS:
        return jax.ops.segment_sum(
            v.astype(jnp.int64), safe, num_segments=n_groups
        )
    if nbits <= 16:
        limbs = [(v.astype(jnp.int32), 0)]
    else:
        n_limbs = nbits // 16
        limbs = [
            (((v >> (16 * i)) & 0xFFFF).astype(jnp.int32), 16 * i)
            for i in range(n_limbs - 1)
        ]
        # top limb keeps the sign via arithmetic shift
        limbs.append(
            ((v >> (16 * (n_limbs - 1))).astype(jnp.int32),
             16 * (n_limbs - 1))
        )
    pad = n_blocks * _SUM_BLOCK - n
    safe_p = jnp.pad(safe, (0, pad))
    ids = (
        jnp.arange(n_blocks * _SUM_BLOCK, dtype=jnp.int32) // _SUM_BLOCK
    ) * n_groups + safe_p
    total = jnp.zeros(n_groups, dtype=jnp.int64)
    for limb, shift in limbs:
        part = jax.ops.segment_sum(
            jnp.pad(limb, (0, pad)), ids, num_segments=n_blocks * n_groups
        )
        block_sums = part.reshape(n_blocks, n_groups).astype(jnp.int64).sum(0)
        total = total + (block_sums << shift)
    return total


@functools.partial(jax.jit, static_argnames=("n_groups", "ops"))
def partial_tables(codes, measures, ops, n_groups, mask=None):
    """Compute per-group partial tables for one shard.

    codes:    int[n] dense group codes in [0, n_groups); negative = null key
              (row dropped, matching pandas groupby's NaN-key behaviour)
    measures: tuple of value arrays [n], one per aggregation
    ops:      static tuple of op names aligned with measures (MERGEABLE_OPS)
    mask:     optional bool[n] row filter (where_terms pushdown)

    Returns a pytree: {"rows": int64[n_groups],
                       "aggs": tuple of per-measure partial dicts}.
    """
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    safe = jnp.where(valid, codes, 0).astype(jnp.int32)

    seg_sum = functools.partial(
        jax.ops.segment_sum, segment_ids=safe, num_segments=n_groups
    )

    def int_count(flags):  # bool[n] -> int64[n_groups], no s64 scatter
        return _int64_segment_sum(flags.astype(jnp.int8), flags, safe, n_groups)

    rows = int_count(valid)

    aggs = []
    for values, op in zip(measures, ops):
        if op not in MERGEABLE_OPS:
            raise ValueError(
                f"op {op!r} has no mergeable partial; use the dedicated kernel"
            )
        null = _null_mask(values)
        present = valid & ~null
        if op in ("sum", "mean"):
            if jnp.issubdtype(values.dtype, jnp.floating):
                contrib = jnp.where(present, values, 0).astype(
                    _accum_dtype(values.dtype)
                )
                partial = {"sum": seg_sum(contrib)}
            else:
                partial = {
                    "sum": _int64_segment_sum(values, present, safe, n_groups)
                }
            if op == "mean":
                partial["count"] = int_count(present)
            aggs.append(partial)
        elif op == "count":
            aggs.append({"count": int_count(present)})
        elif op == "count_na":
            aggs.append({"count": int_count(valid & null)})
        elif op == "min":
            big = (
                jnp.inf
                if jnp.issubdtype(values.dtype, jnp.floating)
                else jnp.iinfo(values.dtype).max
            )
            fill = jnp.where(present, values, big)
            aggs.append(
                {
                    "min": jax.ops.segment_min(fill, safe, num_segments=n_groups),
                    "count": int_count(present),
                }
            )
        elif op == "max":
            small = (
                -jnp.inf
                if jnp.issubdtype(values.dtype, jnp.floating)
                else jnp.iinfo(values.dtype).min
            )
            fill = jnp.where(present, values, small)
            aggs.append(
                {
                    "max": jax.ops.segment_max(fill, safe, num_segments=n_groups),
                    "count": int_count(present),
                }
            )
    return {"rows": rows, "aggs": tuple(aggs)}


def combine_partials(a, b):
    """Merge two partial-table pytrees (host- or device-side tree reduce)."""
    rows = a["rows"] + b["rows"]
    aggs = []
    for pa, pb in zip(a["aggs"], b["aggs"]):
        merged = {}
        for key in pa:
            if key == "min":
                merged[key] = jnp.minimum(pa[key], pb[key])
            elif key == "max":
                merged[key] = jnp.maximum(pa[key], pb[key])
            else:  # sum / count
                merged[key] = pa[key] + pb[key]
        aggs.append(merged)
    return {"rows": rows, "aggs": tuple(aggs)}


def psum_partials(partials, axis_name):
    """Merge partials across a mesh axis with XLA collectives: psum for
    sums/counts, pmin/pmax for extrema.  This is the ICI merge that replaces
    the reference's controller tar-merge."""
    rows = jax.lax.psum(partials["rows"], axis_name)
    aggs = []
    for partial in partials["aggs"]:
        merged = {}
        for key, value in partial.items():
            if key == "min":
                merged[key] = jax.lax.pmin(value, axis_name)
            elif key == "max":
                merged[key] = jax.lax.pmax(value, axis_name)
            else:
                merged[key] = jax.lax.psum(value, axis_name)
        aggs.append(merged)
    return {"rows": rows, "aggs": tuple(aggs)}


def finalize(partials, ops):
    """Turn merged partials into final per-group aggregate arrays.

    mean = sum / count (correct weighted mean across shards — deliberately
    NOT the reference's sum-of-shard-means, reference bqueryd/rpc.py:171).
    Groups with no contributing rows yield NaN for mean/min/max and 0 for
    sum/count, matching pandas.
    """
    out = []
    for partial, op in zip(partials["aggs"], ops):
        if op == "mean":
            count = partial["count"]
            out.append(
                jnp.where(
                    count > 0,
                    partial["sum"] / jnp.maximum(count, 1),
                    jnp.nan,
                )
            )
        elif op in ("sum",):
            out.append(partial["sum"])
        elif op in ("count", "count_na"):
            out.append(partial["count"])
        elif op in ("min", "max"):
            value = partial[op]
            empty = partial["count"] == 0
            if jnp.issubdtype(value.dtype, jnp.floating):
                # empty groups -> NaN by count, never by value: genuine
                # +/-inf data must survive
                out.append(jnp.where(empty, jnp.nan, value))
            else:
                # int columns have no NaN; empty groups report 0 and are
                # dropped upstream by the rows>0 filter
                out.append(jnp.where(empty, 0, value))
        else:
            raise ValueError(f"cannot finalize op {op!r}")
    return tuple(out)


def groupby_aggregate(codes, measures, ops, n_groups, mask=None):
    """Single-shard convenience: partials -> finalize in one call.

    Returns ``(tables, rows)`` where ``tables[i]`` is the aggregate array for
    ``ops[i]`` (shape [n_groups]) and ``rows`` counts valid rows per group
    (used to drop never-seen groups)."""
    ops = tuple(ops)
    partials = partial_tables(codes, tuple(measures), ops, n_groups, mask)
    return finalize(partials, ops), partials["rows"]


@functools.partial(jax.jit, static_argnames=("n_groups", "n_values"))
def groupby_count_distinct(codes, value_codes, n_groups, n_values, mask=None):
    """Distinct-value count per group via sort + boundary detection.

    ``value_codes`` are dense codes of the measure values (host-factorized).
    Static shapes throughout: sort of [n], then a segment_sum of boundary
    flags.  O(n log n) but bandwidth-friendly on TPU."""
    valid = (codes >= 0) & (value_codes >= 0)
    if mask is not None:
        valid = valid & mask
    composite = jnp.where(
        valid, codes.astype(jnp.int64) * n_values + value_codes, jnp.int64(-1)
    )
    ordered = jnp.sort(composite)
    first = jnp.concatenate(
        [jnp.array([True]), ordered[1:] != ordered[:-1]]
    )
    is_new = first & (ordered >= 0)
    group_of = jnp.where(is_new, ordered // n_values, 0).astype(jnp.int32)
    return jax.ops.segment_sum(
        is_new.astype(jnp.int64), group_of, num_segments=n_groups
    )


def expand_mask_by_group(group_codes, mask, n_groups=None):
    """Expand a row mask to whole groups: every row whose group contains at
    least one selected row becomes selected (the basket-expansion semantics of
    ``is_in_ordered_subgroups(basket_col, bool_arr)`` at reference
    bqueryd/worker.py:306-307, without requiring sorted input).

    segment-max of the mask over group codes, gathered back to rows.
    Negative codes (null baskets) are never selected.  Pass ``n_groups`` (the
    dense code cardinality) to keep the scatter O(groups); it defaults to the
    safe-but-wasteful row count."""
    if mask is None:
        return None
    group_codes = jnp.asarray(group_codes)
    if n_groups is None:
        n_groups = group_codes.shape[0]
    return _expand_mask_jit(group_codes, jnp.asarray(mask), int(n_groups))


@functools.partial(jax.jit, static_argnames=("n_groups",))
def _expand_mask_jit(group_codes, mask, n_groups):
    valid = group_codes >= 0
    safe = jnp.where(valid, group_codes, 0).astype(jnp.int32)
    hit = jax.ops.segment_max(
        (mask & valid).astype(jnp.int32), safe, num_segments=max(n_groups, 1),
    )
    return (hit[safe] > 0) & valid


@functools.partial(jax.jit, static_argnames=("n_groups",))
def groupby_sorted_count_distinct(codes, values, n_groups, mask=None):
    """bquery's ``sorted_count_distinct``: counts value *runs* per group,
    assuming rows are pre-sorted by value within each group (reference
    bquery API surface; run-boundary semantics).  Works on raw values (no
    factorize needed) since only adjacent comparison matters."""
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    # Run boundaries must be measured against the previous *valid* row (a
    # masked-out row in the middle of a run must not split or hide it):
    # last-valid-index-before-i via an exclusive cumulative max.
    idx = jnp.arange(codes.shape[0])
    marked = jnp.where(valid, idx, -1)
    last_valid = jax.lax.cummax(marked)
    prev_idx = jnp.concatenate([jnp.array([-1]), last_valid[:-1]])
    has_prev = prev_idx >= 0
    gather = jnp.clip(prev_idx, 0, None)
    same = (
        has_prev
        & (codes[gather] == codes)
        & (values[gather] == values)
    )
    is_new_run = valid & ~same
    safe = jnp.where(valid, codes, 0).astype(jnp.int32)
    return jax.ops.segment_sum(
        is_new_run.astype(jnp.int64), safe, num_segments=n_groups
    )
