"""Segment-reduction groupby kernels.

The TPU replacement for bquery's Cython ``ctable.groupby`` (the only place
real computation happens in the reference, reference bqueryd/worker.py:311-314).
Design:

* group keys arrive as dense int codes (see :mod:`bqueryd_tpu.ops.factorize`);
* the hot reduction (sums and counts) runs on the **MXU as a one-hot
  matmul**, not a scatter: XLA lowers ``segment_sum`` to scatter-add, which
  on TPU costs ~90 ms for 10 M rows (and ~9x that again in emulated-s64
  mode), while the same contraction as ``limbs[blocks, R, K] x
  one_hot(codes)[blocks, K, G]`` rides the systolic array in ~1-4 ms.
  Exactness is preserved by 8-bit limb decomposition: every value is biased
  to unsigned, split into byte limbs (each exactly representable in
  bfloat16), and block sums are bounded below 2^24 so the MXU's float32
  accumulation is exact; per-block tables are then recombined in uint64
  (mod-2^64 arithmetic == two's complement) — bit-exact for the full int64
  range.  Counts ride along as a row of ones in the same matmul.  min/max,
  float64 measures, and cardinalities above ``matmul_groups_limit()`` use
  the scatter path: exact 16-bit-limb int32 scatters over 64Ki row blocks
  (mod-2^32 wrap recovered by a uint32 bitcast), switching to a sort +
  prefix-diff reduction at extreme cardinality where the blocked table
  would outgrow ``_MAX_BLOCK_SEGMENTS`` — never the emulated-s64 scatter.
  A pure-NumPy twin (:func:`host_partial_tables`) serves latency-aware
  host routing for small inputs;
* results are produced as **partial tables** (pytrees of fixed-width arrays,
  e.g. mean = {sum, count}) that are closed under elementwise merge: merging
  shard partials is ``combine_partials`` on host/device or ``psum_partials``
  over a mesh axis, and only :func:`finalize` turns partials into final
  values.  This is what moves the reference's tar-merge + client re-groupby
  (reference bqueryd/controller.py:186-211, rpc.py:150-173) onto the
  interconnect — and fixes the reference's sum-of-shard-means quirk
  (reference bqueryd/rpc.py:171), since mean partials carry (sum, count).

Aggregation ops supported: the bquery set (sum, mean, count, count_na,
count_distinct, sorted_count_distinct) plus min/max.
"""

import functools
import os
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# canonical definitions live JAX-free in models.query (the controller needs
# them to decide shard batching without importing jax); re-exported here
from bqueryd_tpu.models.query import (  # noqa: F401
    AGG_OPS,
    MERGEABLE_OPS,
    extremum_fill,
)
# compile/call accounting on the jit entry points below (obs.profile is
# stdlib-only at import; the wrappers pass straight through under an outer
# trace and under the BQUERYD_TPU_METRICS=0 kill switch)
from bqueryd_tpu.obs import profile as _obsprofile


def _accum_dtype(dtype):
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer) or jnp.issubdtype(dtype, jnp.bool_):
        return jnp.int64
    if jnp.issubdtype(dtype, jnp.floating) and jax.config.jax_enable_x64:
        # f32 scatter sums accumulate in f64: the MXU path represents f32
        # losslessly via its 3-limb split, and the scatter path must match
        # that accuracy (a plain f32 segment_sum drifts ~1e-4 at 1M-row
        # groups, outside the bench's float gate)
        return jnp.float64
    return dtype


#: native host-groupby routing: below the row floor thread spawn overhead
#: beats the striping win; above the group ceiling the per-thread [G]
#: accumulators (16 B x workers x G) stop being cache/memory friendly
_NATIVE_GROUPBY_MIN_ROWS = 200_000
_NATIVE_GROUPBY_MAX_GROUPS = 1 << 18

#: float64 mantissa bound: a weighted bincount over int64 values is exact
#: iff every partial sum stays below this (|partial| <= n rows x max|v|).
#: Shared with the host-routing cost estimate (models.query), which must
#: rate queries beyond it at the limb-fallback cost.
HOST_EXACT_SUM_BOUND = 2**53


def _null_mask(values):
    if jnp.issubdtype(values.dtype, jnp.floating):
        return jnp.isnan(values)
    return jnp.zeros(values.shape, dtype=bool)


def _measure_null(values, sentinel):
    """Per-measure null rows, or None when the measure cannot be null.

    ``sentinel`` marks an integer encoding whose one reserved value means
    missing — datetime columns store NaT as int64 min (pandas convention) —
    so those rows must vanish from counts/extrema exactly like float NaNs.
    """
    if sentinel is not None:
        return values == jnp.asarray(sentinel, dtype=values.dtype)
    if jnp.issubdtype(values.dtype, jnp.floating):
        return jnp.isnan(values)
    return None


def _normalize_sentinels(null_sentinels, n):
    if null_sentinels is None:
        return (None,) * n
    t = tuple(
        None if s is None else int(s) for s in null_sentinels
    )
    if len(t) != n:
        raise ValueError(
            f"null_sentinels has {len(t)} entries for {n} measures"
        )
    return t


#: rows per scatter block in the exact-int64 segment sum.  A 16-bit limb's
#: block sum stays below ``2^16 (max limb) * 2^16 (rows) = 2^32``: exactly
#: representable in the int32 scatter's mod-2^32 arithmetic, recovered by a
#: uint32 bitcast (unsigned limbs) or plain sign extension (the top limb,
#: whose magnitude is bounded by 2^16 * 2^15 = 2^31).
_SUM_BLOCK = 65536

#: above this many scatter buckets (blocks x groups) the blocked decomposition
#: stops paying for itself in HBM; switch to the sort-based path
_MAX_BLOCK_SEGMENTS = 1 << 25


#: kernel routes partial_tables accepts as a planner hint (None == "auto").
#: "matmul" is advisory — every profitability/backend guard still applies —
#: while "scatter"/"sort" are binding (both are always-correct fallbacks).
#: "matmul!" is the CALIBRATION-BACKED form: binding inside the guards —
#: it bypasses only the op/dtype profitability heuristic, while the backend
#: guard and the groups/cells value guards (matmul_route_allowed) stand, so
#: the forced-matmul regression stays unreachable through any hint.
KERNEL_STRATEGIES = ("auto", "matmul", "scatter", "sort", "matmul!")


def _sorted_segment_sum(values, safe, n_groups, acc_dtype=jnp.int64):
    """Per-group sums without a wide scatter: sort rows by group code,
    prefix-sum the sorted values in ``acc_dtype``, and difference the prefix
    at group boundaries.  One O(n log n) device sort + cheap elementwise
    wide adds (only the SCATTER is expensive in emulated 64-bit arithmetic),
    and no ``blocks x groups`` table, so cost is independent of ``n_groups``.
    For int64 the wrapping (mod 2^64) prefix sums difference back exactly —
    bit-exact for the full range; for float64 accumulation the prefix-diff
    matches direct summation to ~1 ulp of the running prefix."""
    codes_s, order = lax.sort(
        (safe, jnp.arange(safe.shape[0], dtype=jnp.int32)), num_keys=1
    )
    v_s = values[order].astype(acc_dtype)
    prefix = jnp.cumsum(v_s)
    # one past the last row of each group (== prefix index of its total)
    ends = jnp.searchsorted(
        codes_s, jnp.arange(n_groups, dtype=codes_s.dtype), side="right"
    )
    zero = jnp.zeros(1, acc_dtype)
    bounds = jnp.concatenate([zero, prefix])[ends]
    return jnp.diff(jnp.concatenate([zero, bounds]))


def _int64_segment_sum(values, valid, safe, n_groups, force_sort=False):
    """Exact per-group int64 sums of integer ``values`` without any int64
    scatter.

    TPUs emulate s64 (`jax's x64 mode <https://docs.jax.dev>`_) and the
    emulated scatter-add behind ``segment_sum`` dominates the whole query
    (~5x the cost of the s32 scatter at 10 M rows, measured on v5e).  Instead:
    split values into 16-bit limbs (elementwise s64 ops are cheap — only the
    scatter is not), scatter each limb in int32 over ``blocks x groups``
    buckets, recover each bucket exactly (mod-2^32 wrap is invertible because
    a block's true limb sum is < 2^32), then reduce the per-block tables in
    uint64 and recombine limbs with shifts.  Bit-exact for the full int64
    range.  Past ``_MAX_BLOCK_SEGMENTS`` buckets (~extreme group counts) the
    sort-based path takes over instead of the emulated-s64 scatter that used
    to cost ~3 s at 10 M rows."""
    n = values.shape[0]
    v = jnp.where(valid, values, 0)
    nbits = values.dtype.itemsize * 8
    signed_in = jnp.issubdtype(values.dtype, jnp.signedinteger)
    n_blocks = -(-n // _SUM_BLOCK)
    if force_sort or n_blocks * n_groups > _MAX_BLOCK_SEGMENTS:
        return _sorted_segment_sum(v, safe, n_groups)
    # limbs: (int32 row, shift, signed). Non-top limbs are unsigned 16-bit
    # slices; the top limb carries the sign for signed inputs.
    if nbits <= 16:
        limbs = [(v.astype(jnp.int32), 0, signed_in)]
    else:
        n_limbs = nbits // 16
        limbs = [
            (((v >> (16 * i)) & 0xFFFF).astype(jnp.int32), 16 * i, False)
            for i in range(n_limbs - 1)
        ]
        # top limb keeps the sign via arithmetic shift (logical for unsigned)
        limbs.append(
            ((v >> (16 * (n_limbs - 1))).astype(jnp.int32),
             16 * (n_limbs - 1), signed_in)
        )
    pad = n_blocks * _SUM_BLOCK - n
    safe_p = jnp.pad(safe, (0, pad))
    ids = (
        jnp.arange(n_blocks * _SUM_BLOCK, dtype=jnp.int32) // _SUM_BLOCK
    ) * n_groups + safe_p
    total = jnp.zeros(n_groups, dtype=jnp.uint64)
    for limb, shift, signed in limbs:
        part = jax.ops.segment_sum(
            jnp.pad(limb, (0, pad)), ids, num_segments=n_blocks * n_groups
        ).reshape(n_blocks, n_groups)
        if signed:
            # |block sum| <= 2^16 * 2^15 = 2^31: no wrap, sign-extend
            bs = part.astype(jnp.int64).astype(jnp.uint64).sum(0)
        else:
            # true block sum < 2^16 * 2^16 = 2^32: the int32 wrap is exactly
            # the uint32 value
            bs = (
                lax.bitcast_convert_type(part, jnp.uint32)
                .astype(jnp.uint64)
                .sum(0)
            )
        total = total + (bs << jnp.uint64(shift))
    return total.astype(jnp.int64)


#: rows per MXU block: 8-bit limb block sums stay <= 32768 * 255 < 2^24, so
#: the MXU's float32 accumulation of a block is exact
_MATMUL_BLOCK = 32768


def program_bucket(n, fine=False):
    """Round a program-shape dimension UP onto a coarse grid so XLA programs
    are reused across data refreshes and cardinality drift.

    Static shapes are the TPU contract: every exact (rows, groups) pair is
    its own compile, which costs 20-40 s per program through a tunneled
    backend — while real serving data drifts a few percent per refresh.
    Grid: pow2/64 steps for row counts (``fine=True``, <=~3.2% padding) and
    pow2/16 for group counts (<=~12.5%, typically ~5%).  Padded groups get
    zero rows and are sliced off by callers after fetch; padded rows carry
    code -1 and vanish from every reduction.  BQUERYD_TPU_SHAPE_BUCKETS=0
    disables (exact shapes, maximum compiles)."""
    n = int(n)
    if n <= 16 or os.environ.get("BQUERYD_TPU_SHAPE_BUCKETS", "1") == "0":
        return max(n, 0)
    step = 1 << max((n - 1).bit_length() - (6 if fine else 4), 0)
    return -(-n // step) * step


def matmul_groups_limit():
    """Above this group cardinality the one-hot matmul's N*G FLOPs cost more
    than the scatter it replaces (crossover ~8-16k groups at 10 M rows on
    v5e); tune with BQUERYD_TPU_MATMUL_GROUPS (0 disables the MXU path)."""
    return int(os.environ.get("BQUERYD_TPU_MATMUL_GROUPS", 8192))


def _matmul_cells_limit():
    """Cap on rows * groups for the MXU path: bounds the one-hot contraction's
    FLOPs (and its worst-case materialized size, should an XLA version decline
    to fuse the one-hot into the dot).  Default ~6.9e10 cells = the measured
    10 M-row x 8k-group crossover on v5e."""
    return int(os.environ.get("BQUERYD_TPU_MATMUL_CELLS", 1 << 36))


def matmul_route_allowed(n, n_groups):
    """The MXU route's SAFETY guards, shared by the adaptive dispatcher and
    the calibration-backed binding hint: backend (the one-hot contraction
    emulates ~7x slower than the int32 scatter on CPU backends —
    BQUERYD_TPU_FORCE_MATMUL=1 overrides, pinned by the test suite for
    MXU-path coverage on the CPU test backend), group ceiling, and the
    rows x groups cells budget.  A "matmul!" hint that fails ANY of these
    demotes to the adaptive default — only the op/dtype profitability
    heuristic below yields to measurement."""
    if (
        jax.default_backend() == "cpu"
        and os.environ.get("BQUERYD_TPU_FORCE_MATMUL") != "1"
    ):
        return False
    if not (0 < n_groups <= matmul_groups_limit()):
        return False
    if n * n_groups > _matmul_cells_limit():
        return False
    return True


def _matmul_profitable(measures, ops, n, n_groups):
    """MXU path only when within budget AND some sum/count actually rides the
    matmul (min/max and float64 sums scatter regardless, so a query made only
    of those gains nothing from building the one-hot)."""
    if not matmul_route_allowed(n, n_groups):
        return False
    x64 = bool(jax.config.jax_enable_x64)
    for values, op in zip(measures, ops):
        if op in ("count", "count_na"):
            return True
        if op in ("sum", "mean") and not (
            x64 and jnp.dtype(values.dtype) == jnp.float64
        ):
            return True
    return not measures  # rows-count-only query still benefits


def _hicard_matmul_profitable(measures, ops, n, n_groups):
    """Whether the group-tiled Pallas MXU path should take a query past
    ``matmul_groups_limit``.  Opt-in (BQUERYD_TPU_PALLAS=1) until proven on
    hardware; INT sums/counts only — the kernel's in-kernel mod-2^32 limb
    accumulation has no wrap-free encoding for float Dekker limbs, and
    min/max ride dedicated scatter kernels regardless.  The pre-fix
    hardware sample for the 70k-group blocked scatter was 0.583 s at 10M
    rows; the one-hot contraction is ~1.4e12 bf16 MACs there, tens of ms
    at realistic MXU utilization."""
    from bqueryd_tpu.ops import pallas_groupby

    if not pallas_groupby.pallas_enabled():
        return False
    if (
        jax.default_backend() == "cpu"
        and os.environ.get("BQUERYD_TPU_FORCE_MATMUL") != "1"
    ):
        return False  # same CPU-emulation economics as _matmul_profitable
    if not (
        matmul_groups_limit()
        < n_groups
        <= pallas_groupby.hicard_groups_limit()
    ):
        return False
    if n > pallas_groupby.HICARD_MAX_ROWS:
        return False
    if not measures:
        return True  # rows-count-only query
    for values, op in zip(measures, ops):
        if op in ("count", "count_na"):
            continue
        if op not in ("sum", "mean"):
            return False  # min/max scatter anyway: no matmul rows to win
        dt = jnp.dtype(values.dtype)
        if jnp.issubdtype(dt, jnp.floating):
            return False
    # the stacked row count must fit the kernel's VMEM plan: one count row,
    # per-measure present rows (worst case), 8 limbs per 64-bit measure
    est_rows = 1 + sum(
        1 + (jnp.dtype(v.dtype).itemsize if v.dtype != jnp.bool_ else 1)
        for v in measures
    )
    return pallas_groupby.hicard_fits_vmem(est_rows)


def partial_tables(codes, measures, ops, n_groups, mask=None,
                   null_sentinels=None, strategy=None):
    """Compute per-group partial tables for one shard.

    codes:    int[n] dense group codes in [0, n_groups); negative = null key
              (row dropped, matching pandas groupby's NaN-key behaviour)
    measures: tuple of value arrays [n], one per aggregation
    ops:      static tuple of op names aligned with measures (MERGEABLE_OPS)
    mask:     optional bool[n] row filter (where_terms pushdown)
    null_sentinels: optional tuple aligned with measures; an int entry marks
              that integer value as the measure's missing-data encoding
              (datetime NaT = int64 min) so those rows skip counts/extrema
              the way float NaNs do.  sum/mean measures may not carry a
              sentinel (the engine rejects datetime sums as pandas-meaningless
              before reaching the kernels).

    Returns a pytree: {"rows": int64[n_groups],
                       "aggs": tuple of per-measure partial dicts}.

    Sums and counts route to the MXU one-hot matmul (module docstring) when
    the cardinality is within :func:`matmul_groups_limit`; min/max, float64
    measures, and high-cardinality queries use segment scatters.

    ``strategy`` is the planner's route hint (:data:`KERNEL_STRATEGIES`):
    ``"scatter"`` goes straight to the blocked scatters, ``"sort"`` to the
    scatter entry with the sort+prefix-diff reduction forced, and
    ``"matmul"``/``"auto"``/None keep the full profitability logic — the
    hint can steer toward the MXU but never override its backend guard (a
    CPU backend still declines, so a planner hint cannot reproduce the
    forced-matmul regression).
    """
    ops = tuple(ops)
    measures = tuple(measures)
    null_sentinels = _normalize_sentinels(null_sentinels, len(measures))
    for sentinel, op in zip(null_sentinels, ops):
        if sentinel is not None and op in ("sum", "mean"):
            # the MXU limb path contracts raw rows (exclusion rides the
            # one-hot of the SHARED codes, so per-measure nulls can't be
            # expressed there) and a sentinel sum is semantically undefined
            # anyway — the engine raises long before this
            raise ValueError(
                f"op {op!r} cannot aggregate a sentinel-null measure"
            )
    if strategy is not None and strategy not in KERNEL_STRATEGIES:
        raise ValueError(f"unknown kernel strategy {strategy!r}")
    if strategy == "scatter":
        return _partial_tables_scatter(
            codes, measures, ops, int(n_groups), mask,
            null_sentinels=null_sentinels,
        )
    if strategy == "sort":
        return _partial_tables_scatter(
            codes, measures, ops, int(n_groups), mask,
            null_sentinels=null_sentinels, force_sort=True,
        )
    if strategy == "matmul!" and matmul_route_allowed(
        int(codes.shape[0]), int(n_groups)
    ):
        # calibration-backed promotion: measurement overrides only the
        # op/dtype profitability heuristic — backend + value guards were
        # just enforced (a failed guard falls through to the adaptive
        # dispatch below, exactly as if the hint were advisory)
        from bqueryd_tpu.ops import pallas_groupby

        return _partial_tables_mm(
            codes, measures, ops, int(n_groups), mask,
            use_pallas=pallas_groupby.pallas_enabled()
            and int(n_groups) <= pallas_groupby.pallas_groups_limit(),
            null_sentinels=null_sentinels,
        )
    if _matmul_profitable(measures, ops, int(codes.shape[0]), int(n_groups)):
        # env flags are read HERE, outside jit, so toggling them takes effect
        # per call instead of being frozen into the first trace
        from bqueryd_tpu.ops import pallas_groupby

        return _partial_tables_mm(
            codes, measures, ops, int(n_groups), mask,
            # the Pallas kernel has its own (VMEM-bound) cardinality ceiling:
            # a raised BQUERYD_TPU_MATMUL_GROUPS must not push it past the
            # group count where its smallest one-hot tile still fits
            use_pallas=pallas_groupby.pallas_enabled()
            and int(n_groups) <= pallas_groupby.pallas_groups_limit(),
            null_sentinels=null_sentinels,
        )
    if _hicard_matmul_profitable(
        measures, ops, int(codes.shape[0]), int(n_groups)
    ):
        return _partial_tables_mm(
            codes, measures, ops, int(n_groups), mask,
            use_pallas="hicard",
            null_sentinels=null_sentinels,
        )
    return _partial_tables_scatter(
        codes, measures, ops, int(n_groups), mask,
        null_sentinels=null_sentinels,
    )


def bucketize_partials(partials, n_groups, n_buckets):
    """Re-emit a partial-table pytree on the key-span bucket layout: every
    leaf's group axis is padded from ``n_groups`` to ``span * n_buckets``
    (``span = ceil(n_groups / n_buckets)``) so bucket ``d`` — device ``d``
    of the merge mesh — owns the contiguous span ``[d*span, (d+1)*span)``.

    Returns ``(padded_partials, span)``.  Pad entries are zeros; they are
    appended PAST every real group, so sums/counts gain nothing and min/max
    pads can never shadow a real group — the collector slices the pad tail
    off after the fetch.  Trace-safe (``jnp.pad`` only), and called on the
    OUTPUT of :func:`partial_tables`, so every kernel guard (matmul
    backend/ceiling, scatter budgets, strategy hints) applies unchanged to
    the bucketized emission."""
    from bqueryd_tpu.parallel.devicemerge import bucket_span

    span, padded = bucket_span(n_groups, n_buckets)
    pad = padded - int(n_groups)
    if pad == 0:
        return partials, span
    out = jax.tree_util.tree_map(
        lambda leaf: jnp.pad(leaf, (0, pad)), partials
    )
    return out, span


def bundle_partial_tables(codes, masks, measures, member_specs, n_groups,
                          null_sentinels=None, strategy=None):
    """Stacked-mask shared-scan emission: per-member partial tables over ONE
    codes array and ONE set of deduplicated measure blocks.

    codes:        int[n] dense group codes, shared by every member (uploaded
                  once, unmasked — each member's filter applies per member)
    masks:        bool[n_masks, n] stacked row filters, one row per member
                  that carries a filter (members without one index None)
    measures:     tuple of value arrays [n], one per DISTINCT measure column
                  across the whole bundle (the union upload)
    member_specs: static tuple, one entry per member:
                  ``(mask_idx_or_None, ((measure_slot, op), ...))`` — which
                  stacked mask row (None = unfiltered) and which
                  (deduplicated measure block, op) pairs this member
                  aggregates
    null_sentinels: optional tuple aligned with ``measures`` (per distinct
                  column, same semantics as :func:`partial_tables`)

    Returns a tuple of per-member partial-table pytrees, each shaped exactly
    like :func:`partial_tables` produces for that member alone.

    On CPU backends this is the shared-scan KERNEL, not just a member
    loop: every (measure slot, op) family shared across members runs as
    ONE batched segment reduction over the ``[members, rows]`` stack of
    masked contributions — the scan/build work that dominates GROUP BY
    cost is paid once per bundle, not once per member (measured 4x+ over
    the member-at-a-time loop at bench shapes).  On accelerator backends
    the batched form would be exactly the emulated wide scatter
    (s64/f64 ``segment_sum``) that :func:`_int64_segment_sum` and
    :func:`_sorted_segment_sum` exist to avoid, so each member runs its
    own :func:`partial_tables` dispatch there instead — full guards, limb
    paths and MXU routes intact; the bundle still shares every host-side
    pass (decode/align/H2D/program dispatch), just not the reduction.
    Backend is read at trace time, like the solo kernels' own backend
    branches.  Exactness contract vs solo execution: integer partials are
    bit-identical (integer segment sums are order-exact under any
    reduction), float partials accumulate in the same widened dtype
    (:func:`_accum_dtype`) and differ from a member's solo route at most
    by reassociation — the same tolerance class as the matmul-vs-scatter
    route choice."""
    measures = tuple(measures)
    sentinels = _normalize_sentinels(null_sentinels, len(measures))
    for _mask_idx, aggs in member_specs:
        for slot, op in aggs:
            if op not in MERGEABLE_OPS and op != "count_na":
                raise ValueError(
                    f"op {op!r} has no mergeable partial; bundles carry "
                    "mergeable aggregations only"
                )
            if sentinels[slot] is not None and op in ("sum", "mean"):
                raise ValueError(
                    f"op {op!r} cannot aggregate a sentinel-null measure"
                )

    if jax.default_backend() != "cpu":
        return tuple(
            partial_tables(
                codes,
                tuple(measures[slot] for slot, _op in aggs),
                tuple(op for _slot, op in aggs),
                n_groups,
                mask=None if mask_idx is None else masks[mask_idx],
                null_sentinels=tuple(
                    sentinels[slot] for slot, _op in aggs
                ),
                strategy=strategy,
            )
            for mask_idx, aggs in member_specs
        )

    key_valid = codes >= 0
    safe = jnp.where(key_valid, codes, 0).astype(jnp.int32)
    n_groups = int(n_groups)

    # per-member validity stack (the shared scan's one mask fold)
    valids = tuple(
        key_valid if mask_idx is None else key_valid & masks[mask_idx]
        for mask_idx, _aggs in member_specs
    )

    def batched_count(flags_2d):
        """bool[k, n] -> int64[k, n_groups] in ONE segment pass.  Counts
        accumulate in int32 (a per-dispatch block holds < 2^31 rows) and
        widen to the partials' int64 contract after."""
        return jax.ops.segment_sum(
            flags_2d.T.astype(jnp.int32), safe, num_segments=n_groups
        ).T.astype(jnp.int64)

    rows_all = batched_count(jnp.stack(valids))  # [n_q, n_groups]

    nulls = {
        slot: _measure_null(measures[slot], sentinels[slot])
        for slot in {s for _m, aggs in member_specs for s, _o in aggs}
    }

    # job plan: one batched reduction per (measure slot, op) family across
    # every member that needs it
    jobs = {}
    for qi, (_mask_idx, aggs) in enumerate(member_specs):
        for ai, (slot, op) in enumerate(aggs):
            jobs.setdefault((slot, op), []).append((qi, ai))

    results = [
        [None] * len(aggs) for _mask_idx, aggs in member_specs
    ]
    for (slot, op), takers in jobs.items():
        values = measures[slot]
        null = nulls[slot]
        present = tuple(
            valids[qi] if null is None else valids[qi] & ~null
            for qi, _ai in takers
        )
        stacked = jnp.stack(present)  # [k, n]

        def taker_counts():
            if null is None:
                return tuple(rows_all[qi] for qi, _ai in takers)
            counted = batched_count(stacked)
            return tuple(counted[j] for j in range(len(takers)))

        if op in ("sum", "mean"):
            floating = jnp.issubdtype(values.dtype, jnp.floating)
            if floating or op == "mean":
                # integer means accumulate in float like pandas (and the
                # solo kernels) — see _partial_tables_scatter
                acc = _accum_dtype(
                    values.dtype if floating else jnp.float64
                )
            else:
                acc = jnp.int64
            contrib = jnp.where(stacked, values[None, :], 0).astype(acc)
            sums = jax.ops.segment_sum(
                contrib.T, safe, num_segments=n_groups
            ).T
            counts = taker_counts() if op == "mean" else None
            for j, (qi, ai) in enumerate(takers):
                part = {"sum": sums[j]}
                if op == "mean":
                    part["count"] = counts[j]
                results[qi][ai] = part
        elif op == "count":
            counts = taker_counts()
            for j, (qi, ai) in enumerate(takers):
                results[qi][ai] = {"count": counts[j]}
        elif op == "count_na":
            if null is None:
                zero = jnp.zeros(n_groups, dtype=jnp.int64)
                for qi, ai in takers:
                    results[qi][ai] = {"count": zero}
            else:
                na = batched_count(
                    jnp.stack(tuple(valids[qi] & null for qi, _ai in takers))
                )
                for j, (qi, ai) in enumerate(takers):
                    results[qi][ai] = {"count": na[j]}
        else:  # min / max
            src = values
            as_bool = src.dtype == jnp.bool_
            if as_bool:
                src = src.astype(jnp.uint8)  # bool has no iinfo
            fill = np.dtype(src.dtype).type(extremum_fill(src.dtype, op))
            data = jnp.where(stacked, src[None, :], fill)
            seg = jax.ops.segment_min if op == "min" else jax.ops.segment_max
            ext = seg(data.T, safe, num_segments=n_groups).T
            if as_bool:
                ext = ext.astype(jnp.bool_)
            counts = taker_counts()
            for j, (qi, ai) in enumerate(takers):
                results[qi][ai] = {op: ext[j], "count": counts[j]}

    return tuple(
        {"rows": rows_all[qi], "aggs": tuple(results[qi])}
        for qi in range(len(member_specs))
    )


def partial_tables_bucketized(codes, measures, ops, n_groups, n_buckets,
                              mask=None, null_sentinels=None, strategy=None):
    """:func:`partial_tables` with the output re-laid onto the
    ``n_buckets``-way key-span bucket layout (see
    :func:`bucketize_partials`) — the emission form the device-resident
    distributed merge consumes.  Same guards, same strategies, same
    partial semantics; only the group-axis padding differs."""
    partials = partial_tables(
        codes, measures, ops, n_groups, mask=mask,
        null_sentinels=null_sentinels, strategy=strategy,
    )
    return bucketize_partials(partials, n_groups, n_buckets)


def kernel_route(strategy, measures, ops, n, n_groups):
    """Predict the physical route :func:`partial_tables` takes for this
    dispatch WITHOUT running it — the ``effective_strategy`` reported in
    calc replies / kernel trace spans and the label calibration samples are
    recorded under.  Mirrors the dispatch above; ``measures`` only needs
    ``.dtype`` per entry (device arrays, numpy arrays, and dtype stubs all
    work).  Granularity note: the rare in-kernel demotions (a hicard Pallas
    plan that fails its VMEM recheck at trace time) are not modelled —
    those differ per XLA trace, not per dispatch."""
    n, n_groups = int(n), int(n_groups)
    if strategy == "scatter":
        return "scatter"
    if strategy == "sort":
        return "sort"
    if strategy == "matmul!" and matmul_route_allowed(n, n_groups):
        return "matmul"
    if _matmul_profitable(measures, tuple(ops), n, n_groups):
        return "matmul"
    if _hicard_matmul_profitable(measures, tuple(ops), n, n_groups):
        return "matmul"
    blocks = -(-n // _SUM_BLOCK)
    if blocks * n_groups > _MAX_BLOCK_SEGMENTS:
        return "sort"
    return "scatter"


def _segment_extremum(kind, values, present, safe, n_groups):
    """Per-group min/max via segment scatter; absent rows carry the identity
    fill so they never win (empty groups are masked later by count==0)."""
    if values.dtype == jnp.bool_:
        # bool has no iinfo; reduce as uint8 and view back
        ext = _segment_extremum(
            kind, values.astype(jnp.uint8), present, safe, n_groups
        )
        return ext.astype(jnp.bool_)
    # typed scalar, not a python int: uint64's max overflows the weak int64
    # a bare literal would trace as
    fill = np.dtype(values.dtype).type(extremum_fill(values.dtype, kind))
    seg = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    return seg(
        jnp.where(present, values, fill), safe, num_segments=n_groups
    )


def _blocked(arr, nb, pad, fill=0):
    """Pad a row vector to ``nb * _MATMUL_BLOCK`` and shape it ``[nb, K]``."""
    return jnp.pad(arr, (0, pad), constant_values=fill).reshape(
        nb, _MATMUL_BLOCK
    )


def _limb_rows(values, nbits):
    """8-bit unsigned limbs of biased values, each as an exact bfloat16 row.

    Signed inputs are biased by ``2^(nbits-1)`` into unsigned range; the
    wrap-around of the uint64 cast is harmless because only the low
    ``nbits/8`` limbs are extracted (arithmetic mod 2^nbits), and the bias is
    subtracted again group-wise (``count * bias``) after the merge."""
    signed = jnp.issubdtype(values.dtype, jnp.signedinteger)
    u = values.astype(jnp.uint64)
    bias = 0
    if signed:
        bias = int(1) << (nbits - 1)
        u = u + jnp.uint64(bias)
    rows = [
        (
            (lax.shift_right_logical(u, jnp.uint64(8 * i)) & jnp.uint64(0xFF))
            .astype(jnp.bfloat16)
        )
        for i in range(nbits // 8)
    ]
    return rows, bias


@functools.partial(
    jax.jit,
    static_argnames=("n_groups", "ops", "use_pallas", "null_sentinels"),
)
def _partial_tables_mm(codes, measures, ops, n_groups, mask=None,
                       use_pallas=False, null_sentinels=None):
    """MXU path: one ``dot_general`` of stacked bf16 rows (a ones row for
    counts, byte limbs for int sums, a 3-limb bf16 split for float32 sums)
    against the blocked one-hot of the folded codes."""
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    n = codes.shape[0]
    nb = -(-n // _MATMUL_BLOCK)
    pad = nb * _MATMUL_BLOCK - n

    folded = jnp.where(valid, codes, -1).astype(jnp.int32)
    c_blk = _blocked(folded, nb, pad, fill=-1)

    rows = []          # flat [n] bf16 rows, blocked right before the dot
    int_rows = []      # indices reduced exactly in uint64
    float_rows = []    # indices reduced in float64

    def add_int(row):
        rows.append(row)
        int_rows.append(len(rows) - 1)
        return len(rows) - 1

    def add_float(row):
        rows.append(row)
        float_rows.append(len(rows) - 1)
        return len(rows) - 1

    valid_count_row = add_int(valid.astype(jnp.bfloat16))

    sentinels = _normalize_sentinels(null_sentinels, len(measures))
    # per-measure row plans, resolved after the single dot below
    plans = []
    for values, op, sentinel in zip(measures, ops, sentinels):
        if op not in MERGEABLE_OPS:
            raise ValueError(
                f"op {op!r} has no mergeable partial; use the dedicated kernel"
            )
        is_float = jnp.issubdtype(values.dtype, jnp.floating)
        null = _measure_null(values, sentinel)
        if null is None:
            present_row = valid_count_row
        elif op == "count_na":
            # consumes only the null row below — a presence row would be a
            # wasted [n] bf16 contraction row in the stacked dot
            present_row = None
        else:
            present_row = add_int((valid & ~null).astype(jnp.bfloat16))
        if op in ("sum", "mean"):
            if not is_float and op == "mean":
                # pandas float-mean semantics (see the scatter path)
                plans.append(
                    ("f64_scatter", op, values.astype(jnp.float64),
                     present_row)
                )
            elif not is_float:
                v = values
                if v.dtype == jnp.bool_:
                    v = v.astype(jnp.uint8)
                nbits = v.dtype.itemsize * 8
                limbs, bias = _limb_rows(v, nbits)
                idxs = [add_int(r) for r in limbs]
                plans.append(("int_sum", op, idxs, bias, present_row))
            elif values.dtype == jnp.float64 and jax.config.jax_enable_x64:
                plans.append(("f64_scatter", op, values, present_row))
            else:
                v = values.astype(jnp.float32)
                v = jnp.where(valid & ~_null_mask(v), v, 0.0)
                # 3-limb Dekker split: each bf16 limb captures >=8 mantissa
                # bits and each residual is exact in f32, so hi+mid+lo
                # reconstructs all 24 f32 mantissa bits — the measure's
                # REPRESENTATION on the MXU path is lossless and the only
                # error left is the accumulation rounding any f32 sum has.
                # The rounding MUST be lax.reduce_precision, not an
                # f32->bf16->f32 astype round-trip: on TPU the XLA
                # excess-precision pass elides the round-trip, which turns
                # r1 into v - v == 0 and silently drops the mid/lo limbs
                # (~0.9% relative error, caught on hardware by
                # tpu_validate.py; reduce_precision is contractually never
                # folded away).
                hi_f = lax.reduce_precision(v, exponent_bits=8,
                                            mantissa_bits=7)
                r1 = v - hi_f
                mid_f = lax.reduce_precision(r1, exponent_bits=8,
                                             mantissa_bits=7)
                r2 = r1 - mid_f
                hi = hi_f.astype(jnp.bfloat16)
                mid = mid_f.astype(jnp.bfloat16)
                lo = lax.reduce_precision(
                    r2, exponent_bits=8, mantissa_bits=7
                ).astype(jnp.bfloat16)
                plans.append(
                    ("float_sum", op, add_float(hi), add_float(mid),
                     add_float(lo), present_row)
                )
        elif op == "count":
            plans.append(("count", op, present_row))
        elif op == "count_na":
            if null is not None:
                null_row = add_int(
                    (valid & null).astype(jnp.bfloat16)
                )
                plans.append(("count", op, null_row))
            else:  # plain integers can't be null: no matmul row needed
                plans.append(("zero_count", op))
        elif op in ("min", "max"):
            plans.append((op, op, values, present_row, null))

    # resolve the contraction route now that the stacked row count is
    # known (all static python: len(rows) and n_groups are trace-time
    # constants).  The dispatcher's gates only knew n_groups.
    route = {False: "xla", True: "pallas", "hicard": "hicard"}[use_pallas]
    if route == "hicard":
        from bqueryd_tpu.ops import pallas_groupby

        # past the VMEM plan the scatter path must take over (NOT the XLA
        # dot below, whose [nb, K, G] one-hot materializes gigabytes at
        # this cardinality)
        if not (
            pallas_groupby.hicard_fits_vmem(len(rows))
            and not float_rows
        ):
            return _partial_tables_scatter(
                codes, measures, ops, n_groups, mask,
                null_sentinels=null_sentinels,
            )
    elif route == "pallas":
        from bqueryd_tpu.ops import pallas_groupby

        # demote to the XLA dot when the full working set (rows x groups
        # scratch + lhs blocks) would overflow VMEM
        if not pallas_groupby.fits_vmem(len(rows), n_groups):
            route = "xla"

    if route == "hicard":
        # group-tiled fused kernel: [R, G] uint32 limb totals mod 2^32,
        # zero-extended so the downstream uint64 recombination is unchanged
        # (the sum over the singleton block axis is a no-op)
        out = pallas_groupby.onehot_rows_dot_hicard(
            folded,
            jnp.stack(rows, axis=0),
            n_rows=len(rows),
            n_groups=n_groups,
            interpret=jax.default_backend() != "tpu",
        )[None, : len(rows), :n_groups]
    elif route == "pallas":
        # fused VMEM kernel: one-hot tiles formed on the fly, never in HBM
        out = pallas_groupby.onehot_rows_dot(
            folded,
            jnp.stack(rows, axis=0),
            n_rows=len(rows),
            n_groups=n_groups,
            interpret=jax.default_backend() != "tpu",
        )[:, : len(rows), :n_groups]
    else:
        lhs = jnp.stack(
            [_blocked(r, nb, pad) for r in rows], axis=1
        )  # [nb,R,K]
        one_hot = (
            c_blk[:, :, None]
            == jnp.arange(n_groups, dtype=jnp.int32)[None, None, :]
        ).astype(jnp.bfloat16)
        out = lax.dot_general(
            lhs,
            one_hot,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [nb, R, G]

    int_idx = jnp.asarray(int_rows, dtype=jnp.int32)
    tot_u = jnp.take(out, int_idx, axis=1).astype(jnp.uint64).sum(axis=0)
    u_pos = {ridx: i for i, ridx in enumerate(int_rows)}
    if float_rows:
        f_idx = jnp.asarray(float_rows, dtype=jnp.int32)
        f_dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        tot_f = jnp.take(out, f_idx, axis=1).astype(f_dt).sum(axis=0)
        f_pos = {ridx: i for i, ridx in enumerate(float_rows)}

    def int_row(ridx):
        return tot_u[u_pos[ridx]]

    rows_count = int_row(valid_count_row).astype(jnp.int64)
    safe = jnp.where(valid, codes, 0).astype(jnp.int32)

    aggs = []
    for plan in plans:
        kind, op = plan[0], plan[1]
        if kind == "int_sum":
            _, _, idxs, bias, present_row = plan
            s = jnp.zeros(n_groups, dtype=jnp.uint64)
            for j, ridx in enumerate(idxs):
                s = s + (int_row(ridx) << jnp.uint64(8 * j))
            count = int_row(present_row)
            if bias:
                s = s - count * jnp.uint64(bias)
            partial = {"sum": s.astype(jnp.int64)}
            if op == "mean":
                partial["count"] = count.astype(jnp.int64)
            aggs.append(partial)
        elif kind == "float_sum":
            _, _, hi_idx, mid_idx, lo_idx, present_row = plan
            # add smallest-magnitude limbs first for accuracy
            partial = {
                "sum": (tot_f[f_pos[lo_idx]] + tot_f[f_pos[mid_idx]])
                + tot_f[f_pos[hi_idx]]
            }
            if op == "mean":
                partial["count"] = int_row(present_row).astype(jnp.int64)
            aggs.append(partial)
        elif kind == "f64_scatter":
            _, _, values, present_row = plan
            present = valid & ~_null_mask(values)
            contrib = jnp.where(present, values, 0).astype(jnp.float64)
            if jax.default_backend() != "cpu":
                # no native f64 on TPU: sort+prefix-diff beats the
                # emulated-f64 scatter (same choice as the scatter path)
                s = _sorted_segment_sum(
                    contrib, safe, n_groups, acc_dtype=jnp.float64
                )
            else:
                s = jax.ops.segment_sum(
                    contrib, safe, num_segments=n_groups
                )
            partial = {"sum": s}
            if op == "mean":
                partial["count"] = int_row(present_row).astype(jnp.int64)
            aggs.append(partial)
        elif kind == "count":
            _, _, ridx = plan
            aggs.append({"count": int_row(ridx).astype(jnp.int64)})
        elif kind == "zero_count":
            aggs.append({"count": jnp.zeros(n_groups, dtype=jnp.int64)})
        elif kind in ("min", "max"):
            _, _, values, present_row, null = plan
            present = valid if null is None else valid & ~null
            ext = _segment_extremum(kind, values, present, safe, n_groups)
            aggs.append(
                {kind: ext, "count": int_row(present_row).astype(jnp.int64)}
            )
    return {"rows": rows_count, "aggs": tuple(aggs)}


_partial_tables_mm = _obsprofile.instrument(
    "ops.partial_tables_mm", _partial_tables_mm
)


@functools.partial(
    jax.jit,
    static_argnames=("n_groups", "ops", "null_sentinels", "force_sort"),
)
def _partial_tables_scatter(codes, measures, ops, n_groups, mask=None,
                            null_sentinels=None, force_sort=False):
    """Scatter path: blocked-int32 segment sums (exact, no s64 scatter).
    ``force_sort`` (the planner's "sort" strategy) makes every sum take the
    sort+prefix-diff reduction regardless of the blocks x groups budget —
    identical partial semantics, group-count-independent cost."""
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    safe = jnp.where(valid, codes, 0).astype(jnp.int32)

    seg_sum = functools.partial(
        jax.ops.segment_sum, segment_ids=safe, num_segments=n_groups
    )

    def int_count(flags):  # bool[n] -> int64[n_groups], no s64 scatter
        return _int64_segment_sum(
            flags.astype(jnp.int8), flags, safe, n_groups,
            force_sort=force_sort,
        )

    rows = int_count(valid)

    sentinels = _normalize_sentinels(null_sentinels, len(measures))
    aggs = []
    for values, op, sentinel in zip(measures, ops, sentinels):
        if op not in MERGEABLE_OPS:
            raise ValueError(
                f"op {op!r} has no mergeable partial; use the dedicated kernel"
            )
        floating = jnp.issubdtype(values.dtype, jnp.floating)
        # plain integer measures can't be null, so their presence IS
        # key-validity: reuse the rows scatter instead of re-scanning 10M
        # rows per count; sentinel measures (datetime NaT) null like floats
        null = _measure_null(values, sentinel)
        present = valid if null is None else valid & ~null

        def present_count():
            return rows if null is None else int_count(present)

        if op in ("sum", "mean"):
            if floating or op == "mean":
                # integer MEANS also accumulate in float like pandas: an
                # exact mod-2^64 int sum divided by count diverges once the
                # group sum wraps past 2^63, which float accumulation never
                # does (sum stays bit-exact int — only mean floats)
                acc = _accum_dtype(
                    values.dtype if floating else jnp.float64
                )
                contrib = jnp.where(present, values, 0).astype(acc)
                # the sort+prefix-diff reduction differences near-equal
                # large prefixes, so it requires the float64 accumulator
                # (the x64 default here); a float32 accumulator (x64 off)
                # stays on the scatter even under a binding "sort" hint —
                # catastrophic cancellation is worse than the hint miss
                if contrib.dtype == jnp.float64 and (
                    force_sort or jax.default_backend() != "cpu"
                ):
                    # no native f64 on TPU: an emulated-f64 scatter is the
                    # wide-scatter cost this module exists to avoid; the
                    # sort+prefix-diff reduction uses only cheap elementwise
                    # wide adds (backend read at trace time, outside data
                    # flow)
                    partial = {
                        "sum": _sorted_segment_sum(
                            contrib, safe, n_groups,
                            acc_dtype=contrib.dtype,
                        )
                    }
                else:
                    partial = {"sum": seg_sum(contrib)}
            else:
                partial = {
                    "sum": _int64_segment_sum(
                        values, present, safe, n_groups,
                        force_sort=force_sort,
                    )
                }
            if op == "mean":
                partial["count"] = present_count()
            aggs.append(partial)
        elif op == "count":
            aggs.append({"count": present_count()})
        elif op == "count_na":
            na = (
                int_count(valid & null)
                if null is not None
                else jnp.zeros(n_groups, dtype=jnp.int64)
            )
            aggs.append({"count": na})
        elif op in ("min", "max"):
            aggs.append(
                {
                    op: _segment_extremum(op, values, present, safe, n_groups),
                    "count": present_count(),
                }
            )
    return {"rows": rows, "aggs": tuple(aggs)}


_partial_tables_scatter = _obsprofile.instrument(
    "ops.partial_tables_scatter", _partial_tables_scatter
)


def host_partial_tables(codes, measures, ops, n_groups, mask=None,
                        null_sentinels=None):
    """Pure-NumPy :func:`partial_tables` — same pytree, host execution.

    Exists for latency-aware routing: on a remote/tunneled device a single
    dispatch+fetch costs tens of ms, so below a row threshold (see
    ``models.query.host_kernel_rows``) the worker computes partials on the
    host instead.  Bit-exactness is preserved without s64 overflow hazards:
    int sums split into 16-bit limbs whose float64 ``bincount`` weights stay
    exact integers (< 2^16 max limb x up to 2^37 rows < 2^53), recombined
    mod 2^64.  NumPy is the reference semantics the device kernels are
    tested against, so the two paths are interchangeable by construction.
    """
    from bqueryd_tpu.utils.tracing import trace_span

    # runtime (un-traced) host kernel: annotate it like the device phases so
    # a BQUERYD_TPU_PROFILE=1 timeline shows host-routed queries too, tagged
    # with the active trace_id (obs.trace)
    with trace_span("host_kernel"):
        return _host_partial_tables(
            codes, measures, ops, n_groups, mask=mask,
            null_sentinels=null_sentinels,
        )


def _host_partial_tables(codes, measures, ops, n_groups, mask=None,
                         null_sentinels=None):
    import numpy as np

    codes = np.asarray(codes)
    valid = codes >= 0
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=bool)
    # the common case — no null keys, no filter — skips every np.where
    # masking pass and takes integer (unweighted) bincounts throughout
    all_valid = bool(valid.all())
    safe = (
        codes.astype(np.int64)
        if all_valid
        else np.where(valid, codes, 0).astype(np.int64)
    )
    minlength = max(int(n_groups), 1)

    # Native fast path: the striped C++ kernels in native/tpucolz.cpp run the
    # same reductions multithreaded, and their int sums accumulate in uint64
    # (mod 2^64) so they are exact at ANY magnitude — no 2^53 bincount bound.
    # Bounded by a row floor (thread spawn overhead) and a group ceiling
    # (per-thread accumulator memory).
    native_mod = None
    if (
        len(codes) >= _NATIVE_GROUPBY_MIN_ROWS
        and minlength <= _NATIVE_GROUPBY_MAX_GROUPS
    ):
        from bqueryd_tpu.storage import native as _native

        if _native.groupby_available():
            native_mod = _native
    codes32 = base_mask = None
    if native_mod is not None:
        codes32 = np.ascontiguousarray(codes, dtype=np.int32)
        if not all_valid:
            # numpy bool is 1 byte: the uint8 view keeps every native call
            # zero-copy on the mask
            base_mask = valid.view(np.uint8)

    def count_where(flags):
        if native_mod is not None:
            m = base_mask if flags is None else (
                flags.view(np.uint8) if flags.dtype == np.bool_ else flags
            )
            return native_mod.groupby_i64(codes32, None, m, minlength)[1]
        if flags is None:  # all rows count
            return np.bincount(safe, minlength=minlength).astype(np.int64)
        return np.bincount(
            safe, weights=flags.astype(np.float64), minlength=minlength
        ).astype(np.int64)

    def exact_int_sum(values, present):
        v = values.astype(np.int64, copy=False)
        if present is not None:
            v = np.where(present, v, 0)
        if len(v):
            # one float64-weighted bincount is exact when every partial sum
            # stays below 2^53: |any partial| <= n rows x max|value|
            bound = max(abs(int(v.min())), abs(int(v.max())))
            if bound * len(v) < HOST_EXACT_SUM_BOUND:
                return np.bincount(
                    safe, weights=v.astype(np.float64), minlength=minlength
                ).astype(np.int64)
        # full-range fallback: 16-bit limbs keep the weighted bincounts
        # exact (< 2^16 max limb x up to 2^37 rows < 2^53) at 4x the cost
        total = np.zeros(minlength, dtype=np.uint64)
        for i in range(4):
            if i < 3:  # unsigned 16-bit slices of the two's complement
                limb = ((v >> np.int64(16 * i)) & np.int64(0xFFFF))
            else:      # top limb keeps the sign via arithmetic shift
                limb = v >> np.int64(48)
            limb_sum = np.bincount(
                safe, weights=limb.astype(np.float64), minlength=minlength
            )
            # float64 totals are exact integers; recombine mod 2^64
            total = total + (
                limb_sum.astype(np.int64).astype(np.uint64)
                << np.uint64(16 * i)
            )
        return total.astype(np.int64)

    def null_mask(values, sentinel):
        if sentinel is not None:
            return values == np.asarray(sentinel, dtype=values.dtype)
        if np.issubdtype(values.dtype, np.floating):
            return np.isnan(values)
        return np.zeros(values.shape, dtype=bool)

    rows = count_where(None if all_valid else valid)
    sentinels = _normalize_sentinels(null_sentinels, len(measures))
    # (values id, dtype) -> (values, (mins, maxs, counts)); the array is
    # cached alongside the result to pin its id() for the cache's lifetime
    _minmax_cache = {}
    aggs = []
    for values, op, sentinel in zip(measures, ops, sentinels):
        if op not in MERGEABLE_OPS:
            raise ValueError(
                f"op {op!r} has no mergeable partial; use the dedicated kernel"
            )
        if sentinel is not None and op in ("sum", "mean"):
            raise ValueError(
                f"op {op!r} cannot aggregate a sentinel-null measure"
            )
        values = np.asarray(values)
        if (
            native_mod is not None
            and sentinel is None
            and op in ("min", "max")
            and native_mod.groupby_minmax_available()
            # unsigned values >= 2^63 would wrap in the signed i64 kernel
            # (and uint64's identity fill overflows int64): numpy path
            and not np.issubdtype(values.dtype, np.unsignedinteger)
        ):
            # one striped pass yields min+max+present counts; empty groups
            # re-filled with the MEASURE dtype's identity after the int64/f64
            # kernel so cross-shard merges stay correct post-cast.  min and
            # max of the SAME measure share the pass via the cache.
            cache_key = (id(values), values.dtype.str)
            entry = _minmax_cache.get(cache_key)
            if entry is None:
                hit = native_mod.groupby_minmax(
                    codes32, values, base_mask, minlength
                )
                # the cached array keeps ``values`` alive so its id() can't
                # be recycled onto a different same-dtype measure while the
                # cache exists (callers may pass non-ndarray measures whose
                # asarray conversion would otherwise die with the iteration)
                _minmax_cache[cache_key] = entry = (values, hit)
            mns, mxs, cnts = entry[1]
            ext64 = mns if op == "min" else mxs
            target = values.dtype
            ext = np.where(
                cnts == 0, extremum_fill(target, op), ext64
            ).astype(target)
            aggs.append({op: ext, "count": cnts})
            continue
        if native_mod is not None and op in ("sum", "mean"):
            # one striped kernel call yields sum AND presence count (the
            # mean denominator) — and runs before any isnan/present
            # bookkeeping, which the kernels handle internally.  Integer
            # MEANS go through the f64 kernel (pandas float-mean semantics,
            # see the scatter path).
            if np.issubdtype(values.dtype, np.floating) or op == "mean":
                fsums, fcounts = native_mod.groupby_f64(
                    codes32, np.asarray(values, dtype=np.float64),
                    base_mask, minlength, want_counts=(op == "mean"),
                )
                partial = {"sum": fsums}
                if op == "mean":
                    partial["count"] = fcounts
            else:
                isums, _ = native_mod.groupby_i64(
                    codes32, values.astype(np.int64, copy=False),
                    base_mask, minlength,
                )
                partial = {"sum": isums}
            aggs.append(partial)
            continue
        null = null_mask(values, sentinel)
        has_null = null.any() if (
            sentinel is not None
            or np.issubdtype(values.dtype, np.floating)
        ) else False
        # present=None means "every row contributes" — the fast paths above
        present = None if (all_valid and not has_null) else (valid & ~null)
        if op in ("sum", "mean"):
            if np.issubdtype(values.dtype, np.floating) or op == "mean":
                # integer means accumulate in f64 like pandas (wrapped
                # mod-2^64 int sums would corrupt the mean past 2^63)
                contrib = (
                    values if present is None else np.where(present, values, 0)
                ).astype(np.float64)
                partial = {
                    "sum": np.bincount(
                        safe, weights=contrib, minlength=minlength
                    )
                }
            else:
                partial = {"sum": exact_int_sum(values, present)}
            if op == "mean":
                partial["count"] = count_where(present)
            aggs.append(partial)
        elif op == "count":
            aggs.append({"count": count_where(present)})
        elif op == "count_na":
            na = (
                np.zeros(minlength, dtype=np.int64)
                if not has_null
                else count_where(valid & null)
            )
            aggs.append({"count": na})
        elif op in ("min", "max"):
            sel = slice(None) if present is None else present
            ext = np.full(
                minlength, extremum_fill(values.dtype, op),
                dtype=values.dtype,
            )
            if op == "min":
                np.minimum.at(ext, safe[sel], values[sel])
            else:
                np.maximum.at(ext, safe[sel], values[sel])
            aggs.append({op: ext, "count": count_where(present)})
    return {"rows": rows, "aggs": tuple(aggs)}


def combine_partials(a, b):
    """Merge two partial-table pytrees (host- or device-side tree reduce)."""
    rows = a["rows"] + b["rows"]
    aggs = []
    for pa, pb in zip(a["aggs"], b["aggs"]):
        merged = {}
        for key in pa:
            if key == "min":
                merged[key] = jnp.minimum(pa[key], pb[key])
            elif key == "max":
                merged[key] = jnp.maximum(pa[key], pb[key])
            else:  # sum / count
                merged[key] = pa[key] + pb[key]
        aggs.append(merged)
    return {"rows": rows, "aggs": tuple(aggs)}


def psum_partials(partials, axis_name):
    """Merge partials across a mesh axis with XLA collectives: psum for
    sums/counts, pmin/pmax for extrema.  This is the ICI merge that replaces
    the reference's controller tar-merge."""
    rows = jax.lax.psum(partials["rows"], axis_name)
    aggs = []
    for partial in partials["aggs"]:
        merged = {}
        for key, value in partial.items():
            if key == "min":
                merged[key] = jax.lax.pmin(value, axis_name)
            elif key == "max":
                merged[key] = jax.lax.pmax(value, axis_name)
            else:
                merged[key] = jax.lax.psum(value, axis_name)
        aggs.append(merged)
    return {"rows": rows, "aggs": tuple(aggs)}


def finalize(partials, ops):
    """Turn merged partials into final per-group aggregate arrays.

    mean = sum / count (correct weighted mean across shards — deliberately
    NOT the reference's sum-of-shard-means, reference bqueryd/rpc.py:171).
    Groups with no contributing rows yield NaN for mean/min/max and 0 for
    sum/count, matching pandas.
    """
    out = []
    for partial, op in zip(partials["aggs"], ops):
        if op == "mean":
            count = partial["count"]
            out.append(
                jnp.where(
                    count > 0,
                    partial["sum"] / jnp.maximum(count, 1),
                    jnp.nan,
                )
            )
        elif op in ("sum",):
            out.append(partial["sum"])
        elif op in ("count", "count_na"):
            out.append(partial["count"])
        elif op in ("min", "max"):
            value = partial[op]
            empty = partial["count"] == 0
            if jnp.issubdtype(value.dtype, jnp.floating):
                # empty groups -> NaN by count, never by value: genuine
                # +/-inf data must survive
                out.append(jnp.where(empty, jnp.nan, value))
            else:
                # int columns have no NaN; empty groups report 0 and are
                # dropped upstream by the rows>0 filter
                out.append(jnp.where(empty, 0, value))
        else:
            raise ValueError(f"cannot finalize op {op!r}")
    return tuple(out)


def groupby_aggregate(codes, measures, ops, n_groups, mask=None):
    """Single-shard convenience: partials -> finalize in one call.

    Returns ``(tables, rows)`` where ``tables[i]`` is the aggregate array for
    ``ops[i]`` (shape [n_groups]) and ``rows`` counts valid rows per group
    (used to drop never-seen groups)."""
    ops = tuple(ops)
    partials = partial_tables(codes, tuple(measures), ops, n_groups, mask)
    return finalize(partials, ops), partials["rows"]


@functools.partial(jax.jit, static_argnames=("n_groups", "n_values"))
def groupby_count_distinct(codes, value_codes, n_groups, n_values, mask=None):
    """Distinct-value count per group via sort + boundary detection.

    ``value_codes`` are dense codes of the measure values (host-factorized).
    Static shapes throughout: sort of [n], then a segment_sum of boundary
    flags.  O(n log n) but bandwidth-friendly on TPU."""
    from bqueryd_tpu.ops.factorize import (
        MAX_COMPOSITE,
        CompositeOverflow,
        total_cardinality,
    )

    if total_cardinality((n_groups, n_values)) >= MAX_COMPOSITE:
        # static args: raises at trace time.  Both factors are bounded by
        # row count, so this needs ~3e9-row single shards to fire — but a
        # wrapped (group, value) composite would undercount distincts
        # silently, which is never acceptable.  The engine degrades to the
        # distinct-value-set path on this error.
        raise CompositeOverflow(
            f"count_distinct composite space {n_groups}x{n_values} "
            "exceeds int64"
        )
    valid = (codes >= 0) & (value_codes >= 0)
    if mask is not None:
        valid = valid & mask
    composite = jnp.where(
        valid, codes.astype(jnp.int64) * n_values + value_codes, jnp.int64(-1)
    )
    ordered = jnp.sort(composite)
    first = jnp.concatenate(
        [jnp.array([True]), ordered[1:] != ordered[:-1]]
    )
    is_new = first & (ordered >= 0)
    group_of = jnp.where(is_new, ordered // n_values, 0).astype(jnp.int32)
    return jax.ops.segment_sum(
        is_new.astype(jnp.int64), group_of, num_segments=n_groups
    )


groupby_count_distinct = _obsprofile.instrument(
    "ops.groupby_count_distinct", groupby_count_distinct
)


def expand_mask_by_group(group_codes, mask, n_groups=None):
    """Expand a row mask to whole groups: every row whose group contains at
    least one selected row becomes selected (the basket-expansion semantics of
    ``is_in_ordered_subgroups(basket_col, bool_arr)`` at reference
    bqueryd/worker.py:306-307, without requiring sorted input).

    segment-max of the mask over group codes, gathered back to rows.
    Negative codes (null baskets) are never selected.  Pass ``n_groups`` (the
    dense code cardinality) to keep the scatter O(groups); it defaults to the
    safe-but-wasteful row count."""
    if mask is None:
        return None
    from bqueryd_tpu.utils import devicehealth

    if devicehealth.backend_wedged():
        # host equivalent (same semantics: any selected row selects its
        # whole group; negative codes never selected) — a wedged backend
        # must not hang the basket filter
        codes_np = np.asarray(group_codes)
        mask_np = np.asarray(mask, dtype=bool)
        if n_groups is None:
            n_groups = codes_np.shape[0]
        valid = codes_np >= 0
        hit = np.zeros(max(int(n_groups), 1), dtype=bool)
        # out-of-range codes (>= n_groups) mirror the device twin exactly:
        # the jit scatter (segment_max with num_segments) silently DROPS
        # them, and the jit gather CLAMPS the index — an unguarded numpy
        # fancy-index would instead raise IndexError (divergent edge
        # semantics between two interchangeable paths, ADVICE r5 low #2)
        sel = valid & mask_np & (codes_np < int(n_groups))
        hit[codes_np[sel]] = True
        gather = np.minimum(
            np.where(valid, codes_np, 0), max(int(n_groups) - 1, 0)
        )
        return valid & hit[gather]
    group_codes = jnp.asarray(group_codes)
    if n_groups is None:
        n_groups = group_codes.shape[0]
    # bucketed (program_bucket): basket cardinality drifts per shard and per
    # refresh; the output is row-shaped, so padding the segment table needs
    # no slicing — padded groups are simply never hit
    return _expand_mask_jit(
        group_codes, jnp.asarray(mask), program_bucket(int(n_groups))
    )


@functools.partial(jax.jit, static_argnames=("n_groups",))
def _expand_mask_jit(group_codes, mask, n_groups):
    valid = group_codes >= 0
    safe = jnp.where(valid, group_codes, 0).astype(jnp.int32)
    hit = jax.ops.segment_max(
        (mask & valid).astype(jnp.int32), safe, num_segments=max(n_groups, 1),
    )
    return (hit[safe] > 0) & valid


_expand_mask_jit = _obsprofile.instrument(
    "ops.expand_mask", _expand_mask_jit
)


def host_sorted_count_distinct(codes, values, n_groups, mask=None):
    """NumPy twin of :func:`groupby_sorted_count_distinct` (identical
    run-boundary semantics, including masked-row bridging and NaN != NaN
    starting a new run) — serves the op while the accelerator backend is
    wedged (:mod:`bqueryd_tpu.utils.devicehealth`)."""
    codes = np.asarray(codes)
    values = np.asarray(values)
    if codes.shape[0] == 0:
        return np.zeros(int(n_groups), dtype=np.int64)
    valid = codes >= 0
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=bool)
    idx = np.arange(codes.shape[0])
    marked = np.where(valid, idx, -1)
    last_valid = np.maximum.accumulate(marked)
    prev_idx = np.concatenate([[-1], last_valid[:-1]])
    has_prev = prev_idx >= 0
    gather = np.clip(prev_idx, 0, None)
    with np.errstate(invalid="ignore"):
        same = (
            has_prev
            & (codes[gather] == codes)
            & (values[gather] == values)
        )
    is_new_run = valid & ~same
    out = np.zeros(max(int(n_groups), 1), dtype=np.int64)
    np.add.at(out, codes[is_new_run].astype(np.int64), 1)
    return out[: int(n_groups)]


@functools.partial(jax.jit, static_argnames=("n_groups",))
def groupby_sorted_count_distinct(codes, values, n_groups, mask=None):
    """bquery's ``sorted_count_distinct``: counts value *runs* per group,
    assuming rows are pre-sorted by value within each group (reference
    bquery API surface; run-boundary semantics).  Works on raw values (no
    factorize needed) since only adjacent comparison matters."""
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    # Run boundaries must be measured against the previous *valid* row (a
    # masked-out row in the middle of a run must not split or hide it):
    # last-valid-index-before-i via an exclusive cumulative max.
    idx = jnp.arange(codes.shape[0])
    marked = jnp.where(valid, idx, -1)
    last_valid = jax.lax.cummax(marked)
    prev_idx = jnp.concatenate([jnp.array([-1]), last_valid[:-1]])
    has_prev = prev_idx >= 0
    gather = jnp.clip(prev_idx, 0, None)
    same = (
        has_prev
        & (codes[gather] == codes)
        & (values[gather] == values)
    )
    is_new_run = valid & ~same
    safe = jnp.where(valid, codes, 0).astype(jnp.int32)
    return jax.ops.segment_sum(
        is_new_run.astype(jnp.int64), safe, num_segments=n_groups
    )


groupby_sorted_count_distinct = _obsprofile.instrument(
    "ops.groupby_sorted_count_distinct", groupby_sorted_count_distinct
)
