"""where_terms: filter terms -> boolean row masks, with shard pruning.

The TPU equivalent of bquery's ``where_terms`` / ``where_terms_factorization_check``
(reference bqueryd/worker.py:296-303): a filter is a list of
``(column, op, value)`` terms AND-ed together.  Ops: ==, !=, <, <=, >, >=,
in, not in.

Masks are computed with jnp ops so the whole predicate fuses into the
aggregation kernel when evaluated under jit (the "masked segment_sum pushdown"
from SURVEY.md §2.3 — no materialized row copies, unlike the reference's
bool-array + fancy-indexing path).

Value translation happens host-side against the table's dictionaries:

* dict columns compare by code; a value absent from the dictionary maps to
  code -2, which naturally yields all-false for ==/in and all-true for
  !=/not-in (codes are always >= -1);
* datetime columns compare as int64 nanoseconds.

:func:`shard_can_match` is the cheap host-side precheck (the
factorization-check early-out at reference bqueryd/worker.py:296-301): column
min/max stats and dictionary membership decide whether a shard can contain any
matching row before anything is decompressed or shipped to the device.
"""


import os

WHERE_OPS = ("==", "!=", "<", "<=", ">", ">=", "in", "not in")

#: ops the per-chunk zone maps can prune on (plan.stats.zone_can_match);
#: ``!=``/``not in`` never prune — NaN rows satisfy them but are invisible
#: to the NaN-skipping zone maps
ZONE_PRUNABLE_OPS = ("==", "<", "<=", ">", ">=", "in")


def _to_ns(value):
    import pandas as pd

    return int(pd.Timestamp(value).value)


def translate_value(table, column, value, op="=="):
    """Translate a user-facing term value into physical column space.

    Range ops on dict columns are rejected: dictionary codes are in
    first-seen order, so ``<``/``>`` over codes would compare ingestion order,
    not values."""
    if isinstance(value, (set, frozenset)):
        value = list(value)  # sets accepted for in/not-in on any column kind
    kind = table.kind(column)
    if kind == "dict":
        if op in ("<", "<=", ">", ">="):
            raise ValueError(
                f"range op {op!r} is not supported on dictionary-encoded "
                f"column {column!r} (codes are unordered)"
            )
        lookup = table.dict_lookup(column)
        if isinstance(value, (list, tuple)):
            return [lookup.get(str(v), -2) for v in value]
        return lookup.get(str(value), -2)
    if kind == "datetime":
        if isinstance(value, (list, tuple)):
            return [_to_ns(v) for v in value]
        return _to_ns(value)
    return value


def term_mask(values, op, value):
    """Boolean mask for one term over a physical value array (jnp or np).

    On a wedged accelerator backend the mask computes in NumPy instead —
    identical elementwise semantics, and the filter path must not be the
    one device dispatch that hangs an otherwise host-served query.  (The
    executor's device-resident columns never reach here while wedged: the
    worker skips the mesh path entirely then.)"""
    from bqueryd_tpu.utils import devicehealth

    if devicehealth.backend_wedged():
        import numpy as xp

        if not isinstance(values, xp.ndarray) and type(values).__module__.split(
            ".", 1
        )[0].startswith("jax"):
            # a device-resident jax Array here means the latch flipped AFTER
            # columns were device-put: np.asarray on it would perform the
            # blocking device transfer this branch exists to avoid.  Fail
            # fast instead of hanging the worker loop; the caller's wedged
            # routing retries from host-resident columns.
            raise TypeError(
                "term_mask received a device-resident array while the "
                "accelerator backend is wedged; re-evaluate the filter from "
                "host-resident columns"
            )
    else:
        import jax.numpy as xp
    values = xp.asarray(values)
    if op == "==":
        return values == value
    if op == "!=":
        return values != value
    if op == "<":
        return values < value
    if op == "<=":
        return values <= value
    if op == ">":
        return values > value
    if op == ">=":
        return values >= value
    if op == "in":
        return xp.isin(values, xp.asarray(value))
    if op == "not in":
        return ~xp.isin(values, xp.asarray(value))
    raise ValueError(f"unsupported where op {op!r}")


def build_mask(table, where_terms_list, column_getter=None):
    """AND together all terms into one bool mask (jnp array), or return None
    for an empty term list (no filtering — same contract as the reference
    passing bool_arr=None, reference bqueryd/worker.py:294-309).

    ``column_getter`` overrides physical column access (the executor passes
    device-resident columns; default reads from the table)."""
    if not where_terms_list:
        return None
    get = column_getter or (lambda name: table.column_raw(name))
    mask = None
    for term in where_terms_list:
        column, op, value = term
        phys = translate_value(table, column, value, op)
        m = term_mask(get(column), op, phys)
        mask = m if mask is None else (mask & m)
    return mask


def chunk_prune_enabled():
    """Chunk-granular zone-map pruning kill switch
    (``BQUERYD_TPU_CHUNK_PRUNE``, default on)."""
    return os.environ.get("BQUERYD_TPU_CHUNK_PRUNE", "1") == "1"


def chunk_prune_selectivity():
    """Surviving-chunk fraction ABOVE which pruning is skipped
    (``BQUERYD_TPU_CHUNK_PRUNE_SELECTIVITY``, default 0.9): a filter that
    keeps nearly every chunk would fragment the content-keyed caches for
    no decode savings."""
    try:
        return float(
            os.environ.get("BQUERYD_TPU_CHUNK_PRUNE_SELECTIVITY", "0.9")
        )
    except ValueError:
        return 0.9


def chunk_selection(table, where_terms_list):
    """Boolean keep-mask over the table's committed chunk grid for an
    AND-ed term list, or None when nothing is prunable (no zone maps, no
    prunable ops, single chunk).  A False entry is PROOF (from per-chunk
    min/max) that no row of that chunk satisfies the conjunction."""
    import numpy as np

    from bqueryd_tpu.plan.stats import zone_can_match

    counts = getattr(table, "chunk_rows", lambda: None)()
    if counts is None or len(counts) <= 1:
        return None
    keep = np.ones(len(counts), dtype=bool)
    prunable = False
    for term in where_terms_list or []:
        try:
            column, op, value = term
        except (TypeError, ValueError):
            continue
        if op not in ZONE_PRUNABLE_OPS or column not in table:
            continue
        maps = table.chunk_zone_maps(column)
        if maps is None or len(maps) != len(counts):
            continue
        phys = translate_value(table, column, value, op)
        for i, zone in enumerate(maps):
            if not keep[i] or zone is None:
                continue
            if not zone_can_match(zone[0], zone[1], op, phys):
                keep[i] = False
                prunable = True
    return keep if prunable else None


def chunk_pruned_table(table, where_terms_list):
    """``(table_or_view, chunks_decoded, chunks_skipped)``: the zone-map
    pruning seam the worker's execute paths call.  Returns the original
    table untouched (counters still meaningful) unless pruning is enabled,
    at least one chunk is provably unmatchable, and the surviving fraction
    sits at or under the selectivity floor.  NEVER use with basket
    expansion (``expand_filter_column``): expansion re-selects rows of the
    same basket that live in pruned chunks."""
    counts = getattr(table, "chunk_rows", lambda: None)()
    total = len(counts) if counts is not None else 0
    if not chunk_prune_enabled():
        return table, 0, 0
    keep = chunk_selection(table, where_terms_list)
    if keep is None:
        return table, total, 0
    selected = int(keep.sum())
    if selected == total or selected / total > chunk_prune_selectivity():
        return table, total, 0
    import numpy as np

    view = table.chunk_view(np.flatnonzero(keep))
    return view, selected, total - selected


def shard_can_match(table, where_terms_list):
    """Host-side pruning: False only if NO row of this shard can satisfy the
    conjunction.  Uses column min/max stats (numeric/datetime) and dictionary
    membership (dict columns); unknown columns/ops conservatively match."""
    for term in where_terms_list or []:
        column, op, value = term
        if column not in table:
            continue
        try:
            kind = table.kind(column)
            if kind == "dict":
                phys = translate_value(table, column, value, op)
                if op == "==" and phys == -2:
                    return False
                if op == "in" and isinstance(phys, list) and all(
                    p == -2 for p in phys
                ):
                    return False
                continue
            stats = table.col_stats(column)
            if stats is None:
                continue
            lo, hi = stats
            if kind == "datetime":
                value_phys = translate_value(table, column, value, op)
            else:
                value_phys = value
            if op == "==" and not (
                isinstance(value_phys, (list, tuple))
            ) and (value_phys < lo or value_phys > hi):
                return False
            if op == ">" and hi <= value_phys:
                return False
            if op == ">=" and hi < value_phys:
                return False
            if op == "<" and lo >= value_phys:
                return False
            if op == "<=" and lo > value_phys:
                return False
            if op == "in" and isinstance(value_phys, (list, tuple)) and all(
                v < lo or v > hi for v in value_phys
            ):
                return False
        except ValueError:
            raise  # range-op-on-dict is a real query error, surface it
        except TypeError:
            # value not comparable with stats (wrong type, etc.): pruning is
            # best-effort — conservatively keep the shard and let the mask
            # path produce the proper error or coercion
            continue
    return True

