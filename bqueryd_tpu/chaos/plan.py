"""Fault-plan model: named sites x trigger predicates x actions.

A :class:`FaultPlan` is compiled from a JSON-safe dict (inline JSON string,
file path, or dict — see :func:`load_plan`) and is **deterministic**: given
the same plan (including its ``seed``) and the same sequence of
``fire(site, **ctx)`` calls, the same faults fire in the same order.  That
is the property the chaos bench leans on — a scenario that failed can be
re-run bit-for-bit.

Plan schema (all rule fields optional except ``site`` and ``action``)::

    {
      "seed": 42,                      # plan-wide determinism seed
      "faults": [
        {
          "site":   "worker.execute",  # injection site (fnmatch pattern)
          "action": "raise",           # one of SITES[site]
          "match":  {"verb": "groupby", "worker": "ab12*"},
                                       # ctx predicates: fnmatch for strings,
                                       # equality otherwise; missing ctx key
                                       # means no match
          "args":   {"error": "DeviceBusyError", "seconds": 0.5},
          "times":  1,                 # fire at most N times (0 = unlimited)
          "after":  0,                 # skip the first N matching triggers
          "every":  1,                 # then fire every Nth match
          "probability": 1.0,          # seeded per-rule RNG (deterministic)
          "window_s": 0                # first qualifying match opens the
                                       # window; stays active this many
                                       # seconds, then exhausts for good
                                       # (0 = off).  times/every/probability
                                       # still gate matches INSIDE the window

        }
      ]
    }

Sites and their legal actions are declared in :data:`SITES`; an unknown
site/action fails loudly at **arm** time, never silently at inject time.

Rule state (hit counters, window clocks) is lock-guarded: sites fire from
the controller loop, worker loops, heartbeat threads, and client threads
concurrently.  Stdlib only; importable everywhere (including the
jax-free controller).
"""

import fnmatch
import json
import os
import random
import threading
import time

#: every injection site threaded through the stack, with the actions its
#: call sites understand.  ``delay`` and ``raise`` are interpreted inside
#: ``FaultPlan.fire`` itself but are legal ONLY where a site's tuple lists
#: them — a ``raise`` at a seam that doesn't catch it (e.g. the controller
#: dispatch loop) would lose the message instead of injecting a fault; the
#: rest are returned to the hook for site-specific interpretation.
SITES = {
    # controller -> worker work envelopes (ControllerNode._send_to_worker)
    "controller.dispatch": ("drop", "delay", "duplicate"),
    # worker -> controller result envelopes (ControllerNode.handle_worker)
    "controller.reply": ("drop", "delay", "duplicate"),
    # worker work execution (WorkerBase.handle, before handle_work)
    "worker.execute": ("raise", "delay", "wedge", "die_after_ack"),
    # worker result send (WorkerBase.handle, after handle_work)
    "worker.reply": ("drop", "delay"),
    # mesh-executor device dispatch (MeshQueryExecutor.execute)
    "worker.device": ("raise", "delay"),
    # RPC client socket layer (RPC._rpc)
    "rpc.call": ("timeout", "disconnect", "delay"),
    # coordination-store operations (coordination.ChaosStore)
    "coordination.store": ("partition", "delay"),
}

#: actions interpreted by fire() itself (not returned to the hook); legal
#: only at sites whose SITES tuple lists them
GENERIC_ACTIONS = ("delay", "raise")


class FaultPlanError(ValueError):
    """Malformed fault plan (unknown site/action, bad types) — raised at
    arm time so a typo'd plan can never silently inject nothing."""


class Fault:
    """One fired fault, returned to (or raised at) the injection site."""

    __slots__ = ("site", "action", "args", "rule_index")

    def __init__(self, site, action, args, rule_index):
        self.site = site
        self.action = action
        self.args = args
        self.rule_index = rule_index

    def __repr__(self):
        return (
            f"Fault(site={self.site!r}, action={self.action!r}, "
            f"args={self.args!r}, rule={self.rule_index})"
        )


class FaultRule:
    """One compiled rule; trigger bookkeeping is lock-guarded."""

    def __init__(self, spec, index, seed):
        if not isinstance(spec, dict):
            raise FaultPlanError(f"fault rule {index} is not a dict: {spec!r}")
        unknown = set(spec) - {
            "site", "action", "match", "args", "times", "after", "every",
            "probability", "window_s",
        }
        if unknown:
            raise FaultPlanError(
                f"fault rule {index} has unknown fields {sorted(unknown)}"
            )
        self.site = spec.get("site")
        self.action = spec.get("action")
        if not isinstance(self.site, str) or not self.site:
            raise FaultPlanError(f"fault rule {index} needs a 'site'")
        # the site may be an fnmatch pattern; it must still cover at least
        # one declared site, and the action must be legal at every site the
        # pattern covers
        covered = [s for s in SITES if fnmatch.fnmatchcase(s, self.site)]
        if not covered:
            raise FaultPlanError(
                f"fault rule {index}: site {self.site!r} matches no known "
                f"site (known: {sorted(SITES)})"
            )
        if not isinstance(self.action, str) or not self.action:
            raise FaultPlanError(f"fault rule {index} needs an 'action'")
        for s in covered:
            if self.action not in SITES[s]:
                raise FaultPlanError(
                    f"fault rule {index}: action {self.action!r} is not "
                    f"legal at site {s!r} (legal: {sorted(SITES[s])})"
                )
        self.match = dict(spec.get("match") or {})
        self.args = dict(spec.get("args") or {})
        self.times = int(spec.get("times", 0))
        self.after = int(spec.get("after", 0))
        self.every = max(int(spec.get("every", 1)), 1)
        self.probability = float(spec.get("probability", 1.0))
        self.window_s = float(spec.get("window_s", 0.0))
        self.index = index
        # deterministic per-rule stream: same (seed, index) -> same decisions
        self._rng = random.Random(
            f"{seed}:{index}:{self.site}:{self.action}"
        )
        self._lock = threading.Lock()
        self._matched = 0        # triggers that passed the match predicates
        self._fired = 0          # faults actually injected
        self._window_started = None

    def _ctx_matches(self, ctx):
        for key, pattern in self.match.items():
            value = ctx.get(key)
            if value is None:
                return False
            if isinstance(pattern, str):
                if not fnmatch.fnmatchcase(str(value), pattern):
                    return False
            elif value != pattern:
                return False
        return True

    def consider(self, site, ctx, now=None):
        """Trigger evaluation: returns a :class:`Fault` to inject or None.
        Deterministic given the call sequence (counters + seeded RNG)."""
        if not fnmatch.fnmatchcase(site, self.site):
            return None
        if not self._ctx_matches(ctx):
            return None
        now = time.time() if now is None else now
        with self._lock:
            self._matched += 1
            if self.window_s > 0.0:
                # window semantics: the first qualifying trigger (past
                # ``after``) opens the window; once it closes the rule is
                # exhausted for good.  Matches inside the window still pass
                # through times/every/probability below — a 10%-probability
                # windowed rule injects at 10%, not 100%
                if self._window_started is None:
                    if self._matched <= self.after:
                        return None
                    self._window_started = now
                elif now - self._window_started > self.window_s:
                    return None
            elif self._matched <= self.after:
                return None
            if self.times and self._fired >= self.times:
                return None
            if (self._matched - self.after - 1) % self.every != 0:
                return None
            if self.probability < 1.0 and (
                self._rng.random() >= self.probability
            ):
                return None
            self._fired += 1
        return Fault(site, self.action, self.args, self.index)

    def stats(self):
        with self._lock:
            return {
                "site": self.site,
                "action": self.action,
                "matched": self._matched,
                "fired": self._fired,
            }


class FaultPlan:
    """A compiled plan: ordered rules, first match wins per fire()."""

    def __init__(self, spec):
        if not isinstance(spec, dict):
            raise FaultPlanError(f"fault plan is not a dict: {type(spec)}")
        unknown = set(spec) - {"seed", "faults"}
        if unknown:
            raise FaultPlanError(
                f"fault plan has unknown top-level fields {sorted(unknown)}"
            )
        self.seed = int(spec.get("seed", 0))
        faults = spec.get("faults")
        if not isinstance(faults, list) or not faults:
            raise FaultPlanError("fault plan needs a non-empty 'faults' list")
        self.rules = [
            FaultRule(rule_spec, i, self.seed)
            for i, rule_spec in enumerate(faults)
        ]

    def consider(self, site, ctx):
        """First matching rule's fault, or None."""
        for rule in self.rules:
            fault = rule.consider(site, ctx)
            if fault is not None:
                return fault
        return None

    def stats(self):
        return [rule.stats() for rule in self.rules]


def load_plan(spec):
    """Compile ``spec`` into a :class:`FaultPlan`.

    ``spec`` may be a dict, an inline JSON string (starts with ``{``), or a
    path to a JSON file — the three forms ``BQUERYD_TPU_FAULT_PLAN``
    accepts.  Raises :class:`FaultPlanError` on anything malformed."""
    if isinstance(spec, FaultPlan):
        return spec
    if isinstance(spec, str):
        text = spec.strip()
        if not text.startswith("{"):
            try:
                with open(os.path.expanduser(text)) as f:
                    text = f.read()
            except OSError as exc:
                raise FaultPlanError(
                    f"fault plan file unreadable: {exc}"
                ) from exc
        try:
            spec = json.loads(text)
        except ValueError as exc:
            raise FaultPlanError(f"fault plan is not valid JSON: {exc}") from exc
    return FaultPlan(spec)
