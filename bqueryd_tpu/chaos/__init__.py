"""Deterministic fault injection: the seams that make failure testable.

The serving stack grew retry/timeout/failover plumbing (tracked dispatch,
bounded retries, health routing, replica failover) but nothing ever
*exercised* those paths on purpose — ROADMAP item 4's "kill a node
mid-burst, assert zero failed queries" was unverifiable.  This package is
the harness: injection hooks threaded through the real failure seams
(controller dispatch/reply handling, worker execution, the mesh executor's
device dispatch, the RPC client socket layer, coordination-store access)
fire faults from a declarative, seedable :class:`~bqueryd_tpu.chaos.plan.
FaultPlan`.

Arming
------
Only via ``BQUERYD_TPU_FAULT_PLAN`` (a JSON file path or inline JSON — see
``plan.load_plan``), read when a node constructs (every node calls
:func:`maybe_arm_from_env`), or programmatically via :func:`arm` for
in-process test clusters and the bench's chaos scenarios.  **Unarmed is
free**: every hook funnels through :func:`fire`, whose disarmed path is one
module-global ``None`` check — no dict lookups, no allocation — so the hot
path inside the <2% observability overhead gate is unaffected.

Determinism
-----------
Rules trigger off counters and a per-rule RNG seeded from the plan's
``seed`` (see plan.py): the same plan over the same call sequence injects
the same faults.  The chaos bench re-runs scenarios bit-for-bit.

Error taxonomy
--------------
:class:`TransientError` subclasses (``DeviceBusyError``) are the retryable
class: a worker that catches one replies an ErrorMessage flagged
``transient=True`` and the controller **fails the shard over** to a
different holder instead of aborting the query.  :class:`FaultInjected`
(not transient) exercises the permanent-failure abort path.
"""

import os
import threading

from bqueryd_tpu.chaos.plan import (  # noqa: F401  (public surface)
    SITES,
    Fault,
    FaultPlan,
    FaultPlanError,
    load_plan,
)

__all__ = [
    "SITES", "Fault", "FaultPlan", "FaultPlanError", "load_plan",
    "TransientError", "DeviceBusyError", "FaultInjected",
    "arm", "disarm", "maybe_arm_from_env", "enabled", "fire",
    "injected_total", "site_stats", "plan_stats",
]


class TransientError(RuntimeError):
    """Retryable worker-side failure: the controller re-queues the shard
    onto a DIFFERENT healthy holder (replica failover) instead of aborting
    the parent query.  Raise subclasses for real transient conditions too —
    the taxonomy is not chaos-only."""


class DeviceBusyError(TransientError):
    """The accelerator (or its tunnel) refused/was busy — the transient
    device-fault class chaos injects at worker.execute / worker.device."""


class FaultInjected(RuntimeError):
    """A deliberately injected NON-transient fault (exercises the abort /
    structured-error path end to end)."""


_ERROR_CLASSES = {
    "DeviceBusyError": DeviceBusyError,
    "TransientError": TransientError,
    "FaultInjected": FaultInjected,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
}

#: the active plan; None = disarmed (the ONE attribute the hot path checks)
_plan = None

_stats_lock = threading.Lock()
_injected = {}       # site -> fired count (includes inline delay/raise)
_injected_total = 0


def enabled():
    """True while a fault plan is armed."""
    return _plan is not None


def arm(spec):
    """Compile and arm ``spec`` (dict / inline JSON / path); returns the
    :class:`FaultPlan`.  Replaces any previously armed plan."""
    global _plan
    plan = load_plan(spec)
    _plan = plan
    return plan


def disarm():
    """Disarm fault injection (hooks return to the no-op path)."""
    global _plan
    _plan = None


def maybe_arm_from_env():
    """Arm from ``BQUERYD_TPU_FAULT_PLAN`` when set; called by every node
    constructor.  Unset leaves the current state alone (a plan armed
    programmatically by a test or the bench survives node construction).
    A malformed env plan raises — silently injecting nothing would defeat
    the entire harness."""
    spec = os.environ.get("BQUERYD_TPU_FAULT_PLAN")
    if spec:
        arm(spec)
    return _plan


def _count(site):
    global _injected_total
    with _stats_lock:
        _injected[site] = _injected.get(site, 0) + 1
        _injected_total += 1


def fire(site, **ctx):
    """The injection hook: returns a :class:`Fault` for the call site to
    interpret, or None (no fault / disarmed).

    Generic actions are applied here so call sites stay one-liners:
    ``delay`` sleeps ``args.seconds`` and returns None (transparent);
    ``raise`` raises ``args.error`` (a name from the error taxonomy,
    default :class:`FaultInjected`) with ``args.message``.
    """
    plan = _plan
    if plan is None:
        return None
    fault = plan.consider(site, ctx)
    if fault is None:
        return None
    _count(site)
    if fault.action == "delay":
        import time

        time.sleep(float(fault.args.get("seconds", 0.05)))
        return None
    if fault.action == "raise":
        error_cls = _ERROR_CLASSES.get(
            fault.args.get("error", "FaultInjected"), FaultInjected
        )
        raise error_cls(
            fault.args.get(
                "message",
                f"chaos: injected {error_cls.__name__} at {site}",
            )
        )
    return fault


def injected_total():
    """Process-lifetime count of injected faults (all sites) — exported as
    the ``bqueryd_tpu_fault_injected_total`` gauge on every node."""
    with _stats_lock:
        return _injected_total


def site_stats():
    """Per-site injected counts (process lifetime, survives disarm)."""
    with _stats_lock:
        return dict(_injected)


def plan_stats():
    """Per-rule matched/fired counts of the armed plan ([] when disarmed)."""
    plan = _plan
    return plan.stats() if plan is not None else []


def _reset_for_tests():
    """Disarm and zero the stats (test/bench isolation)."""
    global _plan, _injected, _injected_total
    _plan = None
    with _stats_lock:
        _injected = {}
        _injected_total = 0
