from bqueryd_tpu.storage.ctable import (
    DEFAULT_CHUNKLEN,
    KIND_DATETIME,
    KIND_DICT,
    KIND_NUMERIC,
    ctable,
    free_cachemem,
    open_ctable,
)

__all__ = [
    "ctable",
    "open_ctable",
    "free_cachemem",
    "DEFAULT_CHUNKLEN",
    "KIND_NUMERIC",
    "KIND_DICT",
    "KIND_DATETIME",
]
