"""tpucolz ctable: chunked, compressed, columnar on-disk tables.

The storage role bcolz plays in the reference (opened at reference
bqueryd/worker.py:291, written by tests via ``ctable.fromdataframe``,
reference tests/test_simple_rpc.py:78-99), redesigned for the TPU data path:

* **single data file per column** (``cols/<name>/data.tpc``) holding
  back-to-back compressed chunks plus a JSON chunk index — one sequential read
  per column, then a multithreaded native decode straight into one contiguous
  host buffer sized for a single host→device transfer;
* **dictionary encoding at ingest** for string/category columns: the physical
  column is dense int32 codes and the dictionary is stored beside it.  Group
  keys are therefore *pre-factorized on disk*, which is what the TPU kernels
  want (TPUs can't factorize strings) and subsumes bquery's on-disk
  factorization cache;
* **datetimes stored as int64 nanoseconds** (TPU-friendly), reconstructed on
  the way out;
* same sharding semantics as the reference: a table is a directory named
  ``*.bcolz`` (full table) or ``*.bcolzs`` (shard), discovered by workers
  scanning their data_dir.

Layout::

    <root>/
      meta.json                  format header, nrows, column order
      __attrs__.json             user attrs (provenance metadata etc.)
      cols/<enc(name)>/meta.json chunk index: [{offset,csize,usize,nrows}...]
      cols/<enc(name)>/data.tpc  compressed chunks, back to back
      cols/<enc(name)>/dictionary.json   (dict-encoded columns only)
"""

import json
import os
import zlib

import numpy as np

from bqueryd_tpu.storage import codec
from bqueryd_tpu.utils.cache import BytesCappedCache
from bqueryd_tpu.utils.fs import mkdir_p, rm_file_or_dir

FORMAT_NAME = "tpucolz"
FORMAT_VERSION = 1
DEFAULT_CHUNKLEN = 1 << 18  # rows per chunk

KIND_NUMERIC = "numeric"
KIND_DICT = "dict"
KIND_DATETIME = "datetime"


def _pd():
    import pandas as pd

    return pd


def _atomic_json_dump(obj, path):
    """Write-then-rename so a crash mid-write never truncates committed data."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _enc_name(name):
    out = []
    for ch in name:
        if ch.isalnum() or ch in "._-":
            out.append(ch)
        else:
            out.append("%%%02X" % ord(ch))
    return "".join(out)


class _ColumnMeta:
    def __init__(self, name, kind, dtype, chunks=None, vmin=None, vmax=None):
        self.name = name
        self.kind = kind
        self.dtype = dtype  # physical numpy dtype string, e.g. "<i8"
        self.chunks = chunks or []
        # column-level min/max over physical values (numeric/datetime only):
        # powers host-side shard pruning before any decompression
        self.vmin = vmin
        self.vmax = vmax

    def to_json(self):
        return {
            "name": self.name,
            "kind": self.kind,
            "dtype": self.dtype,
            "chunks": self.chunks,
            "min": self.vmin,
            "max": self.vmax,
        }

    @classmethod
    def from_json(cls, d):
        return cls(
            d["name"], d["kind"], d["dtype"], d["chunks"],
            d.get("min"), d.get("max"),
        )


# Process-wide decoded-column cache: the in-memory analogue of bquery's
# auto_cache (reference bqueryd/worker.py:291).  Keyed by (realpath, column,
# data-file mtime+size) so reshard/activation invalidates naturally.
_COLUMN_CACHE = BytesCappedCache(
    int(os.environ.get("BQUERYD_TPU_COLUMN_CACHE_BYTES", 2 * 1024**3))
)


def free_cachemem():
    """Drop the process-wide decoded-column cache (parity with bquery's
    ``free_cachemem``, called post-task at reference bqueryd/worker.py:330)."""
    _COLUMN_CACHE.clear()


def column_cache_stats():
    """Decoded-column cache counters (hits/misses/evictions/bytes) — feeds
    the bench ``pipeline`` section's storage-decode hit rate."""
    return _COLUMN_CACHE.stats()


def _cache_get(key):
    return _COLUMN_CACHE.get(key)


def _cache_put(key, arr):
    _COLUMN_CACHE.put(key, arr)


# -- sidecar persistence helpers (factor + composite caches) ---------------

def _sidecar_enabled():
    return os.environ.get("BQUERYD_TPU_DISK_FACTOR_CACHE", "1") == "1"


def _sidecar_save(dirname, path, **arrays):
    """Atomic best-effort npz write (tempfile + rename); failures are
    swallowed — read-only media just keeps paying the recompute."""
    import tempfile

    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".sidecar.tmp")
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except Exception:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _sidecar_load(path, stamp, digest=None):
    """(codes, uniques) from an npz sidecar iff its stamp (and digest, when
    given) match; None on absent/stale/corrupt."""
    if stamp is None:
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            if not np.array_equal(z["stamp"], stamp):
                return None
            if digest is not None and z["digest"].tobytes() != digest:
                return None
            return z["codes"], z["uniques"]
    except Exception:
        return None


def _narrow_codes(codes, uniques):
    codes = np.asarray(codes)
    if len(uniques) < 2**31 and codes.dtype != np.int32:
        codes = codes.astype(np.int32)  # halves sidecar IO
    return codes


class ctable:
    """Open (mode='r'/'a') or create (mode='w') a tpucolz table directory."""

    def __init__(self, rootdir, mode="r", auto_cache=True, nthreads=0,
                 chunklen=DEFAULT_CHUNKLEN, codec_id=codec.DEFAULT_CODEC):
        self.rootdir = rootdir
        self.mode = mode
        self.auto_cache = auto_cache
        self.nthreads = nthreads
        self._meta_path = os.path.join(rootdir, "meta.json")
        self._attrs_path = os.path.join(rootdir, "__attrs__.json")
        if mode == "w":
            rm_file_or_dir(rootdir)
            mkdir_p(os.path.join(rootdir, "cols"))
            self.nrows = 0
            self.chunklen = chunklen
            self.codec_id = codec_id
            self._columns = {}
            self._order = []
            self._dictionaries = {}
            self._dict_lookups = {}
            self._write_meta()
        elif mode in ("r", "a"):
            if not os.path.exists(self._meta_path):
                raise IOError(f"not a tpucolz table: {rootdir}")
            with open(self._meta_path) as f:
                meta = json.load(f)
            if meta.get("format") != FORMAT_NAME:
                raise IOError(f"unknown table format in {rootdir}")
            self.nrows = meta["nrows"]
            self.chunklen = meta["chunklen"]
            self.codec_id = meta["codec"]
            self._order = meta["columns"]
            self._columns = {}
            for name in self._order:
                with open(self._col_path(name, "meta.json")) as f:
                    self._columns[name] = _ColumnMeta.from_json(json.load(f))
            self._dictionaries = {}
            self._dict_lookups = {}
        else:
            raise ValueError(f"bad mode {mode!r}")

    # -- paths & meta ------------------------------------------------------
    def _col_dir(self, name):
        return os.path.join(self.rootdir, "cols", _enc_name(name))

    def _col_path(self, name, fname):
        return os.path.join(self._col_dir(name), fname)

    def _write_meta(self):
        meta = {
            "format": FORMAT_NAME,
            "version": FORMAT_VERSION,
            "nrows": self.nrows,
            "chunklen": self.chunklen,
            "codec": self.codec_id,
            "columns": self._order,
        }
        _atomic_json_dump(meta, self._meta_path)

    # -- public surface ----------------------------------------------------
    @property
    def names(self):
        return list(self._order)

    def __len__(self):
        return self.nrows

    def __contains__(self, name):
        return name in self._columns

    def kind(self, name):
        return self._columns[name].kind

    @property
    def attrs(self):
        if os.path.exists(self._attrs_path):
            with open(self._attrs_path) as f:
                return json.load(f)
        return {}

    def set_attrs(self, **kv):
        attrs = self.attrs
        attrs.update(kv)
        _atomic_json_dump(attrs, self._attrs_path)

    def physical_dtype(self, name):
        """Stored physical numpy dtype of a column (metadata only, no decode)."""
        return np.dtype(self._columns[name].dtype)

    def col_stats(self, name):
        """(min, max) over the column's physical values, or None if unknown
        (dict columns, empty columns, legacy tables)."""
        col = self._columns[name]
        if col.vmin is None:
            return None
        return (col.vmin, col.vmax)

    def dictionary(self, name):
        """The value dictionary of a dict-encoded column (list), else None."""
        col = self._columns[name]
        if col.kind != KIND_DICT:
            return None
        if name not in self._dictionaries:
            with open(self._col_path(name, "dictionary.json")) as f:
                self._dictionaries[name] = json.load(f)
        return self._dictionaries[name]

    def dict_lookup(self, name):
        """Memoized {value: code} mapping for a dict column (predicate
        translation hot path — rebuilt only when the dictionary grows)."""
        dictionary = self.dictionary(name)
        if dictionary is None:
            return None
        cached = self._dict_lookups.get(name)
        if cached is None or len(cached) != len(dictionary):
            cached = {v: i for i, v in enumerate(dictionary)}
            self._dict_lookups[name] = cached
        return cached

    # -- on-disk factorize cache -------------------------------------------
    # The full analogue of bquery's auto_cache=True (reference
    # bqueryd/worker.py:291): factorizations persist NEXT TO THE SHARD, so a
    # cold process (or a different worker adopting the shard) skips the
    # decode+factorize entirely.  Validated against the column data file's
    # (mtime, size) and the table's row count — reshard/activation rewrites
    # the data file, invalidating naturally; a plain directory move keeps
    # both, and keeps the cache valid, which is correct (content unchanged).
    #
    # TOCTOU discipline: callers must capture the stamp BEFORE reading the
    # column bytes they factorize and pass it to the store.  If the shard is
    # rewritten mid-computation, the sidecar then lands with the OLD stamp
    # and every future load misses (recompute) — stamping at store time
    # would instead pair new-stamp with old-bytes codes and poison the
    # cache permanently.

    _FACTOR_CACHE_VERSION = 1

    def factor_stamp(self, name):
        """Identity of one column's data bytes (+ table rows); capture
        before reading, pass to the matching ``*_cache_store``.  st_ino
        closes the same-mtime same-size atomic-rewrite window exactly as
        :func:`rootdir_cache_key` does for meta.json; a same-filesystem
        directory move is a rename (inode kept, cache stays valid — content
        unchanged), a cross-filesystem copy invalidates conservatively."""
        try:
            st = os.stat(self._col_path(name, "data.tpc"))
        except OSError:
            return None
        return np.array(
            [self._FACTOR_CACHE_VERSION, st.st_mtime_ns, st.st_size,
             st.st_ino, self.nrows],
            dtype=np.int64,
        )

    def composite_stamp(self, cols):
        stamps = [self.factor_stamp(c) for c in cols]
        if any(s is None for s in stamps):
            return None
        return np.concatenate(stamps)

    def _composite_path(self, cols):
        tag = zlib.crc32("|".join(_enc_name(c) for c in cols).encode())
        return self._col_path(cols[0], f"composite_{tag:08x}.npz")

    def factor_cache_load(self, name):
        """Load a persisted (codes, uniques) factorization for a column, or
        None when absent/stale/disabled."""
        if not _sidecar_enabled():
            return None
        return _sidecar_load(
            self._col_path(name, "factor.npz"), self.factor_stamp(name)
        )

    def factor_cache_store(self, name, codes, uniques, stamp):
        """Persist a factorization sidecar (atomic, best-effort: read-only
        media simply keeps paying the factorize).  ``stamp`` must have been
        captured via :meth:`factor_stamp` before the column was read."""
        uniques = np.asarray(uniques)
        if not _sidecar_enabled() or stamp is None:
            return
        if uniques.dtype == object:
            return  # npz would need pickle; object keys never take this path
        _sidecar_save(
            self._col_dir(name),
            self._col_path(name, "factor.npz"),
            stamp=stamp,
            codes=_narrow_codes(codes, uniques),
            uniques=uniques,
        )

    def composite_cache_load(self, cols, digest, stamp=None):
        """Load a persisted multi-key composite factorization
        (packed-code inverse + observed composites), or None.  ``digest``
        must capture everything the packed codes depend on beyond this
        shard's data — the executor hashes the global dictionaries and
        cardinalities into it, so a change in the SHARD SET invalidates.
        Pass the ``stamp`` captured before the key columns were read so the
        sidecar is validated against the bytes the caller actually holds,
        not whatever the file mutated into since."""
        if not _sidecar_enabled():
            return None
        if stamp is None:
            stamp = self.composite_stamp(cols)
        return _sidecar_load(
            self._composite_path(cols), stamp, digest=digest
        )

    def composite_cache_store(self, cols, digest, codes, uniques, stamp):
        """``stamp`` must come from :meth:`composite_stamp` captured before
        the key columns were read (see the TOCTOU note above)."""
        if not _sidecar_enabled() or stamp is None:
            return
        uniques = np.asarray(uniques)
        _sidecar_save(
            self._col_dir(cols[0]),
            self._composite_path(cols),
            stamp=stamp,
            digest=np.frombuffer(digest, dtype=np.uint8),
            codes=_narrow_codes(codes, uniques),
            uniques=uniques,
        )

    def committed_chunks(self, name):
        """This instance's committed chunk prefix for a column: the chunks
        covering exactly ``self.nrows`` rows.  Appends commit through the
        final meta.json rename, so a reader opened mid-append may see extra
        UNCOMMITTED chunks in the column index — they are excluded here,
        which is what gives concurrent readers a consistent row-count
        snapshot.  None when the index cannot cover the committed row count
        on a chunk boundary (truncated/torn data — the caller raises)."""
        col = self._columns[name]
        acc = 0
        out = []
        for c in col.chunks:
            if acc >= self.nrows:
                break
            out.append(c)
            acc += int(c["nrows"])
        return out if acc == self.nrows else None

    def chunk_rows(self, name=None):
        """Per-chunk row counts of the committed chunk grid (all columns of
        a table share one grid: every append chunks all columns by the same
        batch + chunklen), or None when the grid is unreadable.  The grid is
        what zone-map pruning and delta tails select over."""
        if name is None:
            if not self._order:
                return None
            name = self._order[0]
        chunks = self.committed_chunks(name)
        if chunks is None:
            return None
        return [int(c["nrows"]) for c in chunks]

    def chunk_zone_maps(self, name):
        """Per-chunk ``(min, max)`` zone maps over the committed chunks of a
        numeric/datetime column (physical values; datetimes in int64 ns), or
        None when the column kind carries no zone maps.  Individual entries
        are None for chunks written before zone maps existed or holding no
        stats-able values (all-NaN/NaT) — those conservatively match every
        predicate."""
        col = self._columns[name]
        if col.kind not in (KIND_NUMERIC, KIND_DATETIME):
            return None
        chunks = self.committed_chunks(name)
        if chunks is None:
            return None
        return [
            (c["min"], c["max"])
            if c.get("min") is not None and c.get("max") is not None
            else None
            for c in chunks
        ]

    def chunk_view(self, chunk_ids):
        """A :class:`ChunkView` over the given committed-chunk indices."""
        return ChunkView(self, chunk_ids)

    def tail_view(self, start_row):
        """A :class:`ChunkView` of the rows appended after ``start_row``,
        or None when ``start_row`` does not fall on a chunk boundary (only
        append-grown tables have boundary-aligned tails — anything else
        means a rewrite, and the caller must recompute)."""
        counts = self.chunk_rows()
        if counts is None:
            return None
        acc = 0
        for i, n in enumerate(counts):
            if acc == start_row:
                return ChunkView(self, range(i, len(counts)))
            acc += n
        if acc == start_row:  # tail starts exactly at the end: empty view
            return ChunkView(self, ())
        return None

    def _column_cache_key(self, name, extra=()):
        """Content key of one column's decoded bytes.  Beyond the data
        file's (mtime, size), the key carries this INSTANCE's committed
        chunk count + row count: a reader opened mid-append decodes only
        its snapshot prefix, and caching that truncated array under the
        grown file's stat alone would serve stale bytes to the next reader
        of the fully-committed table."""
        col = self._columns[name]
        data_path = self._col_path(name, "data.tpc")
        st = os.stat(data_path) if os.path.exists(data_path) else None
        return (
            os.path.realpath(self.rootdir),
            name,
            st.st_mtime_ns if st else 0,
            st.st_size if st else 0,
            len(col.chunks),
            self.nrows,
        ) + tuple(extra)

    def column_raw(self, name):
        """Physical column values as one contiguous ndarray: int32 codes for
        dict columns, int64 ns for datetimes, the stored dtype otherwise.
        This is the array the TPU kernels consume.  Decodes the committed
        snapshot only: chunks an in-flight append has written past this
        instance's meta.json row count are ignored."""
        col = self._columns[name]
        data_path = self._col_path(name, "data.tpc")
        key = self._column_cache_key(name)
        if self.auto_cache:
            hit = _cache_get(key)
            if hit is not None:
                return hit
        dtype = np.dtype(col.dtype)
        chunks = self.committed_chunks(name)
        if chunks is None:
            chunk_rows = sum(c["nrows"] for c in col.chunks)
            raise IOError(
                f"inconsistent table {self.rootdir!r}: column {name!r} has "
                f"{chunk_rows} rows in its chunk index but meta says {self.nrows}"
            )
        out = np.empty(self.nrows, dtype=dtype)
        self._read_decode_chunks(name, chunks, out)
        if self.auto_cache:
            out.setflags(write=False)
            _cache_put(key, out)
        return out

    def _read_decode_chunks(self, name, chunks, out):
        """Read + decode an ordered chunk subset into ``out``.  Reads each
        file-contiguous run with one seek+read and rebases offsets into the
        compact buffer — the decoder needs back-to-back chunks, and the
        on-disk index may carry byte gaps (pruned selections, orphaned
        bytes left by a repaired torn append)."""
        if not chunks:
            return
        dtype = out.dtype
        parts = []
        runs = [[chunks[0]]]
        for c in chunks[1:]:
            prev = runs[-1][-1]
            if c["offset"] == prev["offset"] + prev["csize"]:
                runs[-1].append(c)
            else:
                runs.append([c])
        rebased = []
        pos = 0
        data_path = self._col_path(name, "data.tpc")
        with open(data_path, "rb") as f:
            for run in runs:
                start = run[0]["offset"]
                length = run[-1]["offset"] + run[-1]["csize"] - start
                f.seek(start)
                parts.append(f.read(length))
                for c in run:
                    nc = dict(c)
                    nc["offset"] = pos + (c["offset"] - start)
                    rebased.append(nc)
                pos += length
        codec.decode_column_into(
            b"".join(parts) if len(parts) > 1 else parts[0], rebased,
            dtype.itemsize, self.codec_id, out, self.nthreads,
        )

    def column_raw_chunks(self, name, chunk_ids):
        """Decode only the given committed-chunk indices (ascending) of a
        column, returning their rows concatenated — the zone-map-pruning /
        delta-tail decode path.  Only the selected chunks' byte ranges are
        read and decompressed; cached like :meth:`column_raw`, keyed
        additionally by the chunk selection."""
        chunk_ids = [int(i) for i in chunk_ids]
        col = self._columns[name]
        key = self._column_cache_key(name, extra=("sel", tuple(chunk_ids)))
        if self.auto_cache:
            hit = _cache_get(key)
            if hit is not None:
                return hit
        snap = self.committed_chunks(name)
        if snap is None:
            raise IOError(
                f"inconsistent table {self.rootdir!r}: column {name!r} "
                f"chunk index does not cover the committed row count"
            )
        chosen = [snap[i] for i in chunk_ids]
        dtype = np.dtype(col.dtype)
        out = np.empty(sum(c["nrows"] for c in chosen), dtype=dtype)
        self._read_decode_chunks(name, chosen, out)
        if self.auto_cache:
            out.setflags(write=False)
            _cache_put(key, out)
        return out

    def prefetch(self, names, submit=None):
        """Warm the decoded-column cache for ``names`` — the chunk-decode
        prefetch stage of the shard pipeline: the executor submits these on
        the pipeline pool so storage decode of the NEXT query inputs
        overlaps alignment/kernel work instead of serializing in front of
        the H2D loop.  ``submit`` is a ``fn -> Future`` scheduler (default:
        the shared pipeline pool); returns the futures (callers that must
        have the bytes wait on them, everyone else just lets the cache
        absorb the result)."""
        if submit is None:
            from bqueryd_tpu.parallel import pipeline

            submit = pipeline.submit

        def decode(name):
            from bqueryd_tpu.parallel import pipeline

            with pipeline.stage("decode"):
                return self.column_raw(name)

        return [
            submit(decode, name) for name in names if name in self._columns
        ]

    def column(self, name):
        """Logical column values: strings decoded from the dictionary,
        datetimes as datetime64[ns]."""
        return _logical_values(self, name, self.column_raw(name))

    def __getitem__(self, name):
        return self.column(name)

    def todataframe(self, columns=None):
        import pandas as pd

        cols = columns or self._order
        return pd.DataFrame({c: self.column(c) for c in cols}, columns=cols)

    # -- writing -----------------------------------------------------------
    def _append_physical(self, name, values):
        """Append physical values (already codes/int64ns/numeric) as chunks."""
        col = self._columns[name]
        dtype = np.dtype(col.dtype)
        values = np.ascontiguousarray(values, dtype=dtype)
        if (
            col.kind in (KIND_NUMERIC, KIND_DATETIME)
            and dtype.kind in "iuf"  # no stats for complex/bool storage
            and len(values)
        ):
            stat_values = values
            if col.kind == KIND_DATETIME:
                # NaT is INT64_MIN in the ns view; it must not poison vmin
                stat_values = values[values != np.iinfo(np.int64).min]
            if len(stat_values):
                import warnings

                with np.errstate(all="ignore"), warnings.catch_warnings():
                    # all-NaN slices legitimately yield NaN bounds (dropped
                    # below); the RuntimeWarning is noise
                    warnings.simplefilter("ignore", RuntimeWarning)
                    lo = np.nanmin(stat_values)
                    hi = np.nanmax(stat_values)
                if not (isinstance(lo, np.floating) and np.isnan(lo)):
                    lo, hi = lo.item(), hi.item()
                    col.vmin = lo if col.vmin is None else min(col.vmin, lo)
                    col.vmax = hi if col.vmax is None else max(col.vmax, hi)
        mkdir_p(self._col_dir(name))
        data_path = self._col_path(name, "data.tpc")
        offset = os.path.getsize(data_path) if os.path.exists(data_path) else 0
        with open(data_path, "ab") as f:
            for start in range(0, len(values), self.chunklen):
                part = values[start:start + self.chunklen]
                used_codec, buf = codec.encode_chunk(
                    part.tobytes(), dtype.itemsize, self.codec_id
                )
                f.write(buf)
                chunk = {
                    "offset": offset,
                    "csize": len(buf),
                    "usize": part.nbytes,
                    "nrows": len(part),
                    "crc": zlib.crc32(buf) & 0xFFFFFFFF,
                }
                # per-chunk zone map (numeric/datetime): min/max over THIS
                # chunk's values, NaN/NaT-skipped like the column stats —
                # what query-time chunk pruning reads to prove a predicate
                # cannot touch the chunk.  Chunks without one (legacy
                # tables, all-null chunks) conservatively match everything.
                if (
                    col.kind in (KIND_NUMERIC, KIND_DATETIME)
                    and dtype.kind in "iuf"
                    and len(part)
                ):
                    zpart = part
                    if col.kind == KIND_DATETIME:
                        zpart = part[part != np.iinfo(np.int64).min]
                    if len(zpart):
                        import warnings

                        with np.errstate(all="ignore"), \
                                warnings.catch_warnings():
                            warnings.simplefilter(
                                "ignore", RuntimeWarning
                            )
                            zlo = np.nanmin(zpart)
                            zhi = np.nanmax(zpart)
                        if not (
                            isinstance(zlo, np.floating) and np.isnan(zlo)
                        ):
                            chunk["min"] = zlo.item()
                            chunk["max"] = zhi.item()
                # A fallback writer may use a different codec than the table
                # default (e.g. zlib instead of LZ4 without the native lib);
                # record it per chunk so mixed tables stay readable.
                if used_codec != self.codec_id:
                    chunk["codec"] = used_codec
                col.chunks.append(chunk)
                offset += len(buf)
        _atomic_json_dump(col.to_json(), self._col_path(name, "meta.json"))

    def _truncate_uncommitted(self):
        """Drop chunk-index entries past the committed row count: a crash
        mid-append leaves some columns with chunks that the final meta.json
        rename never committed, and appending fresh batches on top of a
        torn index would desynchronize the chunk grid across columns.  The
        orphaned data-file bytes stay (appends write at the file end, so
        chunk offsets remain exact); only the index is repaired."""
        for name in self._order:
            col = self._columns[name]
            committed = self.committed_chunks(name)
            if committed is not None and len(committed) < len(col.chunks):
                col.chunks = committed
                _atomic_json_dump(
                    col.to_json(), self._col_path(name, "meta.json")
                )

    def append_dataframe(self, df):
        """Append a pandas DataFrame; creates columns on first append.

        Atomicity contract: column data + chunk indexes land first, the
        meta.json row count last (atomic rename) — readers opened mid-append
        keep a consistent pre-append snapshot (:meth:`committed_chunks`),
        and a crash between the two leaves uncommitted chunks that the next
        append repairs via :meth:`_truncate_uncommitted`."""
        if self.mode == "r":
            raise IOError("table opened read-only")
        first = not self._columns
        if not first:
            self._truncate_uncommitted()
        if first:
            for name in df.columns:
                kind, phys_dtype = _classify_dtype(df[name].dtype)
                self._columns[name] = _ColumnMeta(name, kind, phys_dtype)
                self._order.append(name)
                mkdir_p(self._col_dir(name))
                if kind == KIND_DICT:
                    self._dictionaries[name] = []
        elif list(df.columns) != self._order:
            raise ValueError("appended frame has different columns")

        for name in self._order:
            col = self._columns[name]
            series = df[name]
            if col.kind == KIND_DICT:
                dictionary = self.dictionary(name)
                # Vectorized ingest: factorize the batch, then remap the
                # batch-local uniques into the persistent dictionary.
                local_codes, local_uniques = _pd().factorize(
                    series.to_numpy(dtype=object), use_na_sentinel=True
                )
                local_codes = np.asarray(local_codes)
                # memoized mapping; mutated in place alongside the dictionary
                # (length-based invalidation in dict_lookup stays correct)
                lookup = self.dict_lookup(name)
                remap = np.empty(len(local_uniques), dtype=np.int32)
                for j, v in enumerate(local_uniques):
                    v = str(v)
                    code = lookup.get(v)
                    if code is None:
                        code = len(dictionary)
                        dictionary.append(v)
                        lookup[v] = code
                    remap[j] = code
                codes = np.where(
                    local_codes < 0, np.int32(-1), remap[local_codes]
                ).astype(np.int32)
                _atomic_json_dump(
                    dictionary, self._col_path(name, "dictionary.json")
                )
                self._append_physical(name, codes)
            elif col.kind == KIND_DATETIME:
                self._append_physical(
                    name, series.to_numpy(dtype="datetime64[ns]").view(np.int64)
                )
            else:
                self._append_physical(name, series.to_numpy())
        self.nrows += len(df)
        self._write_meta()

    def append(self, data):
        """Append rows from a dataframe-like: a pandas DataFrame, or any
        mapping of column name -> array-like (converted in column order).
        The streaming-ingest entry point (``rpc.append`` lands here)."""
        pd = _pd()
        if not isinstance(data, pd.DataFrame):
            data = pd.DataFrame(
                dict(data), columns=self._order or None
            )
        self.append_dataframe(data)
        return len(data)

    def flush(self):
        self._write_meta()

    # -- constructors ------------------------------------------------------
    @classmethod
    def fromdataframe(cls, df, rootdir, chunklen=DEFAULT_CHUNKLEN,
                      codec_id=codec.DEFAULT_CODEC, mode="w"):
        ct = cls(rootdir, mode=mode, chunklen=chunklen, codec_id=codec_id)
        ct.append_dataframe(df)
        return ct


def _logical_values(table, name, raw):
    """Physical -> logical values for one column (shared by ctable and
    ChunkView): dictionary decode for dict columns, datetime64 view for
    datetimes, passthrough otherwise."""
    kind = table.kind(name)
    if kind == KIND_DICT:
        dictionary = np.asarray(table.dictionary(name), dtype=object)
        out = np.empty(len(raw), dtype=object)
        valid = raw >= 0
        out[valid] = dictionary[raw[valid]]
        out[~valid] = None
        return out
    if kind == KIND_DATETIME:
        return raw.view("datetime64[ns]")
    return raw


class ChunkView:
    """Read-only row subset of a ctable at chunk granularity.

    The two streaming-ingest consumers:

    * **zone-map pruning** — a selective predicate whose per-chunk min/max
      prove most chunks unmatchable executes over a view of only the
      surviving chunks, so storage decode / alignment / H2D touch a
      fraction of the table (:func:`bqueryd_tpu.ops.predicates.
      chunk_pruned_table`);
    * **delta maintenance** — the chunks an append added (named by
      :func:`bqueryd_tpu.ops.workingset.growth_since`, viewed via
      :meth:`ctable.chunk_view`) re-aggregate alone, and the delta partial
      merges into the cached result; :meth:`ctable.tail_view` is the
      storage-level convenience for the same "rows after N" selection.

    The view quacks like a read-only table for every query-time consumer
    (engine, mesh executor, DAG executor): ``column_raw`` decodes only the
    selected chunks, ``col_stats`` folds the selected chunks' zone maps
    (falling back to the parent's conservative column stats), dictionaries
    and dtypes delegate.  It deliberately exposes NO sidecar methods
    (``factor_stamp``/``factor_cache_load``), so factorize caching falls
    back to the in-memory layer keyed by the view's own cache identity —
    a sidecar stored for a chunk subset would poison full-table loads.
    Row order is preserved (chunks ascending), so float reductions over
    the surviving rows are bit-identical to the masked full-table pass.
    """

    def __init__(self, parent, chunk_ids):
        self.parent = parent
        self.chunk_ids = sorted(int(i) for i in chunk_ids)
        counts = parent.chunk_rows()
        if counts is None:
            raise IOError(
                f"table {parent.rootdir!r} has no readable chunk grid"
            )
        if self.chunk_ids and self.chunk_ids[-1] >= len(counts):
            raise IndexError(
                f"chunk id {self.chunk_ids[-1]} out of range "
                f"({len(counts)} committed chunks)"
            )
        self.nrows = sum(counts[i] for i in self.chunk_ids)
        self.rootdir = None  # table_cache_key falls through to the token
        self.mode = "r"
        self.auto_cache = parent.auto_cache
        # deterministic cache identity: parent meta identity + row count +
        # the chunk selection — an appended/rewritten parent (or a
        # different selection) yields a different token, so every
        # content-keyed cache (factorize, align, codes, blocks) invalidates
        # exactly like it does for real tables
        pkey = rootdir_cache_key(getattr(parent, "rootdir", None))
        if pkey is None:
            pkey = ("unstable", os.urandom(8).hex())
        sig = zlib.crc32(
            np.asarray(self.chunk_ids, dtype=np.int64).tobytes()
        )
        self._bqueryd_cache_token = (
            f"{pkey}|r{int(parent.nrows)}|"
            f"c{len(self.chunk_ids)}:{sig:08x}"
        )

    # -- delegated metadata ------------------------------------------------
    @property
    def names(self):
        return self.parent.names

    def __len__(self):
        return self.nrows

    def __contains__(self, name):
        return name in self.parent

    def kind(self, name):
        return self.parent.kind(name)

    def physical_dtype(self, name):
        return self.parent.physical_dtype(name)

    def dictionary(self, name):
        return self.parent.dictionary(name)

    def dict_lookup(self, name):
        return self.parent.dict_lookup(name)

    def chunk_rows(self, name=None):
        counts = self.parent.chunk_rows(name)
        if counts is None:
            return None
        return [counts[i] for i in self.chunk_ids]

    def chunk_zone_maps(self, name):
        maps = self.parent.chunk_zone_maps(name)
        if maps is None:
            return None
        return [maps[i] for i in self.chunk_ids]

    def col_stats(self, name):
        """(min, max) over the SELECTED chunks' zone maps when every
        selected chunk carries one; the parent's column-level stats (a
        conservative superset range) otherwise."""
        maps = self.chunk_zone_maps(name)
        if maps and all(m is not None for m in maps):
            return (
                min(m[0] for m in maps),
                max(m[1] for m in maps),
            )
        return self.parent.col_stats(name)

    # -- data --------------------------------------------------------------
    def column_raw(self, name):
        return self.parent.column_raw_chunks(name, self.chunk_ids)

    def column(self, name):
        return _logical_values(self.parent, name, self.column_raw(name))

    def __getitem__(self, name):
        return self.column(name)

    def prefetch(self, names, submit=None):
        """Same contract as :meth:`ctable.prefetch`, decoding only the
        selected chunks — the executor's stage-1 prefetch works on views."""
        if submit is None:
            from bqueryd_tpu.parallel import pipeline

            submit = pipeline.submit

        def decode(name):
            from bqueryd_tpu.parallel import pipeline

            with pipeline.stage("decode"):
                return self.column_raw(name)

        return [
            submit(decode, name) for name in names if name in self.parent
        ]


def _classify_dtype(dtype):
    """Map a pandas dtype to (kind, physical numpy dtype string)."""
    dtype = getattr(dtype, "numpy_dtype", dtype)  # pandas extension dtypes
    try:
        np_dtype = np.dtype(dtype)
    except TypeError:
        return KIND_DICT, "<i4"
    if np_dtype.kind == "M":
        return KIND_DATETIME, "<i8"
    if np_dtype.kind in "biufc":
        return KIND_NUMERIC, np_dtype.str
    return KIND_DICT, "<i4"


def open_ctable(rootdir, mode="r", **kw):
    return ctable(rootdir, mode=mode, **kw)


def rootdir_cache_key(rootdir):
    """Stat-based identity of a table rootdir, or None when meta.json is
    not stat-able.  st_ino closes the same-mtime rewrite window: meta.json
    is written atomically (tempfile + rename), so every activation yields a
    fresh inode even when the timestamp granularity would hide the change."""
    try:
        st = os.stat(os.path.join(rootdir, "meta.json"))
    except (OSError, TypeError):
        return None
    return (os.path.realpath(rootdir), st.st_ino, st.st_mtime_ns)


def table_cache_key(table):
    """Cache identity of an on-disk table: path + metadata mtime + rows, so
    reshard/activation (which rewrites meta.json) invalidates naturally.
    Tables without a stat-able meta.json get a one-time random token pinned
    to the instance (NOT id(): CPython reuses addresses after GC, which
    would let a new table hit a dead table's cached blocks)."""
    key = rootdir_cache_key(getattr(table, "rootdir", None))
    if key is not None:
        return key + (int(table.nrows),)
    token = getattr(table, "_bqueryd_cache_token", None)
    if token is None:
        token = os.urandom(8).hex()
        try:
            table._bqueryd_cache_token = token
        except AttributeError:
            pass  # slotted/frozen table: unique token per call = no reuse
    return ("unstable", token)
