"""Reader for legacy bcolz v1 on-disk data (the migration path).

The reference serves ``.bcolz``/``.bcolzs`` rootdirs written by the original
bcolz/Blosc C library (opened at reference bqueryd/worker.py:291; the dataset
walkthrough at reference README.md:33-51 builds them with ``bcolz.ctable``).
This module reads those directories WITHOUT bcolz installed, so existing
deployments can move their data into the TPU-native store with
``bqueryd-tpu import`` (see :func:`import_ctable`).

On-disk layout read here (bcolz 1.x):

    <rootdir>/                      ctable
        __attrs__                   JSON user attrs (optional)
        <col>/                      one carray rootdir per column
            __attrs__               JSON (optional)
            meta/sizes              JSON: {"shape": [n], ...}
            meta/storage            JSON: {"dtype": ..., "chunklen": ...,
                                           "cparams": {...}, ...}
            data/__0.blp ...        Blosc v1 chunks, one per chunklen rows
            data/__leftover*.blp    trailing partial chunk (when present)

Chunks are Blosc v1 containers (16-byte header, block starts table, split
streams) decoded by the native library (``native/tpucolz.cpp``,
``tpc_blosc_decode``: blosclz + LZ4 + zlib codecs, byte-shuffle and
bit-shuffle filters) with a pure Python fallback implementing the same
public format.  Because split policy
varied across c-blosc releases, both decoders validate split framing and
retry the alternative split count rather than trusting the inference.

Column-name order: bcolz's own metadata file is consulted when present; a
deterministic sorted listing of carray subdirectories is the fallback (order
only affects column ordering of the converted table, not values).
"""

import json
import os
import zlib

import numpy as np

from bqueryd_tpu.storage.codec import (
    _bitunshuffle,
    _lz4_decompress_py,
    _unshuffle,
)

#: exceptions that mean "this split framing / codec stream is inconsistent"
#: — the retry-the-alternative-split signal (a wrong split guess feeds the
#: codecs garbage, which surfaces as any of these, never silent corruption)
_DECODE_ERRORS = (ValueError, IndexError, zlib.error)

# ---------------------------------------------------------------------------
# pure-Python Blosc v1 chunk decoder (fallback when libtpucolz is absent)
# ---------------------------------------------------------------------------

_SHUFFLE = 0x1
_MEMCPYED = 0x2
_BITSHUFFLE = 0x4
_MAX_DISTANCE = 8191


def _blosclz_decompress_py(src, usize):
    """BloscLZ (FastLZ-derived) stream decoder; see native/tpucolz.cpp for
    the format notes.  Returns the decoded bytes or raises ValueError."""
    out = bytearray(usize)
    n = len(src)
    if n == 0:
        raise ValueError("empty blosclz stream")
    ip = 0
    op = 0
    ctrl = src[ip] & 31
    ip += 1
    while True:
        if ctrl >= 32:
            length = (ctrl >> 5) - 1
            ofs = (ctrl & 31) << 8
            if length == 6:  # 3-bit field saturated at 7: extend
                while True:
                    if ip >= n:
                        raise ValueError("truncated blosclz match length")
                    code = src[ip]
                    ip += 1
                    length += code
                    if code != 255:
                        break
            if ip >= n:
                raise ValueError("truncated blosclz offset")
            code = src[ip]
            ip += 1
            if code == 255 and ofs == (31 << 8):
                if ip + 2 > n:
                    raise ValueError("truncated blosclz far offset")
                ofs = (src[ip] << 8) + src[ip + 1]
                ip += 2
                ref = op - ofs - _MAX_DISTANCE - 1
            else:
                ref = op - ofs - code - 1
            if ref < 0:
                raise ValueError("blosclz reference before start")
            length += 3
            if op + length > usize:
                raise ValueError("blosclz output overflow")
            if ref + 1 == op:
                out[op:op + length] = out[op - 1:op] * length
            else:
                for k in range(length):  # may overlap forward
                    out[op + k] = out[ref + k]
            op += length
        else:
            run = ctrl + 1
            if ip + run > n or op + run > usize:
                raise ValueError("blosclz literal overflow")
            out[op:op + run] = src[ip:ip + run]
            ip += run
            op += run
        if ip >= n:
            break
        ctrl = src[ip]
        ip += 1
    if op != usize:
        raise ValueError(f"blosclz decoded {op} bytes, expected {usize}")
    return bytes(out)


def _decode_split_stream_py(buf, bsize, nsplits, codec):
    """Decode one block's ``nsplits`` int32-framed sub-streams; raises one of
    ``_DECODE_ERRORS`` on any framing/codec inconsistency (the retry
    signal)."""
    if nsplits <= 0 or bsize % nsplits:
        raise ValueError("invalid split count")
    neblock = bsize // nsplits
    pos = 0
    parts = []
    for _ in range(nsplits):
        if pos + 4 > len(buf):
            raise ValueError("truncated split header")
        sc = int.from_bytes(buf[pos:pos + 4], "little", signed=True)
        pos += 4
        if sc <= 0 or pos + sc > len(buf):
            raise ValueError("bad split size")
        sbuf = buf[pos:pos + sc]
        pos += sc
        if sc == neblock:
            parts.append(bytes(sbuf))
        elif codec == 0:
            parts.append(_blosclz_decompress_py(sbuf, neblock))
        elif codec == 1:
            parts.append(_lz4_decompress_py(sbuf, neblock))
        elif codec == 3:
            raw = zlib.decompress(bytes(sbuf))
            if len(raw) != neblock:
                raise ValueError("zlib split size mismatch")
            parts.append(raw)
        else:
            raise ValueError(f"unsupported blosc codec id {codec}")
    return b"".join(parts)


def _blosc_decode_chunk_py(buf):
    if len(buf) < 16:
        raise ValueError("short blosc header")
    flags = buf[2]
    typesize = buf[3]
    nbytes = int.from_bytes(buf[4:8], "little", signed=True)
    blocksize = int.from_bytes(buf[8:12], "little", signed=True)
    if nbytes < 0 or blocksize <= 0:
        raise ValueError("bad blosc header")
    if flags & _MEMCPYED:
        if len(buf) < 16 + nbytes:
            raise ValueError("truncated memcpyed chunk")
        return bytes(buf[16:16 + nbytes])
    codec = (flags >> 5) & 0x7
    nblocks = -(-nbytes // blocksize)
    out = bytearray()
    for b in range(nblocks):
        start = int.from_bytes(
            buf[16 + 4 * b:20 + 4 * b], "little", signed=True
        )
        if start < 0 or start > len(buf):
            raise ValueError("bad block start")
        bsize = nbytes - b * blocksize if b == nblocks - 1 else blocksize
        leftover = bsize != blocksize
        splittable = (
            not leftover
            and codec in (0, 1)
            and 1 < typesize <= 16
            and bsize % typesize == 0
            and bsize // typesize >= 128
        )
        candidates = [typesize, 1] if splittable else [1, typesize]
        block = None
        err = None
        for nsplits in candidates:
            if nsplits <= 0:
                continue
            try:
                block = _decode_split_stream_py(
                    buf[start:], bsize, nsplits, codec
                )
                break
            except _DECODE_ERRORS as exc:
                err = exc
        if block is None:
            raise ValueError(f"block {b} undecodable: {err}")
        # filter precedence mirrors c-blosc's blosc_d: byte-shuffle wins,
        # else bit-shuffle (both per block; bit-shuffle applies at any
        # typesize — bit-planes are its point for boolean data)
        if flags & _SHUFFLE and typesize > 1:
            block = _unshuffle(block, typesize)
        elif flags & _BITSHUFFLE:
            block = _bitunshuffle(block, typesize)
        out += block
    return bytes(out)


def decode_chunk(buf):
    """Decode one Blosc v1 chunk (native fast path, Python fallback)."""
    from bqueryd_tpu.storage import native

    if native.blosc_available():
        try:
            nbytes, _typesize, _flags = native.blosc_info(bytes(buf))
            return native.blosc_decode(bytes(buf), nbytes)
        except ValueError:
            pass  # fall through: Python decoder raises the precise error
    return _blosc_decode_chunk_py(buf)


# ---------------------------------------------------------------------------
# carray / ctable directory readers
# ---------------------------------------------------------------------------

def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _parse_dtype(spec):
    if spec is None:
        raise ValueError("carray metadata has no dtype")
    if isinstance(spec, str):
        spec = spec.strip()
        # bcolz writes dtype reprs like "'<i8'" / "int64" / "|S5"
        if len(spec) >= 2 and spec[0] == spec[-1] and spec[0] in "'\"":
            spec = spec[1:-1]
    return np.dtype(spec)


def is_carray_dir(path):
    return os.path.isfile(os.path.join(path, "meta", "storage"))


def is_ctable_dir(path):
    if not os.path.isdir(path):
        return False
    if is_carray_dir(path):
        return False
    return any(
        is_carray_dir(os.path.join(path, d))
        for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d))
    )


def _chunk_files(data_dir):
    """Numbered ``__<i>.blp`` files in index order, then any leftover files."""
    numbered = []
    leftovers = []
    for name in os.listdir(data_dir):
        if not name.endswith(".blp"):
            continue
        stem = name[:-4]
        if stem.startswith("__") and stem[2:].isdigit():
            numbered.append((int(stem[2:]), name))
        else:
            leftovers.append(name)
    numbered.sort()
    leftovers.sort()
    return [name for _, name in numbered] + leftovers


def read_carray(rootdir):
    """Read one bcolz v1 carray rootdir into a 1-D numpy array."""
    meta = {}
    meta.update(_load_json(os.path.join(rootdir, "meta", "storage")))
    sizes = _load_json(os.path.join(rootdir, "meta", "sizes"))
    meta.update(sizes)
    dtype = _parse_dtype(meta.get("dtype"))
    shape = meta.get("shape")
    length = None
    if isinstance(shape, (list, tuple)) and shape:
        if len(shape) != 1:
            raise ValueError(
                f"{rootdir}: only 1-D carrays are supported, shape={shape}"
            )
        length = int(shape[0])
    data_dir = os.path.join(rootdir, "data")
    if not os.path.isdir(data_dir):
        raise ValueError(f"{rootdir}: no data/ directory")
    pieces = []
    for name in _chunk_files(data_dir):
        with open(os.path.join(data_dir, name), "rb") as f:
            buf = f.read()
        if not buf:
            continue
        try:
            pieces.append(decode_chunk(buf))
        except ValueError:
            # leftover files in some layouts are raw element bytes
            if name in ("__leftover.blp", "__leftovers.blp"):
                pieces.append(buf)
            else:
                raise
    raw = b"".join(pieces)
    if len(raw) % dtype.itemsize:
        raise ValueError(
            f"{rootdir}: decoded {len(raw)} bytes, not a multiple of "
            f"itemsize {dtype.itemsize}"
        )
    arr = np.frombuffer(raw, dtype=dtype)
    if length is not None:
        if len(arr) < length:
            raise ValueError(
                f"{rootdir}: decoded {len(arr)} rows, metadata says {length}"
            )
        arr = arr[:length]
    return arr.copy()


def _column_names(rootdir):
    # bcolz metadata variants first, sorted subdirs as the fallback
    for candidate in ("__cols__", "__attrs__", "__rootdirs__"):
        blob = _load_json(os.path.join(rootdir, candidate))
        names = blob.get("names") if isinstance(blob, dict) else None
        if isinstance(names, list) and names:
            present = [
                n for n in names if is_carray_dir(os.path.join(rootdir, n))
            ]
            if present:
                return present
    return sorted(
        d
        for d in os.listdir(rootdir)
        if is_carray_dir(os.path.join(rootdir, d))
    )


def read_ctable(rootdir):
    """Read a bcolz v1 ctable rootdir: returns (columns dict in stable
    order, user attrs dict)."""
    if is_carray_dir(rootdir):
        raise ValueError(
            f"{rootdir} is a bare carray; wrap it in a ctable or import "
            "column by column via read_carray"
        )
    names = _column_names(rootdir)
    if not names:
        raise ValueError(f"{rootdir}: no carray columns found")
    columns = {name: read_carray(os.path.join(rootdir, name)) for name in names}
    lengths = {name: len(col) for name, col in columns.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(f"{rootdir}: ragged columns {lengths}")
    attrs = _load_json(os.path.join(rootdir, "__attrs__"))
    return columns, (attrs if isinstance(attrs, dict) else {})


def import_ctable(src, dst):
    """Convert a legacy bcolz v1 ctable rootdir into the TPU-native store.

    ``bqueryd-tpu import <src.bcolz> <dst.bcolz>`` — after conversion the
    destination serves through the normal query path (same rootdir naming
    contract as the reference's data dirs, reference bqueryd/worker.py:32-33).
    Byte-string columns become dictionary-encoded text.  Returns the number
    of rows imported.
    """
    import pandas as pd

    from bqueryd_tpu.storage.ctable import ctable

    columns, attrs = read_ctable(src)
    df = pd.DataFrame(
        {
            name: (
                np.char.decode(col, "utf-8", "replace")
                if col.dtype.kind == "S"
                else col
            )
            for name, col in columns.items()
        }
    )
    table = ctable.fromdataframe(df, dst)
    if attrs:
        table.set_attrs(bcolz_v1_attrs=attrs)
    return len(df)
