"""Chunk codec front-end: native fast path with pure-Python fallbacks.

Codec ids are part of the on-disk format: 0=raw, 1=shuffle+LZ4, 2=shuffle+zlib.
The fallback implements raw and zlib natively and can *decode* (not encode) LZ4
blocks in pure Python, so data written with the native library stays readable
on hosts without it.
"""

import zlib

import numpy as np

from bqueryd_tpu.storage import native

RAW = native.TPC_RAW
LZ4 = native.TPC_LZ4
ZLIB = native.TPC_ZLIB

DEFAULT_CODEC = LZ4


def _shuffle(payload: bytes, elem_size: int) -> bytes:
    if elem_size <= 1:
        return payload
    n = len(payload)
    nelems = n // elem_size
    body = np.frombuffer(payload, dtype=np.uint8, count=nelems * elem_size)
    out = body.reshape(nelems, elem_size).T.tobytes()
    return out + payload[nelems * elem_size:]


def _unshuffle(payload: bytes, elem_size: int) -> bytes:
    if elem_size <= 1:
        return payload
    n = len(payload)
    nelems = n // elem_size
    body = np.frombuffer(payload, dtype=np.uint8, count=nelems * elem_size)
    out = body.reshape(elem_size, nelems).T.tobytes()
    return out + payload[nelems * elem_size:]


def _bitshuffle(payload: bytes, elem_size: int) -> bytes:
    """c-blosc BITSHUFFLE filter (the bitshuffle library's
    ``bshuf_trans_bit_elem`` layout): elements are truncated to a multiple
    of 8, the truncated region is stored as ``elem_size * 8`` bit-planes —
    plane ``(jj, kk)`` holds bit ``kk`` (LSB first) of byte ``jj`` of every
    element, element index packed LSB-first 8 per byte — and trailing bytes
    are copied through unshuffled (c-blosc shuffle.c ``bitshuffle()``).
    Layout pinned against a direct port of the scalar reference pipeline in
    tests/test_bcolz_v1.py."""
    if elem_size <= 0:
        return payload
    nelems = (len(payload) // elem_size) & ~7
    cut = nelems * elem_size
    if nelems == 0:
        return payload
    arr = np.frombuffer(payload, dtype=np.uint8, count=cut)
    bits = np.unpackbits(
        arr.reshape(nelems, elem_size), axis=1, bitorder="little"
    ).reshape(nelems, elem_size, 8)
    planes = bits.transpose(1, 2, 0)  # (byte-of-elem, bit, element)
    out = np.packbits(planes.reshape(-1), bitorder="little").tobytes()
    return out + payload[cut:]


def _bitunshuffle(payload: bytes, elem_size: int) -> bytes:
    """Inverse of :func:`_bitshuffle` (same truncation + tail-copy rule)."""
    if elem_size <= 0:
        return payload
    nelems = (len(payload) // elem_size) & ~7
    cut = nelems * elem_size
    if nelems == 0:
        return payload
    planes = np.unpackbits(
        np.frombuffer(payload, dtype=np.uint8, count=cut),
        bitorder="little",
    ).reshape(elem_size, 8, nelems)
    bits = planes.transpose(2, 0, 1)  # (element, byte-of-elem, bit)
    out = np.packbits(bits.reshape(-1), bitorder="little").tobytes()
    return out + payload[cut:]


def _lz4_decompress_py(src: bytes, usize: int) -> bytes:
    """Pure-Python LZ4 block decoder (read-compat fallback)."""
    dst = bytearray()
    ip, n = 0, len(src)
    while ip < n:
        token = src[ip]
        ip += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                b = src[ip]
                ip += 1
                lit_len += b
                if b != 255:
                    break
        dst += src[ip:ip + lit_len]
        ip += lit_len
        if ip >= n:
            break
        offset = src[ip] | (src[ip + 1] << 8)
        ip += 2
        ml = token & 15
        if ml == 15:
            while True:
                b = src[ip]
                ip += 1
                ml += b
                if b != 255:
                    break
        ml += 4
        start = len(dst) - offset
        if start < 0:
            raise ValueError("corrupt LZ4 block: bad offset")
        for i in range(ml):
            dst.append(dst[start + i])
    if len(dst) != usize:
        raise ValueError("corrupt LZ4 block: size mismatch")
    return bytes(dst)


def encode_chunk(payload: bytes, elem_size: int, codec: int = DEFAULT_CODEC):
    """Compress one chunk; returns (codec_used, compressed_bytes).  Falls back
    to zlib when LZ4 is requested without the native library."""
    if native.available():
        return codec, native.encode(payload, elem_size, codec)
    if codec == LZ4:
        codec = ZLIB  # encodable without native lib; recorded per chunk
    shuffled = _shuffle(payload, elem_size)
    if codec == RAW:
        return RAW, shuffled
    return ZLIB, zlib.compress(shuffled, 1)


def decode_chunk(buf: bytes, usize: int, elem_size: int, codec: int) -> bytes:
    if native.available():
        return native.decode(buf, usize, elem_size, codec)
    if codec == RAW:
        payload = buf
    elif codec == ZLIB:
        payload = zlib.decompress(buf)
    elif codec == LZ4:
        payload = _lz4_decompress_py(buf, usize)
    else:
        raise ValueError(f"unknown codec id {codec}")
    if len(payload) != usize:
        raise ValueError("corrupt chunk: size mismatch")
    return _unshuffle(payload, elem_size)


def decode_column_into(file_buf, chunks, elem_size, codec, out, nthreads=0):
    """Decode a whole column into the contiguous array ``out``.

    ``chunks`` is the column metadata list ({offset, csize, usize} dicts in
    file order).  Uses the native multithreaded decoder when present.
    """
    if not chunks:
        return
    _verify_crcs(file_buf, chunks)
    # a chunk may carry its own codec id (mixed-writer tables)
    uniform = all(c.get("codec", codec) == codec for c in chunks)
    if native.available() and uniform:
        offsets = np.array(
            [c["offset"] for c in chunks] + [chunks[-1]["offset"] + chunks[-1]["csize"]],
            dtype=np.uint64,
        )
        usizes = np.array([c["usize"] for c in chunks], dtype=np.uint64)
        native.decode_column(file_buf, offsets, usizes, elem_size, codec, out, nthreads)
        return
    view = out.view(np.uint8).reshape(-1)
    pos = 0
    for c in chunks:
        raw = decode_chunk(
            file_buf[c["offset"]:c["offset"] + c["csize"]],
            c["usize"],
            elem_size,
            c.get("codec", codec),
        )
        view[pos:pos + c["usize"]] = np.frombuffer(raw, dtype=np.uint8)
        pos += c["usize"]


def _verify_crcs(file_buf, chunks):
    """Check each chunk's stored CRC32 (over the compressed bytes) before
    decoding — LZ4 happily 'succeeds' on some corrupted inputs, so decode
    success alone does not prove integrity."""
    view = memoryview(file_buf)
    for i, c in enumerate(chunks):
        crc = c.get("crc")
        if crc is None:
            continue
        got = zlib.crc32(view[c["offset"]:c["offset"] + c["csize"]]) & 0xFFFFFFFF
        if got != crc:
            raise ValueError(f"corrupt chunk {i}: CRC mismatch")


def first_seen_order(uniques, inverse, n_values):
    """Re-order np.unique output (sorted) into first-seen order:
    returns (codes int32, uniques reordered)."""
    first_pos = np.full(len(uniques), n_values, dtype=np.int64)
    np.minimum.at(first_pos, inverse, np.arange(n_values))
    order = np.argsort(first_pos, kind="stable")
    remap = np.empty(len(order), dtype=np.int64)
    remap[order] = np.arange(len(order))
    return remap[inverse].astype(np.int32), uniques[order]


def factorize_i64(values: np.ndarray):
    """Dense-code int64 values in first-seen order -> (codes i32, uniques i64)."""
    if native.available():
        return native.factorize_i64(values)
    uniques, inverse = np.unique(values, return_inverse=True)
    return first_seen_order(uniques, inverse, len(values))
