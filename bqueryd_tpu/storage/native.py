"""ctypes bindings for libtpucolz (native codec + column decoder).

The native library is optional at runtime: every entry point here has a pure
NumPy/zlib fallback in :mod:`bqueryd_tpu.storage.codec`.  Callers go through
:mod:`codec`, never through this module directly.
"""

import ctypes
import os

import numpy as np

TPC_RAW = 0
TPC_LZ4 = 1
TPC_ZLIB = 2

_lib = None
_searched = False
_has_blosc = False
_has_groupby = False
_has_groupby_minmax = False


def _candidate_paths():
    env = os.environ.get("BQUERYD_TPU_NATIVE_LIB")
    if env:
        yield env
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    yield os.path.join(repo, "native", "build", "libtpucolz.so")
    yield os.path.join(here, "libtpucolz.so")


def _try_build():
    """Attempt a one-shot build of the native lib (g++ is in the base image)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    script = os.path.join(repo, "native", "build.sh")
    if not os.path.exists(script):
        return
    import subprocess

    try:
        subprocess.run(
            ["/bin/sh", script], capture_output=True, timeout=120, check=True
        )
    except Exception:
        pass


def get_lib():
    """Load (and memoize) the native library; returns None if unavailable."""
    global _lib, _searched
    if _lib is not None or _searched:
        return _lib
    _searched = True
    paths = list(_candidate_paths())
    if not any(os.path.exists(p) for p in paths):
        _try_build()
    for path in paths:
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        lib.tpc_max_csize.restype = ctypes.c_size_t
        lib.tpc_max_csize.argtypes = [ctypes.c_size_t]
        lib.tpc_encode.restype = ctypes.c_size_t
        lib.tpc_encode.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_size_t,
        ]
        lib.tpc_decode.restype = ctypes.c_size_t
        lib.tpc_decode.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.tpc_decode_column.restype = ctypes.c_int32
        lib.tpc_decode_column.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        # optional symbols: absent from libtpucolz builds predating the
        # bcolz import feature — a stale lib must keep serving the query
        # path, with blosc decoding falling back to pure Python
        global _has_blosc
        try:
            lib.tpc_blosc_info.restype = ctypes.c_int32
            lib.tpc_blosc_info.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.tpc_blosc_decode.restype = ctypes.c_size_t
            lib.tpc_blosc_decode.argtypes = [
                ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            _has_blosc = True
        except AttributeError:
            _has_blosc = False
        lib.tpc_factorize_i64.restype = ctypes.c_int64
        lib.tpc_factorize_i64.argtypes = [
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_size_t,
        ]
        global _has_groupby, _has_groupby_minmax
        # separate probes: a stale prebuilt .so may carry the sum kernels
        # but predate the minmax ones — the older capability must survive
        try:
            for name in ("tpc_groupby_minmax_i64", "tpc_groupby_minmax_f64"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int32
                fn.argtypes = [
                    ctypes.c_void_p,  # codes int32*
                    ctypes.c_void_p,  # values
                    ctypes.c_void_p,  # mask uint8* (nullable)
                    ctypes.c_size_t,  # n
                    ctypes.c_int64,   # n_groups
                    ctypes.c_void_p,  # mins
                    ctypes.c_void_p,  # maxs
                    ctypes.c_void_p,  # counts
                    ctypes.c_int32,   # nthreads
                ]
            _has_groupby_minmax = True
        except AttributeError:
            _has_groupby_minmax = False
        try:
            for name in ("tpc_groupby_i64", "tpc_groupby_f64"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int32
                fn.argtypes = [
                    ctypes.c_void_p,  # codes int32*
                    ctypes.c_void_p,  # values (nullable)
                    ctypes.c_void_p,  # mask uint8* (nullable)
                    ctypes.c_size_t,  # n
                    ctypes.c_int64,   # n_groups
                    ctypes.c_void_p,  # sums (nullable for i64)
                    ctypes.c_void_p,  # counts
                    ctypes.c_int32,   # nthreads
                ]
            _has_groupby = True
        except AttributeError:
            _has_groupby = False
        _lib = lib
        break
    return _lib


def available():
    return get_lib() is not None


def blosc_available():
    """True when the loaded lib carries the Blosc v1 decoder symbols (older
    builds predate them; callers fall back to the Python decoder)."""
    return get_lib() is not None and _has_blosc


def encode(payload: bytes, elem_size: int, codec: int) -> bytes:
    lib = get_lib()
    cap = lib.tpc_max_csize(len(payload))
    dst = ctypes.create_string_buffer(cap)
    csize = lib.tpc_encode(payload, len(payload), elem_size, codec, dst, cap)
    if csize == 0:
        raise RuntimeError("tpc_encode failed")
    return dst.raw[:csize]


def decode(buf: bytes, usize: int, elem_size: int, codec: int) -> bytes:
    lib = get_lib()
    dst = ctypes.create_string_buffer(usize)
    got = lib.tpc_decode(buf, len(buf), usize, elem_size, codec, dst)
    if got != usize:
        raise RuntimeError("tpc_decode failed (corrupt chunk?)")
    return dst.raw


def decode_column(file_buf, offsets, usizes, elem_size, codec, out, nthreads):
    """Decode all chunks of a column in parallel into ``out`` (a writable
    contiguous ndarray viewed as bytes).  ``offsets`` has nchunks+1 entries."""
    lib = get_lib()
    nchunks = len(usizes)
    off = np.ascontiguousarray(offsets, dtype=np.uint64)
    usz = np.ascontiguousarray(usizes, dtype=np.uint64)
    ok = lib.tpc_decode_column(
        file_buf,
        off.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        usz.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        nchunks,
        elem_size,
        codec,
        out.ctypes.data,
        nthreads,
    )
    if not ok:
        raise RuntimeError("tpc_decode_column failed (corrupt column?)")


def blosc_info(buf: bytes):
    """Parse a Blosc v1 chunk header: returns (nbytes, typesize, flags)."""
    lib = get_lib()
    nbytes = ctypes.c_int64()
    typesize = ctypes.c_int32()
    flags = ctypes.c_int32()
    if not lib.tpc_blosc_info(
        buf, len(buf),
        ctypes.byref(nbytes), ctypes.byref(typesize), ctypes.byref(flags),
    ):
        raise ValueError("not a Blosc v1 chunk")
    return nbytes.value, typesize.value, flags.value


def blosc_decode(buf: bytes, usize: int) -> bytes:
    """Decode one Blosc v1 chunk (legacy bcolz .blp files)."""
    lib = get_lib()
    dst = ctypes.create_string_buffer(usize)
    got = lib.tpc_blosc_decode(buf, len(buf), dst, usize)
    if got != usize:
        raise ValueError("Blosc chunk decode failed (corrupt or unsupported)")
    return dst.raw


def factorize_i64(values: np.ndarray):
    """Dense-code an int64 array in first-seen order: returns (codes int32,
    uniques int64)."""
    lib = get_lib()
    values = np.ascontiguousarray(values, dtype=np.int64)
    n = len(values)
    codes = np.empty(n, dtype=np.int32)
    uniques = np.empty(n if n else 1, dtype=np.int64)
    nuniq = lib.tpc_factorize_i64(
        values.ctypes.data, n, codes.ctypes.data, uniques.ctypes.data, max(n, 1)
    )
    if nuniq < 0:
        raise RuntimeError("tpc_factorize_i64 capacity exceeded")
    return codes, uniques[:nuniq].copy()


def groupby_available():
    """True when the loaded lib carries the host groupby sum/count kernels
    (older builds predate them; callers fall back to the numpy paths)."""
    return get_lib() is not None and _has_groupby


def groupby_minmax_available():
    """True when the loaded lib also carries the min/max kernels."""
    return get_lib() is not None and _has_groupby_minmax


def groupby_i64(codes, values, mask, n_groups, nthreads=0):
    """Per-group exact int64 sums (mod 2^64, any value magnitude) and counts.

    codes: int32[n] (negative = excluded); values: int64[n] or None (counts
    only); mask: bool[n] or None.  Returns (sums int64[n_groups] | None,
    counts int64[n_groups])."""
    lib = get_lib()
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    n = len(codes)
    counts = np.empty(n_groups, dtype=np.int64)
    sums = None
    vptr = sptr = mptr = None
    if values is not None:
        values = np.ascontiguousarray(values, dtype=np.int64)
        sums = np.empty(n_groups, dtype=np.uint64)
        vptr, sptr = values.ctypes.data, sums.ctypes.data
    if mask is not None:
        mask = np.ascontiguousarray(mask, dtype=np.uint8)
        mptr = mask.ctypes.data
    rc = lib.tpc_groupby_i64(
        codes.ctypes.data, vptr, mptr, n, n_groups, sptr,
        counts.ctypes.data, nthreads,
    )
    if rc != 0:
        raise RuntimeError("tpc_groupby_i64 failed")
    return (None if sums is None else sums.view(np.int64)), counts


def groupby_f64(codes, values, mask, n_groups, nthreads=0, want_counts=True):
    """Per-group float64 sums with NaN skip; counts = present (non-NaN) rows.

    Thread-merge order is fixed, so results are deterministic for a given
    thread count but not bit-identical to numpy's bincount order."""
    lib = get_lib()
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float64)
    n = len(codes)
    sums = np.empty(n_groups, dtype=np.float64)
    counts = np.empty(n_groups, dtype=np.int64) if want_counts else None
    mptr = None
    if mask is not None:
        mask = np.ascontiguousarray(mask, dtype=np.uint8)
        mptr = mask.ctypes.data
    rc = lib.tpc_groupby_f64(
        codes.ctypes.data, values.ctypes.data, mptr, n, n_groups,
        sums.ctypes.data,
        None if counts is None else counts.ctypes.data, nthreads,
    )
    if rc != 0:
        raise RuntimeError("tpc_groupby_f64 failed")
    return sums, counts


def groupby_minmax(codes, values, mask, n_groups, nthreads=0):
    """Per-group (min, max, present_count) in one striped pass.

    int64 values take the i64 kernel; floats go through the f64 kernel
    (NaN rows skipped).  Empty groups report the identity fills (int64
    max/min or +/-inf) with count 0, the same convention the numpy and
    device paths use."""
    lib = get_lib()
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    n = len(codes)
    counts = np.empty(n_groups, dtype=np.int64)
    mptr = None
    if mask is not None:
        mask = np.ascontiguousarray(mask, dtype=np.uint8)
        mptr = mask.ctypes.data
    if np.issubdtype(np.asarray(values).dtype, np.floating):
        values = np.ascontiguousarray(values, dtype=np.float64)
        mins = np.empty(n_groups, dtype=np.float64)
        maxs = np.empty(n_groups, dtype=np.float64)
        rc = lib.tpc_groupby_minmax_f64(
            codes.ctypes.data, values.ctypes.data, mptr, n, n_groups,
            mins.ctypes.data, maxs.ctypes.data, counts.ctypes.data, nthreads,
        )
    else:
        values = np.ascontiguousarray(values, dtype=np.int64)
        mins = np.empty(n_groups, dtype=np.int64)
        maxs = np.empty(n_groups, dtype=np.int64)
        rc = lib.tpc_groupby_minmax_i64(
            codes.ctypes.data, values.ctypes.data, mptr, n, n_groups,
            mins.ctypes.data, maxs.ctypes.data, counts.ctypes.data, nthreads,
        )
    if rc != 0:
        raise RuntimeError("tpc_groupby_minmax failed")
    return mins, maxs, counts
