"""Blob storage backends for the dataset distribution pipeline.

The reference downloads shard zips from S3 (boto3 + smart_open streaming,
reference bqueryd/worker.py:442-498) or Azure blob storage (reference
bqueryd/worker.py:519-556).  Neither SDK is guaranteed present here, so
backends are gated on import and a filesystem-backed backend exists for
tests and air-gapped clusters — the same seam the reference's tests use by
subclassing the downloader (reference tests/test_download.py:25-45).

URL scheme picks the backend: ``s3://bucket/key``, ``azure://container/blob``,
``localfs://bucket/key`` (rooted at BQUERYD_TPU_BLOB_DIR).
"""

import os
import shutil

CHUNK_SIZE = 16 * 1024 * 1024  # streaming chunk (reference bqueryd/worker.py:31)


class BlobBackend:
    scheme = None

    def fetch(self, bucket, key, dest_path, progress_cb=None):
        """Download bucket/key to dest_path, calling progress_cb(bytes_done)
        after each chunk."""
        raise NotImplementedError

    def put(self, bucket, key, src_path):
        raise NotImplementedError


class LocalFSBackend(BlobBackend):
    """``localfs://`` — a directory tree standing in for object storage."""

    scheme = "localfs"

    def __init__(self, root=None):
        self.root = root or os.environ.get(
            "BQUERYD_TPU_BLOB_DIR", "/tmp/bqueryd_tpu_blobs"
        )

    def _path(self, bucket, key):
        return os.path.join(self.root, bucket, key)

    def fetch(self, bucket, key, dest_path, progress_cb=None):
        src = self._path(bucket, key)
        if not os.path.exists(src):
            raise FileNotFoundError(f"localfs://{bucket}/{key}")
        done = 0
        with open(src, "rb") as fin, open(dest_path, "wb") as fout:
            while True:
                chunk = fin.read(CHUNK_SIZE)
                if not chunk:
                    break
                fout.write(chunk)
                done += len(chunk)
                if progress_cb:
                    progress_cb(done)

    def put(self, bucket, key, src_path):
        dest = self._path(bucket, key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copyfile(src_path, dest)


class S3Backend(BlobBackend):
    """``s3://`` via boto3; streamed in CHUNK_SIZE chunks with retry handled
    by the caller.  Endpoint/credentials come from the standard AWS env or
    the constructor (the localstack seam)."""

    scheme = "s3"

    def __init__(
        self, endpoint_url=None, access_key=None, secret_key=None, client=None
    ):
        if client is not None:
            # injection seam: tests (and exotic deployments) hand in a
            # ready-made client; boto3 never has to be importable
            self.client = client
            return
        import boto3  # gated import: optional dependency

        kwargs = {}
        if endpoint_url or os.environ.get("BQUERYD_TPU_S3_ENDPOINT"):
            kwargs["endpoint_url"] = endpoint_url or os.environ[
                "BQUERYD_TPU_S3_ENDPOINT"
            ]
        if access_key:
            kwargs["aws_access_key_id"] = access_key
            kwargs["aws_secret_access_key"] = secret_key
        self.client = boto3.client("s3", **kwargs)

    def fetch(self, bucket, key, dest_path, progress_cb=None):
        response = self.client.get_object(Bucket=bucket, Key=key)
        body = response["Body"]
        done = 0
        with open(dest_path, "wb") as fout:
            while True:
                chunk = body.read(CHUNK_SIZE)
                if not chunk:
                    break
                fout.write(chunk)
                done += len(chunk)
                if progress_cb:
                    progress_cb(done)

    def put(self, bucket, key, src_path):
        self.client.upload_file(src_path, bucket, key)


class AzureBackend(BlobBackend):
    """``azure://`` via azure-storage-blob; connection string from
    /etc config or env (reference bqueryd/node.py:9-11)."""

    scheme = "azure"

    def __init__(self, conn_string=None, service=None):
        if service is not None:
            self.service = service  # injection seam, as S3Backend.client
            return
        from azure.storage.blob import BlobServiceClient  # gated import

        conn = conn_string or os.environ.get("AZURE_STORAGE_CONNECTION_STRING")
        self.service = BlobServiceClient.from_connection_string(conn)

    def fetch(self, bucket, key, dest_path, progress_cb=None):
        blob = self.service.get_blob_client(container=bucket, blob=key)
        stream = blob.download_blob()
        done = 0
        with open(dest_path, "wb") as fout:
            for chunk in stream.chunks():
                fout.write(chunk)
                done += len(chunk)
                if progress_cb:
                    progress_cb(done)

    def put(self, bucket, key, src_path):
        blob = self.service.get_blob_client(container=bucket, blob=key)
        with open(src_path, "rb") as f:
            blob.upload_blob(f, overwrite=True)


_BACKENDS = {
    "localfs": LocalFSBackend,
    "s3": S3Backend,
    "azure": AzureBackend,
}


def parse_url(url):
    """'scheme://bucket/key' -> (scheme, bucket, key)."""
    scheme, _, rest = url.partition("://")
    bucket, _, key = rest.partition("/")
    if not scheme or not bucket or not key:
        raise ValueError(f"bad blob url {url!r}")
    return scheme, bucket, key


def backend_for(scheme, **kwargs):
    cls = _BACKENDS.get(scheme)
    if cls is None:
        raise ValueError(f"unknown blob scheme {scheme!r}")
    return cls(**kwargs)
