"""Dataset distribution: download tickets, locks, progress, two-phase commit.

The host-side pipeline that gets sharded tables onto worker machines —
capability match for the reference's downloader/movebcolz machinery:

* the controller registers a **ticket**: one coordination-store hash per
  download, one slot per (node, file-url), value ``"<timestamp>_<progress>"``
  starting at ``-1`` (reference bqueryd/controller.py:435-469);
* every downloader polls the tickets, claims (node, ticket, file) work with a
  TTL lock, streams the blob into ``incoming/<ticket>/``, heartbeats progress
  into the slot, marks it DONE (reference bqueryd/worker.py:358-498);
* a cancelled ticket (slots deleted) aborts mid-flight downloads
  (reference bqueryd/worker.py:418-428);
* the **movebcolz** role watches the same tickets and, only when EVERY slot
  on EVERY node is DONE, atomically swaps the new shard dirs into the serving
  directory, writing a ``bqueryd.metadata`` provenance file into each —
  the two-phase commit that flips all nodes in sync (reference
  bqueryd/worker.py:570-637, README.md:153).
"""

import json
import os
import random
import shutil
import time
import zipfile

import bqueryd_tpu
from bqueryd_tpu import blob as blob_mod
from bqueryd_tpu.utils.fs import mkdir_p, rm_file_or_dir

DONE = "DONE"
ERROR_PREFIX = "ERROR"
METADATA_FILENAME = "bqueryd.metadata"


def ticket_key(ticket):
    return bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + ticket


def lock_name(node, ticket, fileurl):
    return bqueryd_tpu.REDIS_DOWNLOAD_LOCK_PREFIX + node + ticket + fileurl


def set_progress(store, node, ticket, fileurl, progress):
    store.hset(ticket_key(ticket), f"{node}_{fileurl}", f"{time.time()}_{progress}")


def slot_state(value):
    """Progress slot value -> the progress token after the last underscore."""
    return value.rpartition("_")[2]


# ---------------------------------------------------------------------------
# controller side
# ---------------------------------------------------------------------------

def placement_nodes(filename, nodes, factor):
    """Replica placement: the ``factor`` nodes that should hold ``filename``,
    chosen by rendezvous hashing (highest-random-weight) so the choice is
    deterministic per file, stable under node churn (only 1/n of files move
    when a node joins/leaves), and balanced across the fleet.  ``factor``
    <= 0 or >= len(nodes) means every node (the historical full fan-out)."""
    import zlib

    if factor <= 0 or factor >= len(nodes):
        return list(nodes)
    ranked = sorted(
        nodes,
        key=lambda node: zlib.crc32(f"{node}\x00{filename}".encode()),
    )
    return ranked[:factor]


def setup_download(controller, msg):
    """Register a ticket for every (file, placement-node) pair and either
    park the RPC until a TicketDoneMessage (wait=True) or return the ticket
    immediately.

    Placement honors ``BQUERYD_TPU_REPLICA_FACTOR`` (overridable per ticket
    via ``replica_factor=``): 0 keeps the historical every-node fan-out; N
    targets N holders per file via rendezvous hashing — how a cold shard
    gets the second holder the failover dispatch needs."""
    _args, kwargs = msg.get_args_kwargs()
    filenames = kwargs.get("filenames") or []
    bucket = kwargs.get("bucket")
    wait = kwargs.get("wait", False)
    scheme = kwargs.get("scheme", "s3")
    if not filenames or not bucket:
        raise ValueError("download needs filenames=[...] and bucket=...")

    factor = kwargs.get("replica_factor")
    if factor is None:
        # the controller ctor is the single parse site for
        # BQUERYD_TPU_REPLICA_FACTOR (clamped to >= 0); re-reading the env
        # here would drift from those semantics
        factor = getattr(controller, "replica_factor", 0)

    nodes = sorted(
        {info.get("node") for info in controller.worker_map.values() if info.get("node")}
    )
    if not nodes:
        # no workers yet: register for this controller's own node so the
        # ticket is still actionable by co-located downloaders
        nodes = [controller.node_name]

    ticket = os.urandom(8).hex()
    for filename in filenames:
        fileurl = f"{scheme}://{bucket}/{filename}"
        for node in placement_nodes(filename, nodes, int(factor)):
            set_progress(controller.store, node, ticket, fileurl, -1)

    if wait:
        controller.rpc_segments[f"ticket_{ticket}"] = {
            "client_token": msg["token"],
            "msg": msg,
            "created": time.time(),
        }
    else:
        reply = msg.copy()
        reply.add_as_binary("result", ticket)
        controller.reply_rpc_message(msg["token"], reply)


# ---------------------------------------------------------------------------
# downloader side
# ---------------------------------------------------------------------------

def incoming_dir(worker, ticket):
    base = os.environ.get(
        "BQUERYD_TPU_INCOMING", os.path.join(worker.data_dir, "incoming")
    )
    return os.path.join(base, ticket)


def check_downloads(worker):
    """One poll cycle: claim any pending slot for this node and hand it to
    the worker's download pool.  The claim lock (TTL-bounded, so a crashed
    downloader's work is reclaimable) stays held by the in-flight job and
    also stops this poller re-claiming the same slot next tick."""
    keys = worker.store.keys(bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + "*")
    random.shuffle(keys)
    node = worker.node_name
    for key in keys:
        ticket = key[len(bqueryd_tpu.REDIS_TICKET_KEY_PREFIX):]
        for slot, value in worker.store.hgetall(key).items():
            slot_node, _, fileurl = slot.partition("_")
            state = slot_state(value)
            if slot_node != node or state == DONE or state.startswith(
                ERROR_PREFIX
            ):
                continue
            lock = worker.store.lock(
                lock_name(node, ticket, fileurl),
                ttl=bqueryd_tpu.REDIS_DOWNLOAD_LOCK_DURATION,
            )
            if not lock.acquire(blocking=False):
                continue
            worker.run_download(ticket, fileurl, lock)


def get_backend(worker, scheme):
    """Backend construction seam: tests and exotic deployments override this
    (or the worker's ``blob_backend`` attribute) — the subclass-level seam
    strategy of the reference tests (reference tests/test_download.py:25-45)."""
    override = getattr(worker, "blob_backend", None)
    if override is not None:
        return override
    return blob_mod.backend_for(scheme)


def download_file(worker, ticket, fileurl, max_retries=3, lock=None):
    """Stream one blob into incoming/<ticket>/<filename>; zip archives are
    extracted in place (shards travel zipped, reference bqueryd/worker.py:453,
    500-505).  Mid-flight cancellation: if the ticket's slot disappears, the
    download aborts and cleans up.  ``lock`` is the claim lock, periodically
    extended from the progress path so a fetch outlasting the TTL can't be
    re-claimed into a duplicate concurrent download."""
    scheme, bucket, key = blob_mod.parse_url(fileurl)
    backend = get_backend(worker, scheme)
    dest_dir = incoming_dir(worker, ticket)
    mkdir_p(dest_dir)
    filename = os.path.basename(key)
    dest = os.path.join(dest_dir, filename)
    final_target = os.path.join(dest_dir, _strip_zip(filename))
    if os.path.exists(final_target) and final_target != dest:
        # already present from an earlier attempt (reference bqueryd/worker.py:455-457)
        set_progress(worker.store, worker.node_name, ticket, fileurl, DONE)
        return

    watch = CancelWatch(
        worker.store, worker.node_name, ticket, fileurl,
        lock=lock, lock_ttl=bqueryd_tpu.REDIS_DOWNLOAD_LOCK_DURATION,
    )

    def progress(done):
        # cancellation check on EVERY chunk, BEFORE any write: a progress
        # hset after delete_download would resurrect the deleted slot and
        # the cancellation would be lost forever (writes are what's
        # rate-limited, not checks — the reverse drops cancellations)
        if watch.cancelled():
            raise DownloadCancelled(fileurl)
        watch.maybe_write_progress(done)

    for attempt in range(max_retries):
        try:
            backend.fetch(bucket, key, dest, progress_cb=progress)
            break
        except DownloadCancelled:
            worker.logger.info("download %s cancelled", fileurl)
            rm_file_or_dir(dest_dir)
            return
        except Exception:
            if attempt == max_retries - 1:
                raise
            worker.logger.warning(
                "download %s attempt %d failed, retrying", fileurl, attempt + 1
            )
            time.sleep(0.5 * (attempt + 1))

    if zipfile.is_zipfile(dest):
        with zipfile.ZipFile(dest) as zf:
            extract_dir = final_target
            mkdir_p(extract_dir)
            zf.extractall(extract_dir)
        os.remove(dest)
    set_progress(worker.store, worker.node_name, ticket, fileurl, DONE)


def _strip_zip(filename):
    return filename[:-4] if filename.endswith(".zip") else filename


class DownloadCancelled(Exception):
    pass


class CancelWatch:
    """Cancellation detection + rate-limited progress heartbeat for one
    in-flight download.

    ``cancelled()`` (a single hget) runs on every chunk;
    ``maybe_write_progress`` throttles the hset to one per ``interval`` so
    the store isn't hammered.  The check-before-write ordering matters: an
    unconditional progress write after a client's ``delete_download`` would
    re-create the deleted slot and lose the cancellation.  A delete landing
    in the instant between check and write still resurrects the slot — the
    reference's per-chunk check/write pair had the same (wider) window.

    When a claim ``lock`` is supplied its TTL is re-armed from the same
    throttled path (every ``lock_ttl/3`` seconds), so a fetch that outlasts
    the TTL keeps its claim instead of letting another poll cycle start a
    duplicate concurrent download to the same dest file."""

    def __init__(
        self, store, node, ticket, fileurl, interval=2.0,
        lock=None, lock_ttl=None,
    ):
        self.store = store
        self.node = node
        self.ticket = ticket
        self.fileurl = fileurl
        self.slot = f"{node}_{fileurl}"
        self.key = ticket_key(ticket)
        self.interval = interval
        self.lock = lock if lock is not None and lock_ttl else None
        self.lock_ttl = lock_ttl
        self._last_write = 0.0
        self._last_extend = time.time()

    def cancelled(self):
        return self.store.hget(self.key, self.slot) is None

    def maybe_write_progress(self, done):
        now = time.time()
        if now - self._last_write < self.interval:
            return
        self._last_write = now
        set_progress(self.store, self.node, self.ticket, self.fileurl, done)
        if self.lock is not None and now - self._last_extend >= self.lock_ttl / 3:
            self._last_extend = now
            try:
                self.lock.extend(self.lock_ttl)
            except Exception:
                pass  # best-effort: an expired claim is the pre-existing risk


def remove_ticket(worker, ticket):
    """Drop this node's slots for a ticket and its staging dir."""
    key = ticket_key(ticket)
    node = worker.node_name
    for slot in list(worker.store.hgetall(key)):
        if slot.partition("_")[0] == node:
            worker.store.hdel(key, slot)
    rm_file_or_dir(incoming_dir(worker, ticket))


def fail_ticket(worker, ticket, fileurl, error):
    """Mark a terminally failed download as ERROR in its slot (instead of the
    reference's slot deletion, reference bqueryd/worker.py:558-567, which made
    the remaining nodes' all-DONE barrier pass and activate a PARTIAL dataset
    while the waiting client was told DONE — flagged two-phase-commit fix).

    The ERROR state poisons the ticket: movebcolz never activates it (and
    cleans its own staging), waiting clients get the error back, and
    ``delete_download(ticket)`` clears the record."""
    # the state token must survive slot_state()'s rpartition('_') parsing
    reason = str(error).replace("_", "-")[:80] or "failed"
    set_progress(
        worker.store, worker.node_name, ticket, fileurl,
        f"{ERROR_PREFIX}:{reason}",
    )
    rm_file_or_dir(incoming_dir(worker, ticket))


def ticket_error(store, ticket):
    """First ERROR state recorded on a ticket, or None."""
    for value in store.hgetall(ticket_key(ticket)).values():
        state = slot_state(value)
        if state.startswith(ERROR_PREFIX):
            return state
    return None


# ---------------------------------------------------------------------------
# movebcolz side (phase 2 of the commit)
# ---------------------------------------------------------------------------

def check_moves(worker):
    """Activate a ticket only when every slot across ALL nodes is DONE and
    this node staged files for it (reference bqueryd/worker.py:594-633)."""
    for key in worker.store.keys(bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + "*"):
        ticket = key[len(bqueryd_tpu.REDIS_TICKET_KEY_PREFIX):]
        entries = worker.store.hgetall(key)
        if not entries:
            continue
        states = [slot_state(v) for v in entries.values()]
        if any(s.startswith(ERROR_PREFIX) for s in states):
            # poisoned ticket: never activate anywhere; drop own staging so
            # no node serves a partial dataset (the ERROR slot itself stays
            # visible until delete_download clears it)
            rm_file_or_dir(incoming_dir(worker, ticket))
            continue
        if not all(s == DONE for s in states):
            continue
        staging = incoming_dir(worker, ticket)
        if not os.path.isdir(staging):
            continue
        movebcolz(worker, ticket)


def movebcolz(worker, ticket):
    """Atomically swap staged shard dirs into the serving data_dir, stamping
    provenance metadata into each (reference bqueryd/worker.py:573-592)."""
    staging = incoming_dir(worker, ticket)
    for name in sorted(os.listdir(staging)):
        src = os.path.join(staging, name)
        if not os.path.isdir(src):
            continue
        with open(os.path.join(src, METADATA_FILENAME), "w") as f:
            json.dump({"ticket": ticket, "timestamp": time.time()}, f)
        dest = os.path.join(worker.data_dir, name)
        rm_file_or_dir(dest)
        shutil.move(src, dest)
        worker.logger.info("activated %s (ticket %s)", name, ticket)
    worker.remove_ticket(ticket)
