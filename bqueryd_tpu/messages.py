"""Wire protocol: JSON dict envelopes with base64-pickled binary fields.

Format-compatible with the reference protocol (reference bqueryd/messages.py:1-102):
a message is a plain dict serialized to JSON with at least ``msg_type``,
``payload``, ``version`` and ``created`` keys; call parameters travel as a
pickled ``{'args': ..., 'kwargs': ...}`` dict, base64-encoded, under the
``params`` key.  ``msg_factory`` maps ``msg_type`` strings to classes using the
same type names (``calc``, ``rpc``, ``error``, ``worker_register``, ``busy``,
``done``, ``ticketdone``, ``stop``).

Deliberate fixes over the reference (flagged in SURVEY.md §7.4):

* parse failures raise :class:`MalformedMessage` instead of the silent
  ``msg is None`` dead statement (reference bqueryd/messages.py:11);
  callers that want the lenient behaviour use ``msg_factory(..., strict=False)``.
* binary values are pickled with an explicit protocol so Python 3 nodes of
  mixed minor versions interoperate.

Security note: like the reference (reference README.md:129) pickled payloads
assume a trusted network.  ``Message.get_from_binary`` is the single choke
point, so a restricted unpickler can be installed here later.

Observability envelope schema (all keys optional, all JSON-safe — nodes
without them interoperate):

* ``trace`` — the distributed-tracing context injected by the RPC client and
  propagated on every hop: ``{"trace_id": hex, "span_id": hex,
  "parent_span_id": hex?}`` (:class:`bqueryd_tpu.obs.trace.TraceContext`).
  ``span_id`` is the SENDER's active span; the receiver parents its root
  span to it.  ``Message.set_trace``/``get_trace`` are the accessors.
* ``spans`` — on worker calc REPLIES: the worker's span list (see
  ``obs.trace.make_span`` for the per-span fields) which the controller
  folds into the query's ``rpc.trace(trace_id)`` timeline.
* ``phase_timings`` — on worker calc replies: ``{phase_name: seconds, ...,
  "_total": seconds}``.  Phase keys are the worker's own phase names
  (``open``, ``align``, ``mask``, ``layout``, ``aggregate``, ``collect``,
  ``serialize``, ``hostmerge``, ...); the synthetic whole-call wall lives
  under the underscore-namespaced ``_total`` key precisely so it can never
  collide with (and silently overwrite) a real phase named ``total``.
* on WorkerRegisterMessages (all optional; controllers ignore what they
  don't know): ``backend_wedged`` (bool, the device-health latch),
  ``work_errors`` (cumulative error-counter total — the controller's
  health scorer derives windowed error rates from its deltas),
  ``metrics`` (histogram snapshot, see obs.metrics), ``calibration`` (the
  worker's measured-cost strategy cells, see plan.calibrate), and ``debug`` — the
  node's debug-bundle slice (flight-ring tail, compile registry, device
  health, runtime versions; see obs.flightrec) absorbed controller-side
  so ``rpc.debug_bundle()`` can speak for dead peers.
"""

import base64
import json
import pickle
import time

PICKLE_PROTOCOL = 4

# -- wire schema --------------------------------------------------------------
# The single declared truth of every envelope key that crosses (or rides) the
# wire, diffed by ``bqueryd_tpu.analysis.wire`` against the key literals the
# wire modules (controller.py / worker.py / rpc.py) actually read and write:
# a key added on one side without the other is a LINT failure, not a silent
# ``None`` three hops later.  Adding a key to the protocol means adding it
# here, with help text, in the same commit.

#: JSON envelope keys (Message dicts).  Keys prefixed ``_`` are controller-
#: internal riders: they travel inside the process (and harmlessly on the
#: wire) but no peer may ever rely on them.
ENVELOPE_SCHEMA = {
    # base Message fields (set by the constructor / accessors below)
    "msg_type": "message class discriminator (msg_factory dispatch)",
    "payload": "verb name on requests; result/error text on replies",
    "version": "protocol version, currently 1",
    "created": "sender timestamp (preserved across parse/copy)",
    "params": "base64-pickled {'args', 'kwargs'} call parameters",
    "deadline": "absolute unix deadline, propagated client->worker",
    "trace": "distributed-tracing context {trace_id, span_id, ...}",
    # client -> controller
    "token": "request identity: client socket token / shard work token",
    "priority": "admission queue priority (ascending)",
    "client_id": "admission quota bucket for RPC(client_id=...)",
    "slo_class": "client-declared SLO class (RPC(slo_class=...)): selects "
                 "the deadline-margin histogram / burn-rate bucket the "
                 "query's outcome lands in (obs.slo; unknown classes fold "
                 "into 'default')",
    "function": "remote-execution verb: pickled callable name",
    "needs_local": "route only to workers holding the file locally",
    # controller -> worker shard dispatch
    "parent_token": "client query a shard CalcMessage belongs to",
    "filename": "shard rootdir(s) this work unit covers",
    "affinity": "pin dispatch to one worker id",
    "sole_shard": "single-shard query: worker may finalize on device",
    "plan": "base64-pickled plan fragment (query + predicates + strategy)",
    "bundle": "base64-pickled shared-scan bundle fragment: shared shard "
              "group + group-key columns plus one record per member query "
              "(member_id, aggs, filters, deadline) — the worker executes "
              "the whole compatible micro-batch as one scan "
              "(plan.bundle.bundle_fragment)",
    "dag": "base64-pickled operator-DAG wire form (plan.dag.OperatorDAG."
           "to_wire): the rpc.query verb's compiled program — broadcast "
           "join dimension table, window rollup, post-derivation filter, "
           "and the ordered physical agg list with extended op strings "
           "(topk:<k>:..., quantile:<q>:<alpha>).  Authoritative on "
           "capable workers; pre-DAG workers fall back to the positional "
           "params and reject the extended ops, which the controller "
           "rewrites into the structured UnsupportedOp mixed-version "
           "error (MIGRATION 'PR 13').  Extended partials ride the "
           "ordinary data frame as ResultPayload part kinds "
           "topk_values/topk_offsets and sketch_keys/sketch_counts/"
           "sketch_offsets (parallel.opexec)",
    "worker_id": "explicit dispatch target / WRM sender identity",
    "ticket": "download/movebcolz ticket id",
    # controller-originated rollup build/refresh (PR 16, serve.rollup)
    "rollup_prior": "on rollup refresh dispatches: the entry's previous "
                    "partials bytes for this shard — the worker merges the "
                    "appended tail into them when the stored chunk prefix "
                    "still validates (ops.workingset.growth_since)",
    "rollup_base": "base64-pickled chunk-prefix fingerprint "
                   "(ops.workingset.table_growth_base): on refresh "
                   "dispatches, the prefix the prior partials were computed "
                   "against; on build/refresh replies, the fingerprint of "
                   "the shard the returned partials cover — the next "
                   "refresh validates against it",
    "rollup_mode": "rollup reply provenance: 'rebuild' (full scan), 'delta' "
                   "(tail chunks aggregated and hostmerged into the "
                   "prior), 'fresh' (no growth, prior returned verbatim)",
    "rollup_zones": "base64-pickled per-column census of the shard the "
                    "rollup covers ({col: {kind, zones, nulls}}): dtype "
                    "kind plus per-chunk (min,max) zone maps — what the "
                    "subsumption lattice's key-fold null-freedom and "
                    "full-chunk filter proofs check (serve.subsume)",
    # worker -> controller replies
    "data": "raw result payload bytes",
    "phase_timings": "per-phase seconds dict; whole-call wall under _total",
    "spans": "worker span list folded into the query trace timeline",
    "deadline_remaining": "seconds left at reply serialization",
    "strategy": "the planner's kernel-strategy hint, echoed on the reply",
    "effective_strategy": "physical kernel route the worker ran post-guards "
                          "(matmul/scatter/sort/host; 'cached' = result-"
                          "cache hit, nothing compiled; 'delta' = delta-"
                          "maintained refresh: only appended chunks "
                          "re-aggregated, merged into the cached result) — "
                          "hints may normalize",
    "merge_mode": "how the reply's partials merged: 'device' (ICI-mesh "
                  "collective, final table only fetched — classic groupbys "
                  "since PR 7, batched extended-DAG dispatches since "
                  "PR 15), 'host' (hostmerge.merge_payloads fallback, also "
                  "the per-shard DAG pipeline's cross-shard merge), 'none' "
                  "(single payload, nothing merged)",
    "bundle_members": "on shared-scan bundle replies: the member_id list "
                      "the reply's data frame covers (its bytes are one "
                      "pickled {payloads: {member_id: bytes}, errors: "
                      "{member_id: text}} envelope the controller "
                      "demultiplexes per member)",
    "member_shares": "on shared-scan bundle replies: {member_id: fraction} "
                     "of the bundle's shared scan wall each member is "
                     "accountable for (measured per-member walls on the "
                     "fallback path, an equal split on the one-program "
                     "mesh path, 0.0 for result-cache hits) — the "
                     "controller scales the shared phase_timings by it so "
                     "a slow BUNDLE never lands every member in the "
                     "slow-query ring with the whole bundle's wall",
    "transient": "on worker ErrorMessage replies: the failure is retryable "
                 "(chaos.TransientError class, e.g. DeviceBusyError) — the "
                 "controller fails the shard over to a different holder "
                 "instead of aborting the query",
    "error": "failure detail on error/ticketdone paths",
    "result": "base64-pickled rpc verb return value",
    # worker register messages (WRM heartbeats)
    "node": "worker host name",
    "ip": "worker advertised IP",
    "data_dir": "worker shard directory",
    "data_files": "shard files the worker serves",
    "workertype": "calc | download",
    "pid": "worker process id",
    "uptime": "seconds since worker start",
    "msg_count": "messages handled by the worker",
    "backend_wedged": "device-health latch (health scoring + routing)",
    "work_errors": "cumulative error-counter total (health windows)",
    "debug": "node debug-bundle slice (flight tail, compile registry, ...)",
    "shard_stats": "per-shard planning stats (rows, min/max, cardinality)",
    "calibration": "measured-cost strategy calibration summary "
                   "(plan.calibrate cells, absorbed controller-side)",
    "metrics": "histogram snapshot (bucket-vector mergeable)",
    "pipeline_busy": "cumulative per-stage StageClock busy seconds "
                     "(parallel.pipeline snapshot) — the controller's "
                     "capacity model (obs.capacity) derives per-stage busy "
                     "deltas from it to name each worker's bottleneck "
                     "stage; None for non-calc roles",
    "liveness_only": "heartbeat-thread WRM: skip data_files rescan",
    # controller gossip + bookkeeping riders
    "from": "gossiping controller address",
    "info": "base64-pickled controller info snapshot (peer gossip)",
    "others": "peer-controller snapshots inside rpc.info(include_peers)",
    "last_seen": "controller-local: last WRM/gossip arrival time",
    "busy": "controller-local: worker has work in flight",
    "hb_only": "controller-local: worker seen only via heartbeats so far",
    "_retries": "controller-internal: dispatch retry count rider",
    "_excluded_workers": "controller-internal: holders this shard already "
                         "failed on — failover dispatch avoids them while "
                         "another candidate exists",
    "_attempt_history": "controller-internal: per-attempt worker/fault "
                        "records, surfaced in the structured exhaustion "
                        "envelope (attempts key)",
    "_not_before": "controller-internal: failover backoff gate — the "
                   "dispatcher holds the shard until this timestamp",
    "_backoff_s": "controller-internal: the backoff delay charged before "
                  "this attempt's dispatch — the attribution layer carves "
                  "it out of the dispatch span as a retry_backoff segment",
    "_bundle_parents": "controller-internal: member_id -> parent_token map "
                       "of a bundle dispatch; rides the envelope so the "
                       "reply (msg.copy) carries its own demux table",
    "_dispatch_queued_ts": "controller-internal: dispatch queue-entry time",
    "_relayed": "controller-internal: fan-out marker on relayed verbs",
    "_obs": "controller-internal: per-query observability state rider",
}

#: the pickled groupby RESULT envelope (not a Message): what rpc.py unpickles
#: from a calc reply
RESULT_ENVELOPE_SCHEMA = {
    "ok": "False when the query failed (error carries the reason)",
    "busy": "admission BUSY backpressure marker (RPCBusyError client-side)",
    "payloads": "per-shard-group ResultPayload byte strings (client result "
                "envelope); in a shared-scan bundle reply data frame, the "
                "{member_id: ResultPayload bytes} demux map",
    "v": "version stamp of a shared-scan bundle reply data frame",
    "errors": "in a shared-scan bundle reply data frame: {member_id: text} "
              "member-only failures (deadline expiry, member-shape "
              "rejection) — the controller aborts just those members",
    "timings": "compacted per-phase timing summary",
    "strategies": "planner report: {hints: hint->dispatches, effective: "
                  "shard-group->executed kernel route}",
    "merge_modes": "shard-group -> merge_mode the worker reported "
                   "(device/host/none; see the merge_mode envelope key)",
    "error": "failure reason when ok is False",
    "error_class": "structured failure class when ok is False (e.g. "
                   "'DispatchExhausted' once the retry/failover budget is "
                   "spent); None for plain errors",
    "attempts": "per-attempt worker/fault history ({worker, reason, "
                "retries, ts} dicts) behind an error_class failure — the "
                "flight-recorder trail a client can act on",
    "answer_source": "answer provenance (PR 16): 'recompute' | 'cached' "
                     "(every shard from a worker result cache) | 'delta' "
                     "(delta-maintained refresh) | 'rollup' (materialized "
                     "rollup served verbatim) | 'subsume' (folded from a "
                     "finer rollup by the subsumption lattice); surfaced "
                     "as rpc.last_call_answer_source",
    "subsumed_from": "on rollup/subsume answers: the materialized-view key "
                     "the answer was proven from (serve.subsume.view_key); "
                     "None on dispatched answers",
}

#: keys legitimately touched on only one side of the wire MODULES — the peer
#: lives elsewhere (the Message base class in this module, plan/admission,
#: client tooling).  Every waiver states where the other side is.
WIRE_ONE_SIDED_OK = {
    "msg_type": "written/read by Message.__init__ and msg_factory here",
    "version": "written by Message.__init__ here; never read yet (v1)",
    "created": "written by Message.__init__ here; age derived by readers",
    "params": "set_args_kwargs/get_args_kwargs accessors in this module",
    "deadline": "written via Message.set_deadline; read via the deadline "
                "helpers in this module",
    "trace": "set_trace/get_trace accessors in this module",
    "priority": "written by rpc.py; read by plan/admission.py (not a wire "
                "module)",
    "function": "read by worker.py execute_code; set by client tooling",
    "needs_local": "read by controller dispatch; set by download tooling",
    "ticket": "written by controller ticketdone replies; read by download "
              "tooling and coordination paths",
    "last_seen": "controller-local worker_map/gossip bookkeeping",
    "hb_only": "controller-local worker_map bookkeeping",
    "_obs": "controller-internal rider, intentionally unread elsewhere",
    "deadline_remaining": "informational reply field for clients/tests; "
                          "the controller deliberately ignores it",
    "strategy": "informational reply field (the hint echo) for "
                "clients/tests; dispatch accounting happens at send time",
    "others": "written into get_info(); read by rpc.info() clients/tests",
    "ip": "operator-facing WRM field surfaced via rpc.info(); the "
          "controller routes by socket identity, not this",
    "pid": "operator-facing WRM field surfaced via rpc.info()",
    "uptime": "operator-facing WRM field surfaced via rpc.info()",
    "msg_count": "operator-facing WRM field surfaced via rpc.info()",
    "v": "bundle data-envelope version stamp written by "
         "worker._handle_bundle; the controller's demux tolerates v1 only "
         "today, so nothing reads it yet",
}

#: The declared truth of every SPAN NAME that can appear on a query trace
#: timeline, diffed by ``bqueryd_tpu.analysis.spans`` against the literal
#: span sites (``timer.phase("...")`` / ``self._phase("...")`` /
#: ``recorder.span("...")`` / ``obs.make_span(trace_id, "...", ...)`` /
#: ``SpanRecorder(root_name="...")``) the package actually contains, and
#: against the attribution map in ``obs.slo.SPAN_CATEGORIES`` — so a new
#: dispatch path cannot ship spans that ``rpc.autopsy`` silently drops into
#: ``unattributed``.  RAW entries are worker PhaseTimer phase names; they
#: surface on the wire under their public name via
#: ``obs.trace.PHASE_SPAN_NAMES`` (noted per entry).  Adding a span site
#: means adding its name here (and a category in obs.slo) in the same
#: commit.
SPAN_SCHEMA = {
    # controller-side spans
    "groupby": "the query's controller root span: submit -> final reply",
    "admission": "admission-queue wait: submit -> launch (or -> staging)",
    "batch_window": "micro-batch staging wait: window stage -> flush "
                    "(BQUERYD_TPU_BATCH_WINDOW_MS)",
    "plan": "logical-plan compilation + rewrites inside rpc_groupby",
    "dispatch": "one dispatch ATTEMPT: queue entry -> worker send; tags "
                "carry worker/retries/backoff_s/hedge so the attribution "
                "layer can split out retry_backoff and hedge duplicates",
    "demux": "shared-scan bundle reply demultiplex at the controller",
    # worker-side spans (public names)
    "calc": "the worker's root span for one CalcMessage",
    "storage_decode": "raw phase 'open': shard open + column decode",
    "prune": "raw phase: chunk-level predicate pruning",
    "filter": "raw phase 'mask': where-term mask evaluation",
    "factorize": "raw phase: key factorization (engine path)",
    "join_probe": "raw phase 'join': broadcast hash-join key factorize + "
                  "dimension probe gather (operator-DAG executor)",
    "window_rollup": "raw phase 'rollup': datetime-bucket derived group "
                     "key computation (operator-DAG executor)",
    "align": "raw phase: cross-shard key alignment / global key space",
    "h2d_transfer": "raw phase 'layout': host->device uploads",
    "kernel": "raw phase 'aggregate': the compiled mesh program (collective "
              "merge fused in; includes async dispatch wait)",
    "d2h_fetch": "raw phase 'fetch': device->host fetch of the merged "
                 "result buffer",
    "merge": "raw phases 'collect'/'hostmerge': materialization / host "
             "value-keyed merge of partials",
    "reply_serialization": "raw phase 'serialize': result payload encoding",
    # raw PhaseTimer names (surface via obs.trace.PHASE_SPAN_NAMES)
    "open": "raw name of storage_decode",
    "mask": "raw name of filter",
    "join": "raw name of join_probe",
    "rollup": "raw name of window_rollup",
    "layout": "raw name of h2d_transfer",
    "aggregate": "raw name of kernel",
    "fetch": "raw name of d2h_fetch",
    "collect": "raw name of merge (device-path materialization)",
    "hostmerge": "raw name of merge (host value-keyed merge)",
    "serialize": "raw name of reply_serialization",
}


class MalformedMessage(Exception):
    pass


class Message(dict):
    """A message is a dict; subclasses only pin ``msg_type``."""

    msg_type = None

    def __init__(self, datadict=None):
        super().__init__()
        if not datadict:
            datadict = {}
        self.update(datadict)
        self["payload"] = datadict.get("payload")
        self["version"] = datadict.get("version", 1)
        self["msg_type"] = self.msg_type
        # Preserve the sender's timestamp across parse/copy so envelope age is
        # measurable; only stamp fresh messages.  (The reference re-stamped on
        # every parse, reference bqueryd/messages.py:37.)
        self["created"] = datadict.get("created", time.time())

    def copy(self):
        return msg_factory(dict(self))

    def isa(self, payload_or_class):
        """True if this message's type matches ``payload_or_class`` (a Message
        subclass) or its payload equals it (a string verb)."""
        if self.msg_type is not None and self.msg_type == getattr(
            payload_or_class, "msg_type", "_"
        ):
            return True
        return self.get("payload") == payload_or_class

    # -- binary fields -----------------------------------------------------
    def add_as_binary(self, key, value):
        self[key] = base64.b64encode(
            pickle.dumps(value, protocol=PICKLE_PROTOCOL)
        ).decode("ascii")

    def get_from_binary(self, key, default=None):
        buf = self.get(key)
        if not buf:
            return default
        if isinstance(buf, str):
            buf = buf.encode("ascii")
        return pickle.loads(base64.b64decode(buf))

    # -- deadlines ---------------------------------------------------------
    # A deadline is an absolute unix timestamp under the ``deadline`` key.
    # The RPC client stamps it, the controller copies it onto every shard
    # CalcMessage it fans out (and expires queued work past it), and the
    # worker refuses work that arrives already expired — replies keep the
    # field (Message.copy()), so deadlines propagate end to end.
    def set_deadline(self, seconds=None, at=None):
        """Absolute (``at``) or relative-to-now (``seconds``) deadline."""
        if at is not None:
            self["deadline"] = float(at)
        elif seconds is not None:
            self["deadline"] = time.time() + float(seconds)

    def deadline_remaining(self, now=None):
        """Seconds until the deadline, or None when none is set."""
        deadline = self.get("deadline")
        if deadline is None:
            return None
        return float(deadline) - (time.time() if now is None else now)

    def deadline_expired(self, now=None):
        remaining = self.deadline_remaining(now)
        return remaining is not None and remaining <= 0

    # -- tracing -----------------------------------------------------------
    # The trace context is a plain dict (schema in the module docstring) so
    # this module stays stdlib-only; obs.trace.TraceContext.from_wire parses
    # it at the hops that record spans.
    def set_trace(self, wire):
        """Attach a wire TraceContext dict (or a TraceContext via its
        ``to_wire``); None clears."""
        if wire is None:
            self.pop("trace", None)
            return
        if hasattr(wire, "to_wire"):
            wire = wire.to_wire()
        self["trace"] = dict(wire)

    def get_trace(self):
        """The wire TraceContext dict, or None."""
        wire = self.get("trace")
        return wire if isinstance(wire, dict) else None

    # -- call params -------------------------------------------------------
    def set_args_kwargs(self, args, kwargs):
        self.add_as_binary("params", {"args": args, "kwargs": kwargs})

    def get_args_kwargs(self):
        params = self.get_from_binary("params", {})
        return params.get("args", []), params.get("kwargs", {})

    def to_json(self):
        return json.dumps(self)


class WorkerRegisterMessage(Message):
    msg_type = "worker_register"


class CalcMessage(Message):
    """A unit of work for a calc worker.  Beyond the reference fields it may
    carry ``deadline`` (absolute ts, see the deadline helpers above) and
    ``plan`` — a pickled plan fragment (:func:`bqueryd_tpu.plan.fragment_for`)
    holding the rewritten query, pushed-down predicates, and the planner's
    kernel-strategy hint; workers execute the fragment when present and fall
    back to the positional params otherwise (mixed-version clusters)."""

    msg_type = "calc"


class RPCMessage(Message):
    msg_type = "rpc"


class ErrorMessage(Message):
    msg_type = "error"


class BusyMessage(Message):
    msg_type = "busy"


class DoneMessage(Message):
    msg_type = "done"


class StopMessage(Message):
    msg_type = "stop"


class TicketDoneMessage(Message):
    msg_type = "ticketdone"


MSG_MAPPING = {
    "calc": CalcMessage,
    "rpc": RPCMessage,
    "error": ErrorMessage,
    "worker_register": WorkerRegisterMessage,
    "busy": BusyMessage,
    "done": DoneMessage,
    "ticketdone": TicketDoneMessage,
    "stop": StopMessage,
    None: Message,
}


def msg_factory(msg, strict=True):
    """Parse ``msg`` (JSON str/bytes or dict) into the right Message subclass.

    Same dispatch table as the reference factory (reference
    bqueryd/messages.py:14-20); unknown ``msg_type`` values map to the base
    class so protocol extensions degrade gracefully.
    """
    if isinstance(msg, bytes):
        msg = msg.decode("utf-8", errors="replace")
    if isinstance(msg, str):
        try:
            msg = json.loads(msg)
        except ValueError as exc:
            if strict:
                raise MalformedMessage(f"unparseable message: {exc}") from exc
            msg = None
    if not msg:
        return Message()
    msg_class = MSG_MAPPING.get(msg.get("msg_type"), Message)
    return msg_class(msg)
