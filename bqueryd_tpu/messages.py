"""Wire protocol: JSON dict envelopes with base64-pickled binary fields.

Format-compatible with the reference protocol (reference bqueryd/messages.py:1-102):
a message is a plain dict serialized to JSON with at least ``msg_type``,
``payload``, ``version`` and ``created`` keys; call parameters travel as a
pickled ``{'args': ..., 'kwargs': ...}`` dict, base64-encoded, under the
``params`` key.  ``msg_factory`` maps ``msg_type`` strings to classes using the
same type names (``calc``, ``rpc``, ``error``, ``worker_register``, ``busy``,
``done``, ``ticketdone``, ``stop``).

Deliberate fixes over the reference (flagged in SURVEY.md §7.4):

* parse failures raise :class:`MalformedMessage` instead of the silent
  ``msg is None`` dead statement (reference bqueryd/messages.py:11);
  callers that want the lenient behaviour use ``msg_factory(..., strict=False)``.
* binary values are pickled with an explicit protocol so Python 3 nodes of
  mixed minor versions interoperate.

Security note: like the reference (reference README.md:129) pickled payloads
assume a trusted network.  ``Message.get_from_binary`` is the single choke
point, so a restricted unpickler can be installed here later.

Observability envelope schema (all keys optional, all JSON-safe — nodes
without them interoperate):

* ``trace`` — the distributed-tracing context injected by the RPC client and
  propagated on every hop: ``{"trace_id": hex, "span_id": hex,
  "parent_span_id": hex?}`` (:class:`bqueryd_tpu.obs.trace.TraceContext`).
  ``span_id`` is the SENDER's active span; the receiver parents its root
  span to it.  ``Message.set_trace``/``get_trace`` are the accessors.
* ``spans`` — on worker calc REPLIES: the worker's span list (see
  ``obs.trace.make_span`` for the per-span fields) which the controller
  folds into the query's ``rpc.trace(trace_id)`` timeline.
* ``phase_timings`` — on worker calc replies: ``{phase_name: seconds, ...,
  "_total": seconds}``.  Phase keys are the worker's own phase names
  (``open``, ``align``, ``mask``, ``layout``, ``aggregate``, ``collect``,
  ``serialize``, ``hostmerge``, ...); the synthetic whole-call wall lives
  under the underscore-namespaced ``_total`` key precisely so it can never
  collide with (and silently overwrite) a real phase named ``total``.
* on WorkerRegisterMessages (all optional; controllers ignore what they
  don't know): ``backend_wedged`` (bool, the device-health latch),
  ``work_errors`` (cumulative error-counter total — the controller's
  health scorer derives windowed error rates from its deltas),
  ``metrics`` (histogram snapshot, see obs.metrics), and ``debug`` — the
  node's debug-bundle slice (flight-ring tail, compile registry, device
  health, runtime versions; see obs.flightrec) absorbed controller-side
  so ``rpc.debug_bundle()`` can speak for dead peers.
"""

import base64
import json
import pickle
import time

PICKLE_PROTOCOL = 4


class MalformedMessage(Exception):
    pass


class Message(dict):
    """A message is a dict; subclasses only pin ``msg_type``."""

    msg_type = None

    def __init__(self, datadict=None):
        super().__init__()
        if not datadict:
            datadict = {}
        self.update(datadict)
        self["payload"] = datadict.get("payload")
        self["version"] = datadict.get("version", 1)
        self["msg_type"] = self.msg_type
        # Preserve the sender's timestamp across parse/copy so envelope age is
        # measurable; only stamp fresh messages.  (The reference re-stamped on
        # every parse, reference bqueryd/messages.py:37.)
        self["created"] = datadict.get("created", time.time())

    def copy(self):
        return msg_factory(dict(self))

    def isa(self, payload_or_class):
        """True if this message's type matches ``payload_or_class`` (a Message
        subclass) or its payload equals it (a string verb)."""
        if self.msg_type is not None and self.msg_type == getattr(
            payload_or_class, "msg_type", "_"
        ):
            return True
        return self.get("payload") == payload_or_class

    # -- binary fields -----------------------------------------------------
    def add_as_binary(self, key, value):
        self[key] = base64.b64encode(
            pickle.dumps(value, protocol=PICKLE_PROTOCOL)
        ).decode("ascii")

    def get_from_binary(self, key, default=None):
        buf = self.get(key)
        if not buf:
            return default
        if isinstance(buf, str):
            buf = buf.encode("ascii")
        return pickle.loads(base64.b64decode(buf))

    # -- deadlines ---------------------------------------------------------
    # A deadline is an absolute unix timestamp under the ``deadline`` key.
    # The RPC client stamps it, the controller copies it onto every shard
    # CalcMessage it fans out (and expires queued work past it), and the
    # worker refuses work that arrives already expired — replies keep the
    # field (Message.copy()), so deadlines propagate end to end.
    def set_deadline(self, seconds=None, at=None):
        """Absolute (``at``) or relative-to-now (``seconds``) deadline."""
        if at is not None:
            self["deadline"] = float(at)
        elif seconds is not None:
            self["deadline"] = time.time() + float(seconds)

    def deadline_remaining(self, now=None):
        """Seconds until the deadline, or None when none is set."""
        deadline = self.get("deadline")
        if deadline is None:
            return None
        return float(deadline) - (time.time() if now is None else now)

    def deadline_expired(self, now=None):
        remaining = self.deadline_remaining(now)
        return remaining is not None and remaining <= 0

    # -- tracing -----------------------------------------------------------
    # The trace context is a plain dict (schema in the module docstring) so
    # this module stays stdlib-only; obs.trace.TraceContext.from_wire parses
    # it at the hops that record spans.
    def set_trace(self, wire):
        """Attach a wire TraceContext dict (or a TraceContext via its
        ``to_wire``); None clears."""
        if wire is None:
            self.pop("trace", None)
            return
        if hasattr(wire, "to_wire"):
            wire = wire.to_wire()
        self["trace"] = dict(wire)

    def get_trace(self):
        """The wire TraceContext dict, or None."""
        wire = self.get("trace")
        return wire if isinstance(wire, dict) else None

    # -- call params -------------------------------------------------------
    def set_args_kwargs(self, args, kwargs):
        self.add_as_binary("params", {"args": args, "kwargs": kwargs})

    def get_args_kwargs(self):
        params = self.get_from_binary("params", {})
        return params.get("args", []), params.get("kwargs", {})

    def to_json(self):
        return json.dumps(self)


class WorkerRegisterMessage(Message):
    msg_type = "worker_register"


class CalcMessage(Message):
    """A unit of work for a calc worker.  Beyond the reference fields it may
    carry ``deadline`` (absolute ts, see the deadline helpers above) and
    ``plan`` — a pickled plan fragment (:func:`bqueryd_tpu.plan.fragment_for`)
    holding the rewritten query, pushed-down predicates, and the planner's
    kernel-strategy hint; workers execute the fragment when present and fall
    back to the positional params otherwise (mixed-version clusters)."""

    msg_type = "calc"


class RPCMessage(Message):
    msg_type = "rpc"


class ErrorMessage(Message):
    msg_type = "error"


class BusyMessage(Message):
    msg_type = "busy"


class DoneMessage(Message):
    msg_type = "done"


class StopMessage(Message):
    msg_type = "stop"


class TicketDoneMessage(Message):
    msg_type = "ticketdone"


MSG_MAPPING = {
    "calc": CalcMessage,
    "rpc": RPCMessage,
    "error": ErrorMessage,
    "worker_register": WorkerRegisterMessage,
    "busy": BusyMessage,
    "done": DoneMessage,
    "ticketdone": TicketDoneMessage,
    "stop": StopMessage,
    None: Message,
}


def msg_factory(msg, strict=True):
    """Parse ``msg`` (JSON str/bytes or dict) into the right Message subclass.

    Same dispatch table as the reference factory (reference
    bqueryd/messages.py:14-20); unknown ``msg_type`` values map to the base
    class so protocol extensions degrade gracefully.
    """
    if isinstance(msg, bytes):
        msg = msg.decode("utf-8", errors="replace")
    if isinstance(msg, str):
        try:
            msg = json.loads(msg)
        except ValueError as exc:
            if strict:
                raise MalformedMessage(f"unparseable message: {exc}") from exc
            msg = None
    if not msg:
        return Message()
    msg_class = MSG_MAPPING.get(msg.get("msg_type"), Message)
    return msg_class(msg)
