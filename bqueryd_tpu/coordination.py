"""Pluggable coordination store (cluster membership, tickets, locks).

The reference uses a Redis server for all host-side coordination: the
controller membership set (reference bqueryd/controller.py:79-81), download
ticket hashes (reference bqueryd/controller.py:457-462) and per-file
distributed locks with a TTL (reference bqueryd/worker.py:400-416).  This
framework keeps that architecture but abstracts the store behind one small
interface so clusters can run without a Redis server:

* ``redis://...``  — real Redis via redis-py, for production parity.
* ``mem://<name>`` — process-local store shared by name; the in-process
  thread-cluster test topology (the reference's own test strategy, reference
  tests/test_simple_rpc.py:42-74) uses this.
* ``file:///path`` — filesystem-backed store with POSIX-lock serialized
  updates, for multi-process single-host clusters.

Only the operations the framework needs are exposed: string sets, string
hashes, key scans, deletes, and TTL locks.  All values are ``str``.
"""

import fnmatch
import json
import os
import threading
import time

__all__ = [
    "coordination_store", "chaos_store", "CoordinationStore", "StoreLock",
    "StorePartitioned",
]


class StorePartitioned(OSError):
    """The coordination store is unreachable (injected by the chaos
    ``coordination.store`` site's ``partition`` action — the shape a real
    Redis ConnectionError takes).  Callers already treat store access as
    fallible: heartbeat ticks skip, loops log and continue."""


class StoreLock:
    """A named lock with a TTL, mirroring redis-py's ``Lock`` surface
    (``acquire(blocking=False)`` / ``release()``) used at reference
    bqueryd/worker.py:400-416.  Expired locks are claimable by others."""

    def __init__(self, store, name, ttl):
        self.store = store
        self.name = name
        self.ttl = ttl
        self.token = os.urandom(8).hex()

    def acquire(self, blocking=True, timeout=None):
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.store._lock_acquire(self.name, self.token, self.ttl):
                return True
            if not blocking:
                return False
            if deadline is not None and time.time() > deadline:
                return False
            time.sleep(0.05)

    def release(self):
        self.store._lock_release(self.name, self.token)

    def extend(self, additional_time):
        """Push the expiry ``additional_time`` seconds past now; long-running
        holders (a blob fetch outlasting the claim TTL) call this from their
        progress path so the claim can't expire mid-download and be re-claimed
        into a duplicate concurrent fetch."""
        return self.store._lock_acquire(
            self.name, self.token, additional_time
        )

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class CoordinationStore:
    """Abstract store; see module docstring for the operation set."""

    url = None

    # sets
    def sadd(self, key, member):
        raise NotImplementedError

    def srem(self, key, member):
        raise NotImplementedError

    def smembers(self, key):
        raise NotImplementedError

    # hashes
    def hset(self, key, field, value):
        raise NotImplementedError

    def hget(self, key, field):
        raise NotImplementedError

    def hgetall(self, key):
        raise NotImplementedError

    def hdel(self, key, *fields):
        raise NotImplementedError

    # keys
    def keys(self, pattern="*"):
        raise NotImplementedError

    def delete(self, *keys):
        raise NotImplementedError

    def flushdb(self):
        raise NotImplementedError

    # locks
    def lock(self, name, ttl):
        return StoreLock(self, name, ttl)

    def _lock_acquire(self, name, token, ttl):
        raise NotImplementedError

    def _lock_release(self, name, token):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# mem:// — shared-by-name in-process store
# ---------------------------------------------------------------------------

class _MemState:
    def __init__(self):
        self.lock = threading.RLock()
        self.sets = {}
        self.hashes = {}
        self.locks = {}  # name -> (token, expiry)


_MEM_REGISTRY = {}
_MEM_REGISTRY_LOCK = threading.Lock()


class MemoryStore(CoordinationStore):
    def __init__(self, url):
        self.url = url
        with _MEM_REGISTRY_LOCK:
            self._state = _MEM_REGISTRY.setdefault(url, _MemState())

    def sadd(self, key, member):
        with self._state.lock:
            self._state.sets.setdefault(key, set()).add(str(member))

    def srem(self, key, member):
        with self._state.lock:
            self._state.sets.get(key, set()).discard(str(member))

    def smembers(self, key):
        with self._state.lock:
            return set(self._state.sets.get(key, set()))

    def hset(self, key, field, value):
        with self._state.lock:
            self._state.hashes.setdefault(key, {})[str(field)] = str(value)

    def hget(self, key, field):
        with self._state.lock:
            return self._state.hashes.get(key, {}).get(str(field))

    def hgetall(self, key):
        with self._state.lock:
            return dict(self._state.hashes.get(key, {}))

    def hdel(self, key, *fields):
        with self._state.lock:
            h = self._state.hashes.get(key, {})
            for f in fields:
                h.pop(str(f), None)
            if not h:
                self._state.hashes.pop(key, None)

    def keys(self, pattern="*"):
        now = time.time()
        with self._state.lock:
            live_locks = {
                k for k, (_tok, exp) in self._state.locks.items() if exp > now
            }
            names = set(self._state.sets) | set(self._state.hashes) | live_locks
            return [k for k in names if fnmatch.fnmatchcase(k, pattern)]

    def delete(self, *keys):
        with self._state.lock:
            for k in keys:
                self._state.sets.pop(k, None)
                self._state.hashes.pop(k, None)
                self._state.locks.pop(k, None)

    def flushdb(self):
        with self._state.lock:
            self._state.sets.clear()
            self._state.hashes.clear()
            self._state.locks.clear()

    def _lock_acquire(self, name, token, ttl):
        now = time.time()
        with self._state.lock:
            held = self._state.locks.get(name)
            if held is not None and held[1] > now and held[0] != token:
                return False
            self._state.locks[name] = (token, now + ttl)
            return True

    def _lock_release(self, name, token):
        with self._state.lock:
            held = self._state.locks.get(name)
            if held is not None and held[0] == token:
                self._state.locks.pop(name, None)


# ---------------------------------------------------------------------------
# file:// — filesystem-backed store (multi-process, single host)
# ---------------------------------------------------------------------------

class FileStore(CoordinationStore):
    """One JSON file per key under the root dir; every mutation runs under an
    ``fcntl`` flock on ``<root>/.store.lock`` so concurrent processes
    serialize.  Key names are encoded to stay filesystem-safe."""

    def __init__(self, url):
        self.url = url
        self.root = url[len("file://"):] or "/tmp/bqueryd_tpu_store"
        os.makedirs(self.root, exist_ok=True)
        self._guard_path = os.path.join(self.root, ".store.lock")

    def _enc(self, key):
        return key.replace("/", "%2F") + ".json"

    def _dec(self, fname):
        return fname[:-5].replace("%2F", "/")

    class _Guard:
        def __init__(self, path):
            self.path = path

        def __enter__(self):
            import fcntl

            self.fd = open(self.path, "a+")
            fcntl.flock(self.fd, fcntl.LOCK_EX)
            return self

        def __exit__(self, *exc):
            import fcntl

            fcntl.flock(self.fd, fcntl.LOCK_UN)
            self.fd.close()

    def _guard(self):
        return FileStore._Guard(self._guard_path)

    def _load(self, key):
        path = os.path.join(self.root, self._enc(key))
        if not os.path.exists(path):
            return None
        with open(path) as f:
            try:
                return json.load(f)
            except ValueError:
                return None

    def _save(self, key, obj):
        path = os.path.join(self.root, self._enc(key))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)

    def _remove(self, key):
        path = os.path.join(self.root, self._enc(key))
        if os.path.exists(path):
            os.remove(path)

    def sadd(self, key, member):
        with self._guard():
            obj = self._load(key) or {"type": "set", "v": []}
            if str(member) not in obj["v"]:
                obj["v"].append(str(member))
            self._save(key, obj)

    def srem(self, key, member):
        with self._guard():
            obj = self._load(key)
            if obj and str(member) in obj["v"]:
                obj["v"].remove(str(member))
                self._save(key, obj)

    def smembers(self, key):
        with self._guard():
            obj = self._load(key)
            return set(obj["v"]) if obj else set()

    def hset(self, key, field, value):
        with self._guard():
            obj = self._load(key) or {"type": "hash", "v": {}}
            obj["v"][str(field)] = str(value)
            self._save(key, obj)

    def hget(self, key, field):
        with self._guard():
            obj = self._load(key)
            return obj["v"].get(str(field)) if obj else None

    def hgetall(self, key):
        with self._guard():
            obj = self._load(key)
            return dict(obj["v"]) if obj else {}

    def hdel(self, key, *fields):
        with self._guard():
            obj = self._load(key)
            if not obj:
                return
            for f in fields:
                obj["v"].pop(str(f), None)
            if obj["v"]:
                self._save(key, obj)
            else:
                self._remove(key)

    def keys(self, pattern="*"):
        with self._guard():
            names = [
                self._dec(f)
                for f in os.listdir(self.root)
                if f.endswith(".json") and not f.startswith(".")
            ]
            return [k for k in names if fnmatch.fnmatchcase(k, pattern)]

    def delete(self, *keys):
        with self._guard():
            for k in keys:
                self._remove(k)

    def flushdb(self):
        with self._guard():
            for f in os.listdir(self.root):
                if f.endswith(".json"):
                    os.remove(os.path.join(self.root, f))

    def _lock_acquire(self, name, token, ttl):
        # Locks are ordinary keys (visible to keys(), clearable with delete()),
        # matching how they would appear on a real Redis deployment.
        now = time.time()
        with self._guard():
            obj = self._load(name)
            if (
                obj
                and obj.get("type") == "lock"
                and obj["v"].get("expiry", 0) > now
                and obj["v"].get("token") != token
            ):
                return False
            self._save(name, {"type": "lock", "v": {"token": token, "expiry": now + ttl}})
            return True

    def _lock_release(self, name, token):
        with self._guard():
            obj = self._load(name)
            if obj and obj.get("type") == "lock" and obj["v"].get("token") == token:
                self._remove(name)


# ---------------------------------------------------------------------------
# redis:// — real Redis (gated on redis-py being installed)
# ---------------------------------------------------------------------------

class RedisStore(CoordinationStore):
    def __init__(self, url):
        import redis  # gated import: optional dependency

        self.url = url
        self._r = redis.from_url(url, decode_responses=True)

    def sadd(self, key, member):
        self._r.sadd(key, member)

    def srem(self, key, member):
        self._r.srem(key, member)

    def smembers(self, key):
        return set(self._r.smembers(key))

    def hset(self, key, field, value):
        self._r.hset(key, field, value)

    def hget(self, key, field):
        return self._r.hget(key, field)

    def hgetall(self, key):
        return self._r.hgetall(key)

    def hdel(self, key, *fields):
        if fields:
            self._r.hdel(key, *fields)

    def keys(self, pattern="*"):
        return list(self._r.keys(pattern))

    def delete(self, *keys):
        if keys:
            self._r.delete(*keys)

    def flushdb(self):
        self._r.flushdb()

    def lock(self, name, ttl):
        # thread_local=False: the claim is acquired on the event-loop thread
        # but released (and extended) by the download-pool thread; redis-py's
        # default thread-local token would make that cross-thread release
        # silently fail and pin the lock for its full TTL
        return _RedisLockAdapter(
            self._r.lock(name, timeout=ttl, thread_local=False)
        )


class _RedisLockAdapter:
    """Presents redis-py's Lock with the StoreLock surface so code written
    against mem:///file:// behaves identically on redis://: ``acquire``'s
    ``timeout`` means overall blocking time (redis-py calls it
    ``blocking_timeout``), and releasing an expired lock is a silent no-op
    (redis-py raises LockError; the reference had to catch it explicitly at
    reference bqueryd/worker.py:407-411)."""

    def __init__(self, redis_lock):
        self._lock = redis_lock

    def acquire(self, blocking=True, timeout=None):
        return self._lock.acquire(blocking=blocking, blocking_timeout=timeout)

    def release(self):
        import redis.exceptions

        try:
            self._lock.release()
        except redis.exceptions.LockError:
            pass

    def extend(self, additional_time):
        import redis.exceptions

        try:
            # replace_ttl: expiry becomes now+additional_time (StoreLock
            # semantics), not a cumulative add
            return self._lock.extend(additional_time, replace_ttl=True)
        except redis.exceptions.LockError:
            return False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


def coordination_store(url):
    """Construct the right backend for ``url``.  Accepts an existing store
    instance unchanged so tests can inject doubles (the reference's
    subclass-level seam strategy, SURVEY.md §4)."""
    if isinstance(url, (CoordinationStore, ChaosStore)):
        return url
    if url.startswith("mem://"):
        return MemoryStore(url)
    if url.startswith("file://"):
        return FileStore(url)
    if url.startswith("redis://") or url.startswith("rediss://"):
        return RedisStore(url)
    raise ValueError(f"unsupported coordination url: {url!r}")


# ---------------------------------------------------------------------------
# chaos seam — coordination.store injection site
# ---------------------------------------------------------------------------

class ChaosStore:
    """Delegating wrapper that fires the ``coordination.store`` chaos site
    before every operation, tagged with this node's id so a fault plan can
    partition ONE worker from the store while its zmq sockets stay up (the
    Redis-partition scenario).  The ``partition`` action raises
    :class:`StorePartitioned`; disarmed, each op pays one None check in
    ``chaos.fire``.

    Deliberately NOT a :class:`CoordinationStore` subclass: the base class
    defines every operation (as ``NotImplementedError`` stubs), which would
    shadow the ``__getattr__`` delegation below."""

    _OPS = (
        "sadd", "srem", "smembers", "hset", "hget", "hgetall", "hdel",
        "keys", "delete", "flushdb", "lock",
    )

    def __init__(self, inner, node_id=None):
        self._inner = inner
        self._node_id = node_id
        self.url = inner.url

    def _guarded(self, op):
        from bqueryd_tpu import chaos

        if not chaos.enabled():
            return
        fault = chaos.fire(
            "coordination.store", op=op, node=self._node_id
        )
        if fault is not None and fault.action == "partition":
            raise StorePartitioned(
                f"chaos: coordination store partitioned from "
                f"{self._node_id or 'node'} (op {op})"
            )

    def __getattr__(self, name):
        # only store OPERATIONS are guarded; anything else (url, private
        # helpers a backend exposes) passes straight through
        attr = getattr(self._inner, name)
        if name not in self._OPS:
            return attr

        def guarded(*args, **kwargs):
            self._guarded(name)
            result = attr(*args, **kwargs)
            if name == "lock":
                # the factory hands back a StoreLock bound to the INNER
                # store — wrap it so acquire/extend/release fail during a
                # partition window too (a real Redis partition kills the
                # lock operations, not just the factory call)
                result = _ChaosLock(result, self._guarded)
            return result

        guarded.__name__ = name
        return guarded


class _ChaosLock:
    """StoreLock proxy handed out by :class:`ChaosStore`: every lock
    operation re-fires the ``coordination.store`` site (op ``lock``) so a
    partitioned node loses its in-flight locks the way a real partition
    takes them — mid-acquire, mid-extend, mid-release."""

    def __init__(self, inner, guard):
        self._inner = inner
        self._guard = guard

    def acquire(self, *args, **kwargs):
        self._guard("lock")
        return self._inner.acquire(*args, **kwargs)

    def release(self):
        self._guard("lock")
        return self._inner.release()

    def extend(self, additional_time):
        self._guard("lock")
        return self._inner.extend(additional_time)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def chaos_store(store, node_id=None):
    """Wrap ``store`` with the ``coordination.store`` injection seam.
    Nodes wrap unconditionally — the disarmed cost is one None check per
    store op, and store ops run at heartbeat cadence, not query cadence."""
    if isinstance(store, ChaosStore):
        return store
    return ChaosStore(store, node_id=node_id)
