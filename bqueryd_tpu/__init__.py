"""bqueryd_tpu — TPU-native distributed columnar query framework.

A brand-new implementation of the capability set of visualfabriq/bqueryd
(reference: /root/reference — a ZeroMQ/Redis controller+worker cluster that fans
groupby/filter/aggregate queries over sharded bcolz column files and merges the
partials; see reference bqueryd/__init__.py:1-24 for the surface re-exported here).

Design differences from the reference (TPU-first, not a port):

* Compute runs as jit'd JAX columnar kernels (factorized group keys +
  ``segment_sum``) instead of Cython bquery kernels; shard partials merge with
  ``jax.lax.psum`` over a device mesh instead of tar-and-re-aggregate.
* Storage is a chunked, compressed columnar store with a C++ codec
  (byte-shuffle + LZ4-class compression) replacing bcolz/Blosc, keeping the
  same on-disk sharding semantics (``.bcolz`` / ``.bcolzs`` directories).
* Coordination is pluggable: ``redis://`` (when redis-py is installed, matching
  the reference deployment), ``mem://`` for in-process clusters (tests), and
  ``file://`` for multi-process single-host clusters without a Redis server.
* The wire protocol (JSON envelope + base64-pickled params, ``CalcMessage``
  et al.) and the ``rpc.groupby(...)`` entrypoint are kept compatible.

This module is intentionally light: no JAX import happens here so that pure
control-plane processes (controller, downloader) never pay for it.  Kernel
modules (``bqueryd_tpu.ops``, ``bqueryd_tpu.parallel``) import JAX lazily and
enable 64-bit mode for bit-exact int64 aggregates.

Unlike the reference (reference bqueryd/__init__.py:13-15) importing this
package has NO filesystem side effects; directories are created at node start.
"""

import logging
import os

logger = logging.getLogger("bqueryd_tpu")
logger.addHandler(logging.NullHandler())


def configure_logging(loglevel=logging.INFO):
    """Attach the framework's stream handler and set the root logger level.

    Called by node constructors and the CLI — NOT at import time, so embedding
    applications keep control of their logging config.  (The reference
    configured a stream handler as an import side effect, reference
    bqueryd/__init__.py:6-10.)

    ``BQUERYD_TPU_LOG_JSON=1`` switches the handler to structured JSON lines
    carrying ``trace_id``/``query_id`` correlation fields (see
    :mod:`bqueryd_tpu.obs.logs`) so fleet logs join against the RPC trace
    waterfall.
    """
    has_stream = any(
        isinstance(h, logging.StreamHandler)
        and not isinstance(h, logging.NullHandler)
        for h in logger.handlers
    )
    if not has_stream:
        handler = logging.StreamHandler()
        if os.environ.get("BQUERYD_TPU_LOG_JSON") == "1":
            from bqueryd_tpu.obs.logs import JsonLogFormatter

            handler.setFormatter(JsonLogFormatter())
        else:
            handler.setFormatter(
                logging.Formatter(
                    "%(asctime)s %(name)s %(levelname)s %(message)s"
                )
            )
        logger.addHandler(handler)
    logger.setLevel(loglevel)

#: Root of served shard directories (reference bqueryd/__init__.py:12).
DEFAULT_DATA_DIR = os.environ.get("BQUERYD_TPU_DATA_DIR", "/srv/bcolz/")
#: Staging area for in-flight downloads (reference bqueryd/__init__.py:13).
INCOMING = os.path.join(DEFAULT_DATA_DIR, "incoming")

#: Coordination-store key names are kept identical to the reference
#: (reference bqueryd/__init__.py:17-19) so a redis-backed deployment of this
#: framework is observable with the same tooling.
REDIS_SET_KEY = "bqueryd_controllers"
REDIS_TICKET_KEY_PREFIX = "bqueryd_download_ticket_"
REDIS_DOWNLOAD_LOCK_PREFIX = "bqueryd_download_lock_"
#: TTL for download locks, seconds (reference bqueryd/__init__.py:20).
REDIS_DOWNLOAD_LOCK_DURATION = 60 * 30

DEFAULT_COORDINATION_URL = os.environ.get(
    "BQUERYD_TPU_COORDINATION_URL", "redis://127.0.0.1:6379/0"
)

from bqueryd_tpu.version import __version__  # noqa: E402

_LAZY_EXPORTS = {
    "RPC": ("bqueryd_tpu.rpc", "RPC"),
    "ControllerNode": ("bqueryd_tpu.controller", "ControllerNode"),
    "WorkerNode": ("bqueryd_tpu.worker", "WorkerNode"),
    "DownloaderNode": ("bqueryd_tpu.worker", "DownloaderNode"),
    "MoveBcolzNode": ("bqueryd_tpu.worker", "MoveBcolzNode"),
}


def __getattr__(name):
    # PEP 562 lazy re-exports: keep `import bqueryd_tpu` light for pure
    # control-plane processes (the reference eagerly imported every role,
    # reference bqueryd/__init__.py:22-24).
    if name in _LAZY_EXPORTS:
        import importlib

        module, attr = _LAZY_EXPORTS[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module 'bqueryd_tpu' has no attribute {name!r}")

__all__ = [
    "RPC",
    "ControllerNode",
    "WorkerNode",
    "DownloaderNode",
    "MoveBcolzNode",
    "logger",
    "DEFAULT_DATA_DIR",
    "INCOMING",
    "REDIS_SET_KEY",
    "REDIS_TICKET_KEY_PREFIX",
    "REDIS_DOWNLOAD_LOCK_PREFIX",
    "REDIS_DOWNLOAD_LOCK_DURATION",
    "__version__",
]
