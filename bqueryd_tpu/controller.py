"""ControllerNode: the broker — discovery, scheduling, fan-out, sink merge.

Re-design of the reference controller (reference bqueryd/controller.py:28-578)
with the same observable surface (verbs, WRM registration cycle, dead-worker
cull, affinity queues, peer gossip) and three deliberate changes:

* **results are small**: workers return partial aggregation tables (or
  filtered rows), already merged across their local device mesh, so the sink
  keeps payloads in memory instead of spooling tar files to disk (reference
  bqueryd/controller.py:174-211);
* **dispatch is tracked**: every in-flight shard has a timestamp and is
  re-queued (bounded retries) if its worker dies or times out — the TODO the
  reference never implemented (reference bqueryd/controller.py:265);
* **the controller never imports JAX or pandas** — merging partial tables is
  the client's job (value-keyed NumPy merge), keeping the broker cheap.

Wire framing on the single ROUTER socket (identity ``tcp://ip:port``, random
port in 14300-14399, reference bqueryd/controller.py:33-42):

* 3 frames with empty middle  = RPC request from a REQ client
* 3 frames, non-empty middle  = worker reply carrying a binary result frame
* 2 frames                    = worker/peer control message
"""

import base64
import binascii
import os
import pickle
import random
import signal
import threading
import time

import zmq

import bqueryd_tpu
from bqueryd_tpu import backoff, chaos, messages
from bqueryd_tpu.coordination import coordination_store
from bqueryd_tpu.messages import (
    BusyMessage,
    CalcMessage,
    DoneMessage,
    ErrorMessage,
    Message,
    RPCMessage,
    StopMessage,
    TicketDoneMessage,
    WorkerRegisterMessage,
    msg_factory,
)
from bqueryd_tpu.utils.env import env_num
from bqueryd_tpu.utils.net import bind_to_random_port, get_my_ip

POLLING_TIMEOUT = 0.5        # seconds
DEAD_WORKER_TIMEOUT = 60.0   # cull workers silent longer than this
HEARTBEAT_INTERVAL = 2.0     # store re-registration + peer sync period
DISPATCH_TIMEOUT = 120.0     # re-queue in-flight work after this
DISPATCH_HARD_TIMEOUT = 1800.0  # ...even if the worker still heartbeats
MAX_DISPATCH_RETRIES = 2
RUNFILE_DIR = os.environ.get("BQUERYD_TPU_RUNFILE_DIR", "/srv")
#: failover pacing: exponential backoff between dispatch attempts of one
#: shard (base * 2^retries, capped) plus a deterministic per-token jitter so
#: a burst of simultaneous failovers doesn't stampede the surviving holder
#: (shared formula: bqueryd_tpu.backoff — the RPC client retries use it too)
RETRY_BACKOFF_BASE_S = backoff.BACKOFF_BASE_S
RETRY_BACKOFF_CAP_S = backoff.BACKOFF_CAP_S


# env-tunable timing knobs: the registered BQUERYD_TPU_* override when
# parseable, the module-constant default otherwise (chaos scenarios and
# small test clusters shrink these without monkeypatching)
_env_num = env_num

CONTROLLER_VERBS = (
    "ping", "loglevel", "info", "kill", "killworkers", "killall",
    "download", "readfile", "execute_code", "sleep", "groupby", "query",
    "trace", "metrics", "slow_queries", "health", "debug_bundle",
    "autopsy", "timeline", "capacity", "append",
)

#: how long an append fan-out may wait for every holder's reply before the
#: client gets a structured partial-failure error (a client deadline, when
#: set, wins)
APPEND_TIMEOUT = 120.0

#: help text for every controller counter — the spec the registry-backed
#: ``counters`` dict (obs.metrics.RegistryCounters) is built from; the same
#: keys keep working as plain dict entries everywhere (tests, bench, info)
COUNTER_SPECS = {
    "plan_pruned_shards": "shards excluded at plan time by advertised stats",
    "plan_shared_dispatches":
        "concurrent queries that joined an existing dispatch instead of "
        "paying their own (identical-work dedup + shared-scan bundle "
        "members beyond the first)",
    "plan_bundles":
        "shared-scan bundle dispatches: one decode/align/upload pass and "
        "one mesh program serving a whole compatible micro-batch",
    "plan_bundled_queries":
        "member queries that rode a shared-scan bundle dispatch",
    "plan_strategy_hints": "non-auto kernel-strategy hints issued",
    "plan_calibrated_overrides":
        "dispatches where measured walls overrode the heuristic route",
    "plan_explore_hints":
        "bounded-exploration dispatches sampling an unmeasured route",
    "plan_matmul_promotions":
        "calibration-backed matmul hints made binding inside the guards",
    "admission_busy": "BUSY backpressure replies sent to clients",
    "admission_queued": "plans held in the admission wait queue",
    "admission_superseded": "abandoned queries retired early on resend",
    "deadline_expired": "work expired by its deadline before running",
    "dispatched_shards": "groupby CalcMessages sent to workers",
    "queries_completed": "groupby parents finished (reply sent or aborted)",
    "slow_queries": "finished queries past BQUERYD_TPU_SLOW_QUERY_MS",
    "health_avoided_dispatches":
        "dispatch decisions that routed around a degraded/wedged worker",
    "reply_payload_bytes":
        "cumulative result-payload bytes received in worker calc replies "
        "(the controller-side twin of the worker's reply_bytes histogram)",
    "failover_dispatches":
        "shards re-queued after a worker loss, timeout, or transient fault "
        "(the retry excludes the failed holder)",
    "transient_faults":
        "transient (retryable) worker error replies that triggered a "
        "shard failover instead of a query abort",
    "hedged_dispatches":
        "duplicate tail-shard dispatches issued past BQUERYD_TPU_HEDGE_MS",
    "hedge_wins":
        "hedged dispatches whose duplicate replied before the original",
    "duplicate_replies":
        "worker replies deduplicated by query token (hedge losers, "
        "late retries, chaos-duplicated envelopes) — counted, never "
        "double-merged",
    "capacity_scale_up_advised":
        "shadow-advisor scale_up recommendations emitted (advisory only — "
        "logged to the flight ring, never acted on)",
    "capacity_scale_down_advised":
        "shadow-advisor scale_down recommendations emitted (advisory only)",
    "capacity_rebalance_advised":
        "shadow-advisor shard-rebalance recommendations emitted (advisory "
        "only)",
    "append_requests":
        "client rpc.append calls accepted for fan-out to shard holders",
    "append_dispatches":
        "per-holder append CalcMessages dispatched (one per distinct "
        "(node, data_dir) replica of the target shard)",
    "rollup_builds":
        "materialized-rollup shard builds completed (serve.rollup full "
        "rebuilds; delta refreshes count separately)",
    "rollup_refreshes":
        "rollup shard refreshes served by aggregating only appended tail "
        "chunks (growth_since exact-prefix validation)",
    "rollup_evictions":
        "rollup entries dropped by the retention sweep (count/byte caps, "
        "wedged-build timeout)",
}


class ControllerNode:
    def __init__(
        self,
        coordination_url=None,
        redis_url=None,
        loglevel=None,
        runfile_dir=RUNFILE_DIR,
        heartbeat_interval=HEARTBEAT_INTERVAL,
        dead_worker_timeout=None,
        dispatch_timeout=None,
        dispatch_hard_timeout=None,
        port_range=(14300, 14400),
        admit_max_active=None,
        admit_queue_depth=None,
        admit_client_quota=None,
        max_dispatch_retries=None,
        hedge_ms=None,
    ):
        import logging

        bqueryd_tpu.configure_logging(loglevel or logging.INFO)
        # fault injection (bqueryd_tpu.chaos): armed only when
        # BQUERYD_TPU_FAULT_PLAN is set; every injection site below is a
        # single None check otherwise
        chaos.maybe_arm_from_env()
        self.store = coordination_store(
            coordination_url or redis_url or bqueryd_tpu.DEFAULT_COORDINATION_URL
        )
        self.heartbeat_interval = heartbeat_interval
        # timing knobs resolve ctor arg -> registered env var -> module
        # constant, so chaos scenarios can shrink them per process
        if dead_worker_timeout is None:
            dead_worker_timeout = _env_num(
                "BQUERYD_TPU_DEAD_WORKER_TIMEOUT", DEAD_WORKER_TIMEOUT
            )
        if dispatch_timeout is None:
            dispatch_timeout = _env_num(
                "BQUERYD_TPU_DISPATCH_TIMEOUT", DISPATCH_TIMEOUT
            )
        if dispatch_hard_timeout is None:
            dispatch_hard_timeout = _env_num(
                "BQUERYD_TPU_DISPATCH_HARD_TIMEOUT", DISPATCH_HARD_TIMEOUT
            )
        self.dead_worker_timeout = dead_worker_timeout
        self.dispatch_timeout = dispatch_timeout
        self.dispatch_hard_timeout = max(dispatch_hard_timeout, dispatch_timeout)
        self.max_dispatch_retries = (
            max_dispatch_retries
            if max_dispatch_retries is not None
            else _env_num(
                "BQUERYD_TPU_MAX_DISPATCH_RETRIES", MAX_DISPATCH_RETRIES, int
            )
        )
        # hedged duplicate dispatch for tail shards: 0 (the default) is OFF;
        # >0 duplicates a shard still inflight past this many milliseconds
        # onto a second healthy holder, first reply wins (dedup by token)
        self.hedge_ms = (
            hedge_ms if hedge_ms is not None
            else _env_num("BQUERYD_TPU_HEDGE_MS", 0.0)
        )
        # replica placement hint: download fan-out targets this many holders
        # per shard (0 = every node, the historical behaviour; see
        # download.setup_download); surfaced in get_info and the
        # replica_holders gauges so under-replication is visible
        self.replica_factor = max(
            _env_num("BQUERYD_TPU_REPLICA_FACTOR", 0, int), 0
        )

        self.context = zmq.Context.instance()
        self.socket = self.context.socket(zmq.ROUTER)
        self.socket.setsockopt(zmq.ROUTER_MANDATORY, 1)
        self.socket.setsockopt(zmq.SNDTIMEO, 1000)
        self.socket.setsockopt(zmq.LINGER, 500)
        ip = get_my_ip()
        self.address = bind_to_random_port(
            self.socket, f"tcp://{ip}", port_range[0], port_range[1]
        )
        self.logger = bqueryd_tpu.logger.getChild(f"controller.{self.address}")
        self.node_name = __import__("socket").gethostname()

        self.poller = zmq.Poller()
        self.poller.register(self.socket, zmq.POLLIN)

        # state
        self.worker_map = {}          # worker_id -> wrm info (+ last_seen/busy)
        self._adoption_blocked = {}   # worker_id -> until-ts (hb-only quarantine)
        self.files_map = {}           # filename -> set(worker_id)
        self.others = {}              # peer address -> info
        self.worker_out_messages = {None: []}  # affinity -> [msg, ...]
        self._affinity_rr = 0
        self.rpc_segments = {}        # parent_token -> fan-out bookkeeping
        self.inflight = {}            # shard token -> dict(worker, sent_at, msg, parent)
        self._hedged_tokens = {}      # token -> hedge ts (late-reply dedup)
        self._hedge_losers = {}       # token -> dict(workers, since): reclaim
        #                               handle on the non-winning side of a
        #                               hedge (its inflight entry is gone)
        self._requeued_tokens = set()  # retries parked in the dispatch queue
        #                                (backoff window): a late reply from
        #                                the failed attempt must not abort
        #                                or double-execute past them
        # streaming-append fan-out bookkeeping (rpc_append): one segment
        # per client call, one dispatch token per replica holder
        self._append_segments = {}    # segment key -> fan-out state
        self._append_waiters = {}     # dispatch token -> segment key
        self._holder_counts_memo = None  # (ts, counts) scrape-window memo
        # -- planning & admission state -------------------------------------
        from bqueryd_tpu.plan import AdmissionController

        self.admission = AdmissionController(
            max_active=admit_max_active,
            queue_depth=admit_queue_depth,
            client_quota=admit_client_quota,
        )
        self._admitting = False
        self._ticket_sigs = {}        # live ticket -> plan signature
        self.shard_stats = {}         # filename -> advertised planning stats
        # measured-cost strategy calibration: WRM `calibration` summaries
        # from workers merge into this model (plan.calibrate), consulted by
        # select_calibrated at dispatch time; in-memory only — the workers
        # own persistence (their measurements re-gossip after a restart)
        from bqueryd_tpu.plan import calibrate as _calibrate

        self.calibration = _calibrate.CalibrationStore()
        # -- semantic serving (PR 16) ---------------------------------------
        # subsumption lattice + materialized-rollup manager (serve/): hit
        # replies skip admission entirely; BQUERYD_TPU_SERVE=0 makes every
        # entry point a no-op without tearing the object down
        from bqueryd_tpu.serve import ServingLayer

        self.serving = ServingLayer(self)
        self._rollup_waiters = {}     # dispatch token -> (entry key, filename)
        self._work_subscribers = {}   # shard token -> [parent_token, ...]
        self._work_keys = {}          # shard token -> shared-dispatch key
        self._work_index = {}         # shared-dispatch key -> shard token
        # admission micro-batch window (plan.bundle): admitted groupby
        # plans staged here until the window closes, then flushed grouped
        # by compatibility signature; empty (and bypassed) at window 0
        self._pending_window = []     # [(msg, plan, kwargs), ...]
        self._window_opened = 0.0
        # -- observability ---------------------------------------------------
        from bqueryd_tpu import obs

        self.metrics = obs.MetricsRegistry()
        # the ad-hoc counters dict, migrated: same dict surface, every write
        # mirrored into a typed registry Counter (Prometheus exposition)
        self.counters = obs.RegistryCounters(self.metrics, COUNTER_SPECS)
        # liveness gauges are callback-backed: read at scrape time, no upkeep
        self.metrics.gauge(
            "bqueryd_tpu_admission_active",
            "plans currently executing", fn=lambda: len(self.admission._active),
        )
        self.metrics.gauge(
            "bqueryd_tpu_admission_queue_depth",
            "plans waiting in the admission queue",
            fn=lambda: len(self.admission._queued),
        )
        self.metrics.gauge(
            "bqueryd_tpu_inflight_shards",
            "shard dispatches awaiting a worker reply",
            fn=lambda: len(self.inflight),
        )
        self.metrics.gauge(
            "bqueryd_tpu_workers_known",
            "workers currently registered", fn=lambda: len(self.worker_map),
        )
        self.metrics.gauge(
            "bqueryd_tpu_fault_injected_total",
            "faults injected by the armed chaos plan, process-lifetime "
            "(0 while BQUERYD_TPU_FAULT_PLAN is unarmed)",
            fn=chaos.injected_total,
        )
        # replica visibility: shards by live holder count — failover needs
        # at least 2 holders, so the holders="1" gauge is the pager signal
        for bucket in ("1", "2", "3plus"):
            self.metrics.gauge(
                "bqueryd_tpu_replica_holders",
                "advertised shards by live holder count (failover needs a "
                "second holder; see BQUERYD_TPU_REPLICA_FACTOR)",
                labels={"holders": bucket},
                fn=(lambda b=bucket: self._holder_counts().get(b, 0)),
            )
        self.query_seconds = self.metrics.histogram(
            "bqueryd_tpu_groupby_seconds",
            "end-to-end groupby wall at the controller (admission to reply)",
        )
        self.admission_wait_seconds = self.metrics.histogram(
            "bqueryd_tpu_admission_wait_seconds",
            "time queued in admission before launch",
        )
        # admission wait observations ride the controller's hook so the
        # admission module stays metrics-agnostic
        self.admission.wait_observer = self._observe_admission_wait
        self.trace_store = obs.TraceStore()
        self.slow_queries = obs.SlowQueryLog()
        # SLO accounting (obs.slo): per-client-class deadline-margin
        # histograms + burn-rate gauges, fed by every finished groupby in
        # _finalize_query_obs; the timeline ring snapshots the registry
        # periodically behind rpc.timeline() for regression spotting
        self.slo = obs.slo.SLOTracker(self.metrics)
        self.timeline_ring = obs.slo.SnapshotTimeline()
        # fleet capacity model (obs.capacity): per-worker μ from WRM
        # histogram deltas, per-class λ from the admission tap, ρ/states
        # with hysteresis, shard heat map, shadow scale/rebalance advice —
        # evaluated each heartbeat, served by rpc.capacity()
        self.capacity = obs.capacity.CapacityModel(
            on_advice=self._record_capacity_advice
        )
        self.admission.arrival_observer = self._observe_arrival
        self.metrics.gauge(
            "bqueryd_tpu_capacity_fleet_utilization",
            "fleet utilization estimate ρ (dispatch rate over aggregate "
            "service rate, tempered by measured busy fractions)",
            fn=lambda: self.capacity.fleet_gauge("utilization"),
        )
        self.metrics.gauge(
            "bqueryd_tpu_capacity_fleet_state",
            "fleet saturation state code (0=ok 1=warm 2=saturated "
            "3=overloaded, hysteresis applied)",
            fn=lambda: self.capacity.fleet_gauge("state"),
        )
        self.metrics.gauge(
            "bqueryd_tpu_capacity_headroom_qps",
            "estimated additional query arrival rate the fleet can absorb "
            "before utilization crosses BQUERYD_TPU_CAPACITY_TARGET_RHO",
            fn=lambda: self.capacity.fleet_gauge("headroom_qps"),
        )
        self.metrics.gauge(
            "bqueryd_tpu_capacity_model_drift",
            "model-vs-measured queue-delay drift: (predicted - measured) / "
            "max(both) — near 0 means the M/G/1 prediction tracks reality",
            fn=lambda: self.capacity.fleet_gauge("model_drift"),
        )
        self.metrics.gauge(
            "bqueryd_tpu_capacity_worker_resets",
            "WRM counter restarts the capacity model detected and rebased "
            "(worker processes restarting under the same node id)",
            fn=self.capacity.worker_resets,
        )
        self._worker_metrics = {}     # worker_id -> last histogram snapshot
        self._worker_metrics_rev = 0  # bumped on absorb/remove (cache key)
        self._worker_hist_cache = (-1, None)  # (rev, merged aggregate)
        # -- forensics & health (PR 3) --------------------------------------
        # flight recorder: bounded always-on ring of envelopes/dispatches/
        # timeouts/worker churn behind rpc.debug_bundle() + SIGUSR1
        self.flight = obs.FlightRecorder(node_id=self.address)
        # WRM-absorbed per-worker debug snapshots (flight tail + compile
        # registry + device health).  DELIBERATELY kept after a worker is
        # removed: a dead peer's last words are exactly what a debug bundle
        # is for — bounded to the newest entries so churn can't grow it
        self._worker_debug = {}       # worker_id -> {"data", "ts"}
        self._worker_debug_cap = 64
        self._worker_wedged = {}      # worker_id -> last advertised latch
        # health scorer: rolling latency/error baselines from the WRM
        # signals, fed back into find_free_worker's candidate ordering
        self.health = obs.HealthScorer()
        for name, help_text, fn in (
            (
                "bqueryd_tpu_trace_buffer_evictions",
                "trace timelines evicted by the ring's entry/byte bounds "
                "(monotonic)",
                lambda: self.trace_store.evictions,
            ),
            (
                "bqueryd_tpu_slow_query_evictions",
                "slow-query entries evicted by the ring's entry/byte bounds "
                "(monotonic)",
                lambda: self.slow_queries.evictions,
            ),
            (
                "bqueryd_tpu_flight_evictions",
                "flight-ring events evicted by the entry/byte bounds "
                "(monotonic)",
                lambda: self.flight.evictions,
            ),
            (
                "bqueryd_tpu_workers_degraded",
                "registered workers currently scored degraded or wedged",
                lambda: sum(
                    1 for s in self.health.statuses().values()
                    if s.get("status") != obs.STATUS_OK
                ),
            ),
        ):
            self.metrics.gauge(name, help_text, fn=fn)
        from bqueryd_tpu.obs import http as obs_http

        self._metrics_server = obs_http.maybe_start(self.metrics, self.logger)
        self.msg_count_in = 0
        self.start_time = time.time()
        self.running = False
        self._loop_thread = None
        self.last_heartbeat = 0.0

        self.runfile_dir = runfile_dir
        self._write_runfiles()

    # -- runfiles ----------------------------------------------------------
    def _write_runfiles(self):
        self._runfiles = []
        try:
            for suffix, content in (
                ("address", self.address),
                ("pid", str(os.getpid())),
            ):
                path = os.path.join(
                    self.runfile_dir, f"bqueryd_tpu_controller.{suffix}"
                )
                with open(path, "w") as f:
                    f.write(content)
                self._runfiles.append(path)
        except OSError:
            self.logger.debug("runfile dir %s not writable", self.runfile_dir)

    def _remove_runfiles(self):
        for path in self._runfiles:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- main loop ---------------------------------------------------------
    def go(self):
        self.running = True
        self._loop_thread = threading.current_thread()
        try:
            # graceful supervisord stop: deregister from the store and
            # remove runfiles instead of dying mid-dispatch (the worker
            # installs the same handler; reference nodes relied on process
            # teardown alone)
            signal.signal(signal.SIGTERM, self._term_signal)
            if hasattr(signal, "SIGUSR1"):
                # local forensic dump: kill -USR1 <pid> writes the full
                # debug bundle without needing a live client
                signal.signal(signal.SIGUSR1, self._dump_debug_signal)
        except ValueError:
            pass  # not the main thread (in-process test clusters)
        self.logger.info("controller %s running", self.address)
        try:
            while self.running:
                try:
                    self.heartbeat()
                    self.free_dead_workers()
                    self.retry_stale_dispatches()
                    self.maybe_hedge()
                    self._sweep_append_segments()
                    # a pending micro-batch window bounds the poll sleep:
                    # the flush must fire when the window closes, not a full
                    # POLLING_TIMEOUT later (closed-loop clients send
                    # nothing while their queries sit staged)
                    timeout_s = POLLING_TIMEOUT
                    if self._pending_window:
                        remaining = self._window_deadline() - time.time()
                        timeout_s = max(min(timeout_s, remaining), 0.0)
                    events = dict(self.poller.poll(int(timeout_s * 1000)))
                    if self.socket in events:
                        # drain everything available this tick
                        while True:
                            try:
                                frames = self.socket.recv_multipart(zmq.NOBLOCK)
                            except zmq.Again:
                                break
                            self.handle_in(frames)
                    self._admit_ready()
                    self._flush_window()
                    self.dispatch_pending()
                except Exception:
                    self.logger.exception("error in controller loop")
        finally:
            self.stop()

    def _term_signal(self, *args):
        self.logger.info("SIGTERM received, stopping")
        self.running = False

    def stop(self):
        # doubles as a cross-thread shutdown REQUEST (see WorkerBase.stop):
        # an external caller only flags the loop — the loop thread re-enters
        # here on exit for the store/socket teardown (zmq sockets are
        # single-thread-only)
        self.running = False
        loop = self._loop_thread
        if (
            loop is not None
            and loop.is_alive()
            and threading.current_thread() is not loop
        ):
            return
        try:
            self.store.srem(bqueryd_tpu.REDIS_SET_KEY, self.address)
        except Exception:
            pass
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        self._remove_runfiles()
        if not self.socket.closed:
            self.socket.close()
            self.logger.info("controller %s stopped", self.address)

    # -- membership --------------------------------------------------------
    def heartbeat(self):
        now = time.time()
        if now - self.last_heartbeat < self.heartbeat_interval:
            return
        self.last_heartbeat = now
        # capacity model evaluation: per-worker/fleet ρ + states
        # (hysteresis is wall-clock based, so the heartbeat cadence doesn't
        # matter) + shadow advice; no-op under BQUERYD_TPU_CAPACITY=0.
        # BEFORE the timeline snapshot, so every ring entry carries THIS
        # beat's capacity slice (and the first entry is never empty)
        self.capacity.evaluate(now=now)
        # controller timeline ring: one bounded registry snapshot per
        # BQUERYD_TPU_TIMELINE_INTERVAL_S (the ring paces itself; <=0
        # disables), served by rpc.timeline()
        self.timeline_ring.maybe_snapshot(self._timeline_snapshot, now=now)
        # serving housekeeping: abandon wedged rollup builds, enforce the
        # retention caps, dispatch delta refreshes for stale entries
        self.serving.tick()
        self.store.sadd(bqueryd_tpu.REDIS_SET_KEY, self.address)
        current = self.store.smembers(bqueryd_tpu.REDIS_SET_KEY)
        for addr in current:
            if addr == self.address or addr in self.others:
                continue
            self.logger.debug("connecting to peer %s", addr)
            self.socket.connect(addr)
            self.others[addr] = {"last_seen": 0.0}
        for addr in list(self.others):
            if addr not in current:
                self.others.pop(addr, None)
                continue
            gossip = Message({"payload": "peer_info"})
            gossip["from"] = self.address
            gossip.add_as_binary("info", self.get_info(include_peers=False))
            try:
                self.socket.send_multipart(
                    [addr.encode(), gossip.to_json().encode()]
                )
            except zmq.ZMQError:
                # unreachable peer: drop it from the registry so clients and
                # workers stop trying it (reference bqueryd/controller.py:94-97)
                self.logger.warning("peer %s unreachable, removing", addr)
                self.store.srem(bqueryd_tpu.REDIS_SET_KEY, addr)
                self.others.pop(addr, None)

    def free_dead_workers(self):
        """Cull workers silent longer than ``dead_worker_timeout`` — but never
        one we handed in-flight work younger than ``dispatch_timeout``: culling
        it would drop its ``files_map`` entries and fail-fast the very query it
        is busy computing (the round-1 benchmark failure).  A genuinely hung
        worker is still reclaimed: its dispatch times out, the shard is
        requeued, and with nothing in flight the cull proceeds next tick."""
        now = time.time()
        for worker_id, info in list(self.worker_map.items()):
            # hb_only adoptees heartbeat forever even with a permanently
            # wedged main loop — and while advertised-but-busy they block the
            # 'no longer on any worker' fail-fast for their shards without
            # ever going inflight (so no dispatch timeout fires either).
            # Give the main socket dispatch_hard_timeout to speak (a legit
            # first-query compile fits), then reclaim.
            hb_since = info.get("hb_only")
            if hb_since and now - hb_since > self.dispatch_hard_timeout:
                self.logger.warning(
                    "hb-only worker %s never spoke on its main socket in "
                    "%.0fs, removing", worker_id, now - hb_since,
                )
                # quarantine against instant re-adoption by its (still
                # ticking) heartbeat thread; a real main-socket WRM lifts it
                self._adoption_blocked[worker_id] = (
                    now + self.dispatch_hard_timeout
                )
                self.remove_worker(worker_id)
                continue
            if now - info.get("last_seen", now) <= self.dead_worker_timeout:
                continue
            if any(
                e["worker"] == worker_id
                and now - e["sent_at"] <= self.dispatch_timeout
                for e in self.inflight.values()
            ):
                continue
            self.logger.warning("culling dead worker %s", worker_id)
            self.remove_worker(worker_id)

    def remove_worker(self, worker_id):
        if worker_id in self.worker_map:
            # forensic event (never gated); the worker's debug snapshot in
            # _worker_debug deliberately survives for rpc.debug_bundle()
            self.flight.record("worker_removed", worker=worker_id)
        self.worker_map.pop(worker_id, None)
        self.health.remove(worker_id)
        self.capacity.remove_worker(worker_id)
        self._worker_wedged.pop(worker_id, None)
        if self._worker_metrics.pop(worker_id, None) is not None:
            self._worker_metrics_rev += 1
        for filename in list(self.files_map):
            self.files_map[filename].discard(worker_id)
            if not self.files_map[filename]:
                del self.files_map[filename]
                self.shard_stats.pop(filename, None)
        # fail pending append dispatches to the removed holder FAST: the
        # fan-out cannot complete anymore, and the client would otherwise
        # wait out the whole segment timeout for a worker that is gone
        for seg_key, segment in list(self._append_segments.items()):
            gone = [
                t for t, w in segment["pending"].items() if w == worker_id
            ]
            for t in gone:
                segment["pending"].pop(t, None)
                self._append_waiters.pop(t, None)
                segment["errors"][worker_id] = (
                    "holder removed (worker lost before confirming)"
                )
            if gone and not segment["pending"]:
                self._finish_append_segment(seg_key, segment)
        # re-queue anything in flight on that worker; a hedged flight
        # collapses onto its surviving side instead (the duplicate is
        # still computing — a fresh dispatch would be redundant)
        for token, entry in list(self.inflight.items()):
            if entry.get("hedged") == worker_id:
                self.inflight.pop(token)
                self._collapse_hedge(token, entry, worker_id)
            elif entry["worker"] == worker_id:
                self.inflight.pop(token)
                if entry.get("hedged"):
                    self._collapse_hedge(token, entry, worker_id)
                else:
                    self._requeue(entry)

    def _absorb_worker_metrics(self, worker_id, info):
        """Latest histogram snapshot per worker (rides the WRM like shard
        stats); aggregated by bucket-vector addition in get_info.  Also
        feeds the WRM's health signals (histograms + error counter +
        backend_wedged) into the health scorer, records wedge-latch flips
        in the flight ring, and absorbs the worker's debug-bundle slice."""
        snap = info.get("metrics")
        wedged = bool(info.get("backend_wedged"))
        # fleet capacity ingestion: μ from the service-histogram deltas +
        # bottleneck stages from the pipeline busy clocks + the wedge
        # latch (a wedged device's μ is excluded from fleet capacity).
        # Calc workers only — downloaders serve no queries and would drag
        # the model's coverage/μ averages.  Runs BEFORE the dedup below:
        # deltas need the fresh cumulative totals every heartbeat,
        # identical or not.
        pipeline_busy = info.pop("pipeline_busy", None)
        if info.get("workertype") == "calc" and isinstance(snap, dict):
            self.capacity.absorb_worker(
                worker_id, snap, pipeline_busy=pipeline_busy,
                wedged=wedged, pid=info.get("pid"),
            )
        if isinstance(snap, dict) and snap != self._worker_metrics.get(
            worker_id
        ):
            # equality check before the rev bump: an idle fleet heartbeats
            # identical snapshots, and bumping on those would defeat the
            # aggregate memo in _aggregate_worker_histograms
            self._worker_metrics[worker_id] = snap
            self._worker_metrics_rev += 1
        # keep worker_map lean: the snapshot lives in _worker_metrics; a
        # second copy per worker entry would bloat get_info and peer gossip
        info.pop("metrics", None)
        prev_wedged = self._worker_wedged.get(worker_id)
        self._worker_wedged[worker_id] = wedged
        if wedged and not prev_wedged:
            # forensic event (never gated): the moment the fleet view
            # learned this worker's accelerator latched
            self.flight.record("worker_wedged", worker=worker_id)
            self.logger.warning(
                "worker %s advertises a wedged accelerator backend",
                worker_id,
            )
        elif prev_wedged and not wedged:
            self.flight.record("worker_unwedged", worker=worker_id)
        # every heartbeat is a health sample, even when the histogram totals
        # did not move — a silent window is itself signal (no throughput)
        self.health.observe(
            worker_id,
            snapshot=self._worker_metrics.get(worker_id),
            wedged=wedged,
            errors=info.get("work_errors"),
            pid=info.get("pid"),
        )
        debug = info.pop("debug", None)
        if isinstance(debug, dict):
            self._worker_debug[worker_id] = {
                "data": debug, "ts": time.time(),
            }
            while len(self._worker_debug) > self._worker_debug_cap:
                # evict dead peers' stale last-words before any live
                # worker's slice: a fleet larger than the cap must never
                # present a reporting worker as "partial" in the bundle
                # (registered entries go only when everything is registered)
                victim = min(
                    self._worker_debug,
                    key=lambda w: (
                        w in self.worker_map,
                        self._worker_debug[w]["ts"],
                    ),
                )
                self._worker_debug.pop(victim, None)

    def _absorb_shard_stats(self, info):
        """Planning stats ride the WRM; keep the freshest copy per shard.
        Entries are shape-checked here: one malformed advertisement (a
        version-skewed or buggy worker) must poison at most its own shard's
        stats, never a query — downstream consumers assume dicts."""
        stats = info.get("shard_stats")
        if isinstance(stats, dict):
            for fname, entry in stats.items():
                if (
                    isinstance(fname, str)
                    and isinstance(entry, dict)
                    and isinstance(entry.get("cols", {}), dict)
                ):
                    self.shard_stats[fname] = entry
        # measured-cost calibration gossip rides the same WRM; absorb is
        # per-cell defensive (plan.calibrate), so a skewed peer degrades to
        # contributing nothing rather than poisoning the model.  source=
        # makes each worker's cumulative summary REPLACE its previous one
        # instead of re-merging every heartbeat (sample double-counting)
        calibration = info.get("calibration")
        if isinstance(calibration, dict):
            try:
                self.calibration.absorb(
                    calibration,
                    source=info.get("worker_id") or "unidentified-worker",
                )
            except Exception:
                self.logger.debug(
                    "calibration gossip absorb failed", exc_info=True
                )

    def _holder_counts(self):
        """Advertised shards bucketed by live holder count ("1"/"2"/"3plus")
        — the replica_holders gauge family and get_info's replication view.
        Briefly memoized: one metrics scrape reads all three buckets (and
        get_info a fourth), which would otherwise walk files_map once per
        bucket."""
        now = time.time()
        cached = self._holder_counts_memo
        if cached is not None and now - cached[0] < 0.25:
            return cached[1]
        counts = {"1": 0, "2": 0, "3plus": 0}
        # list(): gauges render on the metrics HTTP thread while the main
        # loop mutates files_map (WRM registration, worker cull)
        for holders in list(self.files_map.values()):
            n = len(holders)
            if n >= 3:
                counts["3plus"] += 1
            elif n:
                counts[str(n)] += 1
        self._holder_counts_memo = (now, counts)
        return counts

    # -- scheduling --------------------------------------------------------
    def find_free_worker(self, needs_local=False, filename=None, exclude=()):
        """Random choice among free calc workers, constrained to workers
        advertising ``filename`` — a single name or, for a batched shard
        group, a list the worker must advertise in full — and optionally to
        this controller's host (reference bqueryd/controller.py:113-144).

        Health-aware (the observability → scheduling feedback loop): among
        eligible candidates, workers the :class:`obs.HealthScorer` flags
        degraded/wedged are used only when no healthy candidate is free —
        deprioritized, never excluded, so the sole holder of a shard still
        serves it.  ``BQUERYD_TPU_HEALTH_ROUTING=0`` disables the
        preference.

        ``exclude`` is the failover set: holders this shard already failed
        on.  They are avoided while ANY other candidate exists, but — same
        rule as health routing — a shard whose only remaining holder is
        excluded is still served by it (a transient fault may have cleared;
        refusing outright would turn every sole-holder hiccup terminal)."""
        from bqueryd_tpu.obs import health as health_mod

        needed = (
            [filename] if isinstance(filename, str) else list(filename or [])
        )
        candidates = []
        for worker_id, info in self.worker_map.items():
            if info.get("workertype") != "calc" or info.get("busy"):
                continue
            if any(
                worker_id not in self.files_map.get(f, ()) for f in needed
            ):
                continue
            if needs_local and info.get("node") != self.node_name:
                continue
            candidates.append(worker_id)
        if exclude:
            kept = [w for w in candidates if w not in exclude]
            if kept:
                candidates = kept
        if not candidates:
            return None
        if len(candidates) > 1 and health_mod.routing_enabled():
            healthy = self.health.healthy_subset(candidates)
            if healthy and len(healthy) < len(candidates):
                self.counters["health_avoided_dispatches"] += 1
                candidates = healthy
        return random.choice(candidates)

    def dispatch_pending(self):
        """Drain affinity queues round-robin, one message per queue per tick
        (reference bqueryd/controller.py:223-268)."""
        affinities = sorted(self.worker_out_messages, key=lambda a: (a is None, a))
        if not affinities:
            return
        for offset in range(len(affinities)):
            affinity = affinities[
                (self._affinity_rr + offset) % len(affinities)
            ]
            queue = self.worker_out_messages.get(affinity, [])
            if not queue:
                if affinity is not None:
                    self.worker_out_messages.pop(affinity, None)
                continue
            # one action per queue per tick, but a shard inside its failover
            # backoff window must not head-of-line block the messages queued
            # behind it (workers may be free for THEM) — scan for the first
            # actionable message instead of only ever examining the head
            now = time.time()
            idx = None
            for i, msg in enumerate(queue):
                not_before = msg.get("_not_before")
                if not_before is not None and not_before > now:
                    continue  # backing off: skip it, don't block the queue
                idx = i
                break
            if idx is None:
                continue  # whole queue is backing off: retry next tick
            msg = queue[idx]
            if msg.deadline_expired():
                # nobody is waiting anymore: expire instead of dispatching
                queue.pop(idx)
                self.counters["deadline_expired"] += 1
                self._abort_work(
                    msg, "deadline exceeded before dispatch"
                )
                continue
            worker_id = msg.get("worker_id") or self.find_free_worker(
                needs_local=msg.get("needs_local", False),
                filename=msg.get("filename"),
                exclude=frozenset(msg.get("_excluded_workers") or ()),
            )
            if worker_id is None:
                filename = msg.get("filename")
                needed = (
                    [filename]
                    if isinstance(filename, str)
                    else list(filename or [])
                )
                if needed and any(f not in self.files_map for f in needed):
                    # the file vanished from every worker (all holders died):
                    # no future tick can serve this — fail fast instead of
                    # head-of-line-blocking the queue forever
                    queue.pop(idx)
                    self._abort_work(
                        msg,
                        f"file(s) no longer on any worker: "
                        f"{[f for f in needed if f not in self.files_map]}",
                    )
                elif isinstance(filename, list) and not self._servable_by_one(
                    filename
                ):
                    # placement changed since batching (e.g. the co-locating
                    # worker died): re-split the group into per-shard
                    # messages, which the normal scheduler can place
                    queue.pop(idx)
                    children = self._split_batch(msg)
                    self._transfer_work(msg, children)
                    queue.extend(children)
                continue  # retry next tick
            queue.pop(idx)
            self._send_to_worker(worker_id, msg)
        self._affinity_rr += 1

    def _servable_by_one(self, filenames):
        """True if ANY calc worker (busy or not) advertises every file."""
        sets = [self.files_map.get(f, set()) for f in filenames]
        common = set.intersection(*sets) if sets else set()
        return any(
            self.worker_map.get(w, {}).get("workertype") == "calc"
            for w in common
        )

    def _split_batch(self, msg):
        """Explode a batched shard-group CalcMessage back into per-shard
        messages (same parent, fresh tokens, retry count carried over)."""
        args, kwargs = msg.get_args_kwargs()
        children = []
        for filename in msg["filename"]:
            child = CalcMessage(dict(msg))
            child.set_args_kwargs([filename] + list(args[1:]), kwargs)
            child["token"] = os.urandom(8).hex()
            child["filename"] = filename
            # each child is its own dispatch attempt: a fresh trace hop +
            # queue clock (same rule as _requeue), or every child's
            # dispatch/calc spans would share the batch's one span_id
            wire = child.get_trace()
            if wire:
                wire = dict(wire)
                wire["span_id"] = os.urandom(8).hex()
                child.set_trace(wire)
                child["_dispatch_queued_ts"] = time.time()
            children.append(child)
        return children

    # -- shared-dispatch work tracking -------------------------------------
    # Every groupby work unit (one CalcMessage) carries a subscriber list:
    # the parent queries awaiting its payload.  Two concurrent admitted
    # plans that need the same computation over the same shard group fuse
    # into ONE dispatch — one column read, one device transfer, one kernel
    # run — and the result fans out to every subscriber (multi-query
    # batching; observable via counters["plan_shared_dispatches"]).
    def _register_work(self, msg, subscribers, work_key=None):
        token = msg.get("token")
        if not token:
            return
        self._work_subscribers[token] = list(subscribers)
        if work_key is not None:
            self._work_keys[token] = work_key
            self._work_index[work_key] = token

    def _drop_work(self, token):
        self._work_subscribers.pop(token, None)
        self._requeued_tokens.discard(token)
        key = self._work_keys.pop(token, None)
        if key is not None and self._work_index.get(key) == token:
            self._work_index.pop(key, None)

    def _work_parents(self, msg):
        """Every parent awaiting this work unit (shared dispatch aware)."""
        subs = self._work_subscribers.get(msg.get("token"))
        if subs:
            return list(subs)
        parent = msg.get("parent_token")
        return [parent] if parent else []

    def _transfer_work(self, msg, children):
        """Re-home a batch's subscribers onto its re-split children."""
        subs = self._work_subscribers.get(msg.get("token"))
        self._drop_work(msg.get("token"))
        if subs is None:
            return
        for child in children:
            self._register_work(child, subs)

    def _abort_work(self, msg, error_text, error_class=None, attempts=None):
        """Fail every parent subscribed to one work unit."""
        parents = self._work_parents(msg)
        self._drop_work(msg.get("token"))
        for parent in parents:
            self.abort_parent(
                parent, error_text,
                error_class=error_class, attempts=attempts,
            )

    def _dispatch_wire(self, worker_id, msg):
        """The low-level dispatch seam shared by the primary and hedge
        paths: the controller.dispatch chaos site (drop / duplicate /
        delay) plus the raw ROUTER send.  Returns False when the envelope
        was chaos-dropped (recorded here; callers decide whether that
        means 'lost on the wire' or 'never sent'); zmq.ZMQError from a
        gone peer propagates to the caller."""
        fault = chaos.fire(
            "controller.dispatch",
            worker=worker_id,
            verb=msg.get("payload"),
            token=msg.get("token"),
            filename=str(msg.get("filename")),
        ) if chaos.enabled() else None
        if fault is not None and fault.action == "drop":
            self.flight.record(
                "chaos_dispatch_dropped",
                worker=worker_id, token=msg.get("token"),
            )
            return False
        self.socket.send_multipart(
            [worker_id.encode(), msg.to_json().encode()]
        )
        if fault is not None and fault.action == "duplicate":
            self.socket.send_multipart(
                [worker_id.encode(), msg.to_json().encode()]
            )
        return True

    def _send_to_worker(self, worker_id, msg):
        # chaos site controller.dispatch: drop (the envelope "leaves" but
        # never arrives — the dispatch-timeout/failover path must recover),
        # duplicate (the worker sees the work twice — reply dedup must
        # hold), delay (handled inside fire)
        try:
            self._dispatch_wire(worker_id, msg)
        except zmq.ZMQError as exc:
            self.logger.warning("send to worker %s failed: %s", worker_id, exc)
            self.remove_worker(worker_id)
            # a missing route (EHOSTUNREACH) is a controller-side routing
            # fact, not evidence against the shard: requeue without charging
            # the retry budget.  Progress is still guaranteed — the worker
            # was just removed, so the shard either reschedules onto another
            # holder or fails fast via 'no longer on any worker'.  Any OTHER
            # send failure (e.g. EAGAIN on a congested pipe under SNDTIMEO)
            # still charges, or a live-but-wedged worker that keeps
            # re-registering would loop the dispatch forever.
            unroutable = getattr(exc, "errno", None) == zmq.EHOSTUNREACH
            self._requeue(
                {"msg": msg, "retries": msg.get("_retries", 0),
                 "parent": msg.get("parent_token")},
                charge_retry=not unroutable,
                failed_worker=worker_id,
                reason=f"send failed: {exc}",
            )
            return
        if msg.isa("groupby"):
            self.counters["dispatched_shards"] += 1
            # capacity model: per-worker λ window + the per-shard dispatch
            # heat map (skew detection feeding the rebalance advice)
            self.capacity.observe_dispatch(worker_id, msg.get("filename"))
        from bqueryd_tpu import obs

        # flight ring: every work envelope handed to a worker (hot path —
        # kill-switch gated), the forensic counterpart of dispatch_timeout
        if obs.enabled():
            self.flight.record(
                "dispatch",
                worker=worker_id,
                verb=msg.get("payload"),
                token=msg.get("token"),
                filename=str(msg.get("filename"))[:200]
                if msg.get("filename") is not None else None,
                trace_id=(msg.get_trace() or {}).get("trace_id"),
            )
        self._record_dispatch_span(msg, worker_id)
        if worker_id in self.worker_map:
            self.worker_map[worker_id]["busy"] = True
            # a successful dispatch is proof of liveness: the send would have
            # raised on a gone peer (ROUTER_MANDATORY)
            self.worker_map[worker_id]["last_seen"] = time.time()
        token = msg.get("token")
        if token:
            self._requeued_tokens.discard(token)
            self.inflight[token] = {
                "worker": worker_id,
                "sent_at": time.time(),
                "msg": msg,
                "parent": msg.get("parent_token"),
                "retries": msg.get("_retries", 0),
            }

    def _record_dispatch_span(self, msg, worker_id, hedge=False):
        """One "dispatch" span per successful send: queue-entry -> send, its
        span_id the CalcMessage's trace hop (the worker's calc span parents
        to it).  Recorded into EVERY live subscriber segment so shared
        dispatches appear on each joined query's timeline.  Tags carry the
        attempt metadata the attribution layer reads: retry count, the
        charged backoff window (carved out as a retry_backoff segment),
        failover exclusions, and the hedge flag for duplicate dispatches."""
        from bqueryd_tpu import obs

        wire = msg.get_trace()
        queued_ts = msg.get("_dispatch_queued_ts")
        if not wire or queued_ts is None or not obs.enabled():
            return
        if hedge:
            # the hedge dispatched NOW with no backoff of its own: the
            # original attempt's retry/backoff/exclusion tags must not
            # bleed onto its marker (they would read as hedge delay)
            tags = {"worker": worker_id, "hedge": True}
        else:
            tags = {
                "worker": worker_id,
                "filename": str(msg.get("filename")),
                "retries": msg.get("_retries", 0),
            }
            backoff_s = msg.get("_backoff_s")
            if backoff_s:
                tags["backoff_s"] = backoff_s
            excluded = msg.get("_excluded_workers")
            if excluded:
                tags["excluded"] = list(excluded)
        now = time.time()
        span = obs.make_span(
            wire["trace_id"], "dispatch",
            now if hedge else queued_ts,
            0.0 if hedge else max(now - float(queued_ts), 0.0),
            # a hedge duplicates the original attempt's trace hop: its span
            # gets its own id (make_span mints one when None) so both
            # attempts stay distinct on the timeline
            span_id=None if hedge else wire["span_id"],
            parent_span_id=wire.get("parent_span_id"),
            node=self.address,
            tags=tags,
        )
        for parent in self._work_parents(msg):
            segment = self.rpc_segments.get(parent)
            if segment is not None and segment.get("obs"):
                segment["obs"]["spans"].append(span)

    def retry_stale_dispatches(self):
        """Requeue in-flight work whose worker stopped heartbeating (after
        ``dispatch_timeout``) or that exceeded ``dispatch_hard_timeout`` even
        on a live worker.  A live, heartbeating worker inside the hard cap is
        left alone — first-query XLA compilation on a TPU can legitimately
        outlast ``dispatch_timeout``, and requeueing a shard that is still
        being computed would double-execute it and then abort the parent
        after MAX_DISPATCH_RETRIES."""
        now = time.time()
        for token, entry in list(self.inflight.items()):
            if token not in self.inflight:
                continue  # already reclaimed by a remove_worker below
            age = now - entry["sent_at"]
            if age <= self.dispatch_timeout:
                continue
            winfo = self.worker_map.get(entry["worker"])
            worker_alive = (
                winfo is not None
                and now - winfo.get("last_seen", 0.0) <= self.dead_worker_timeout
            )
            if worker_alive and age <= self.dispatch_hard_timeout:
                continue
            self.logger.warning(
                "dispatch %s to %s timed out (age %.0fs, worker %s)",
                token, entry["worker"],
                age, "alive" if worker_alive else "dead",
            )
            # forensic event (never gated): hard timeouts are one of the
            # debug bundle's trigger conditions
            self.flight.record(
                "dispatch_timeout",
                token=token,
                worker=entry["worker"],
                age_s=round(age, 3),
                hard=age > self.dispatch_hard_timeout,
                worker_alive=worker_alive,
                filename=str(entry["msg"].get("filename"))[:200],
                trace_id=(entry["msg"].get_trace() or {}).get("trace_id"),
            )
            self.inflight.pop(token)
            if entry.get("hedged"):
                # the original side timed out while its hedge duplicate is
                # still computing: collapse onto the survivor instead of a
                # redundant third dispatch (the survivor keeps its own
                # freshly-rebased timeout clock)
                self._collapse_hedge(token, entry, entry["worker"])
            else:
                self._requeue(
                    entry,
                    reason=f"dispatch timeout after {age:.0f}s "
                           f"(worker {'alive' if worker_alive else 'dead'})",
                )
            if worker_alive:
                # heartbeating but wedged past the hard cap: reclaim it fully
                # (drop its files_map entries + requeue its other inflight)
                # or it would sit busy-and-advertised forever, head-of-line
                # blocking every query for files only it holds
                self.logger.warning(
                    "worker %s hung past hard timeout, removing", entry["worker"]
                )
                self.remove_worker(entry["worker"])
        # outdistanced workers (hedge losers, stale-attempt holders a late
        # first-worker reply beat) have no inflight entry — the winning
        # reply popped it — but may still be wedged mid-execution: past the
        # hard cap, reclaim each exactly like a hung dispatch.  Their shard
        # is already answered, so there is nothing to requeue for THIS token
        for token, rec in list(self._hedge_losers.items()):
            remaining = []
            for worker in rec["workers"]:
                if worker not in self.worker_map:
                    continue  # culled independently
                age = now - rec["since"]
                if age <= self.dispatch_hard_timeout:
                    remaining.append(worker)
                    continue
                self.logger.warning(
                    "hedge loser %s silent past hard timeout on %s, removing",
                    worker, token,
                )
                self.flight.record(
                    "hedge_loser_timeout",
                    token=token, worker=worker, age_s=round(age, 3),
                )
                self.remove_worker(worker)
            if remaining:
                rec["workers"] = remaining
            else:
                self._hedge_losers.pop(token, None)

    def _mark_hedged(self, token, ts):
        """Record a token in the late-reply dedup ring, bounded: markers
        for workers that die before answering are never popped by a reply,
        so the cap (not the pop) is what keeps a long-lived controller's
        memory flat."""
        self._hedged_tokens[token] = ts
        while len(self._hedged_tokens) > 256:
            self._hedged_tokens.pop(next(iter(self._hedged_tokens)))

    def _withdraw_queued(self, token):
        """Remove a not-yet-dispatched queued work message by token: its
        query was answered by a late reply from a previous attempt, so
        dispatching it would only burn a worker on a finished shard."""
        for affinity, queue in list(self.worker_out_messages.items()):
            kept = [m for m in queue if m.get("token") != token]
            if len(kept) != len(queue):
                self.worker_out_messages[affinity] = kept

    def _collapse_hedge(self, token, entry, failed_worker):
        """One side of a hedged pair is gone (transient fault, timeout,
        cull): re-key the inflight entry onto the surviving side instead of
        requeueing — a third execution would be redundant while the
        duplicate lives, and the survivor needs a hard-timeout reclaim
        handle.  Clears the token's hedge dedup marker: the flight is no
        longer hedged, so the survivor's reply must be processed as THE
        reply, not deduplicated."""
        hedged = entry.get("hedged")
        survivor = hedged if failed_worker == entry.get("worker") \
            else entry["worker"]
        refiled = dict(entry, worker=survivor)
        refiled.pop("hedged", None)
        refiled.pop("hedged_at", None)
        if survivor == hedged:
            # timeout clock restarts at the hedge dispatch, not the
            # original one, or the survivor is reclaimed the moment it
            # inherits the entry
            refiled["sent_at"] = entry.get("hedged_at", entry["sent_at"])
        excluded = list(entry["msg"].get("_excluded_workers") or [])
        if failed_worker and failed_worker not in excluded:
            excluded.append(failed_worker)
        entry["msg"]["_excluded_workers"] = excluded
        self._hedged_tokens.pop(token, None)
        self.inflight[token] = refiled
        self.flight.record(
            "hedge_collapsed",
            token=token, failed=failed_worker, survivor=survivor,
        )
        return refiled

    def _note_losers(self, token, workers):
        """Keep hard-timeout reclaim handles on workers still computing an
        already-answered token (hedge losers, outdistanced stale attempts):
        retry_stale_dispatches reclaims them like any hung dispatch, and a
        loser that answers after all is discarded from tracking."""
        workers = [w for w in workers if w]
        if workers:
            self._hedge_losers[token] = {
                "workers": workers, "since": time.time(),
            }

    def _discard_loser(self, token, worker_id):
        """A tracked loser answered after all — stop holding a reclaim
        handle on it (others computing the same token stay tracked)."""
        rec = self._hedge_losers.get(token)
        if rec is None:
            return
        rec["workers"] = [w for w in rec["workers"] if w != worker_id]
        if not rec["workers"]:
            self._hedge_losers.pop(token, None)

    def maybe_hedge(self):
        """Hedged duplicate dispatch for tail shards (off unless
        ``BQUERYD_TPU_HEDGE_MS`` > 0): a shard still inflight past the
        threshold is duplicated onto a second healthy holder (excluding the
        original and every previously failed one).  First reply wins; the
        loser's reply is deduplicated by query token and **counted**
        (``duplicate_replies``), never double-merged — results are keyed by
        shard filename, so a duplicate could only ever overwrite its own
        identical payload."""
        if self.hedge_ms <= 0 or not self.inflight:
            return
        now = time.time()
        for token, entry in list(self.inflight.items()):
            if token not in self.inflight:
                # remove_worker() below (gone hedge target) requeues that
                # worker's other entries mid-iteration: a snapshot item no
                # longer inflight must not be hedged — its retry is parked,
                # and a ring marker here would discard the retry's valid
                # reply as a duplicate (same guard as
                # retry_stale_dispatches)
                continue
            if entry.get("hedged"):
                continue
            if (now - entry["sent_at"]) * 1000.0 < self.hedge_ms:
                continue
            msg = entry["msg"]
            if not msg.isa("groupby") or msg.get("worker_id"):
                # hedging duplicates EXECUTION: only the idempotent shard
                # verb is safe to run twice (execute_code & co. carry side
                # effects), and a worker-pinned message chose its target
                continue
            exclude = {entry["worker"]} | set(
                msg.get("_excluded_workers") or ()
            )
            target = self.find_free_worker(
                needs_local=msg.get("needs_local", False),
                filename=msg.get("filename"),
                exclude=exclude,
            )
            if target is None or target in exclude:
                continue  # no second healthy holder free right now
            # the hedge rides the same chaos dispatch site as the primary
            # path; a chaos-dropped hedge is simply not sent (no
            # bookkeeping — the next tick may try again, the plan's
            # counters decide)
            try:
                if not self._dispatch_wire(target, msg):
                    continue
            except zmq.ZMQError:
                # gone peer (ROUTER_MANDATORY): cull it like the primary
                # dispatch path does, or this loop re-hedges onto the dead
                # route every tick until the heartbeat cull
                self.remove_worker(target)
                continue
            entry["hedged"] = target
            entry["hedged_at"] = now
            self._mark_hedged(token, now)
            # the duplicate attempt lands on the timeline too (tagged
            # hedge=True so attribution lists it beside the original)
            self._record_dispatch_span(msg, target, hedge=True)
            if target in self.worker_map:
                self.worker_map[target]["busy"] = True
                self.worker_map[target]["last_seen"] = now
            self.counters["hedged_dispatches"] += 1
            self.flight.record(
                "hedged_dispatch",
                token=token, worker=target, original=entry["worker"],
                age_ms=round((now - entry["sent_at"]) * 1000.0, 1),
            )

    def _retry_backoff(self, msg, retries):
        """Exponential backoff + deterministic jitter between dispatch
        attempts of one shard: base * 2^retries capped, stretched by up to
        25% keyed on the work token (stable across re-runs, different
        across shards — simultaneous failovers de-stampede)."""
        return backoff.backoff_delay(
            retries,
            str(msg.get("token") or ""),
            base=RETRY_BACKOFF_BASE_S,
            cap=RETRY_BACKOFF_CAP_S,
        )

    def _requeue(self, entry, charge_retry=True, failed_worker=None,
                 reason=None):
        from bqueryd_tpu import obs

        msg = entry["msg"]
        retries = entry.get("retries", 0)
        if failed_worker is None:
            failed_worker = entry.get("worker")
        # the failed attempt's in-flight window becomes its own dispatch
        # span (tagged with the failure): a shard that sat 1.5 s on a dead
        # worker must autopsy as dispatch wait on THAT worker, not as
        # unattributed wall
        sent_at = entry.get("sent_at")
        wire = msg.get_trace()
        if sent_at is not None and wire and obs.enabled():
            span = obs.make_span(
                wire["trace_id"], "dispatch", sent_at,
                max(time.time() - float(sent_at), 0.0),
                parent_span_id=wire.get("parent_span_id"),
                node=self.address,
                tags={
                    "worker": failed_worker,
                    "retries": retries,
                    "failed": str(
                        reason or "worker lost or dispatch timed out"
                    )[:120],
                },
            )
            for parent in self._work_parents(msg):
                segment = self.rpc_segments.get(parent)
                if segment is not None and segment.get("obs"):
                    segment["obs"]["spans"].append(span)
        # per-attempt forensic history rides the message (bounded by the
        # retry budget); the structured exhaustion envelope surfaces it so
        # a client sees WHERE its query died instead of timing out blind
        history = list(msg.get("_attempt_history") or [])
        history.append(
            {
                "worker": failed_worker,
                "reason": str(
                    reason or "worker lost or dispatch timed out"
                )[:200],
                "retries": retries,
                "ts": round(time.time(), 3),
            }
        )
        msg["_attempt_history"] = history
        if failed_worker:
            # replica failover: the retry must land on a DIFFERENT holder
            # while one exists (find_free_worker's exclude contract)
            excluded = list(msg.get("_excluded_workers") or [])
            if failed_worker not in excluded:
                excluded.append(failed_worker)
            msg["_excluded_workers"] = excluded
        if charge_retry and retries >= self.max_dispatch_retries:
            self._abort_work(
                msg,
                f"shard {msg.get('filename')} failed after "
                f"{retries} retries (worker lost, timed out, or faulted)",
                error_class="DispatchExhausted",
                attempts=history,
            )
            return
        if charge_retry and failed_worker:
            self.counters["failover_dispatches"] += 1
        msg["_retries"] = retries + 1 if charge_retry else retries
        backoff_s = self._retry_backoff(msg, retries)
        msg["_not_before"] = time.time() + backoff_s
        # the charged backoff rides the message so the attempt's dispatch
        # span can tag it — attribution carves it out as retry_backoff
        msg["_backoff_s"] = round(backoff_s, 6)
        # each dispatch ATTEMPT is its own trace hop: a fresh span_id (a
        # slow-but-alive first worker's calc span keeps parenting to the
        # original attempt's recorded span) and a fresh queue-entry clock
        wire = msg.get_trace()
        if wire:
            wire = dict(wire)
            wire["span_id"] = os.urandom(8).hex()
            msg.set_trace(wire)
            msg["_dispatch_queued_ts"] = time.time()
        affinity = msg.get("affinity")
        if msg.get("token"):
            self._requeued_tokens.add(msg.get("token"))
        self.worker_out_messages.setdefault(affinity, []).append(msg)

    # -- inbound demux -----------------------------------------------------
    def handle_in(self, frames):
        self.msg_count_in += 1
        if len(frames) == 3 and frames[1] == b"":
            self.handle_rpc(frames[0], frames[2])
            return
        if len(frames) == 3:
            try:
                msg = msg_factory(frames[1])
            except messages.MalformedMessage:
                self.logger.warning("malformed worker reply dropped")
                return
            msg["data"] = frames[2]
            self.handle_worker(frames[0], msg)
            return
        if len(frames) == 2:
            try:
                msg = msg_factory(frames[1])
            except messages.MalformedMessage:
                self.logger.warning("malformed message dropped")
                return
            if msg.get("payload") == "peer_info":
                self.handle_peer(msg)
            elif msg.get("_relayed") and msg.get("payload") in (
                "killall", "kill", "loglevel",
            ):
                # control verb fanned out by a peer controller (reference
                # bqueryd/controller.py:291-295): dispatch like an RPC, but
                # there is no client to answer (no token)
                getattr(self, f"rpc_{msg['payload']}")(msg)
            else:
                self.handle_worker(frames[0], msg)
            return
        self.logger.warning("dropping %d-frame message", len(frames))

    # -- worker messages ---------------------------------------------------
    def handle_worker(self, sender, msg):
        worker_id = (
            msg.get("worker_id")
            or (sender.decode() if isinstance(sender, bytes) else sender)
        )
        now = time.time()
        if msg.isa(WorkerRegisterMessage):
            if msg.get("liveness_only"):
                # side-channel heartbeat from the worker's liveness thread:
                # for a KNOWN worker refresh last_seen only — its data_files
                # snapshot may lag the main loop's rescan, and dropping
                # advertisements for a busy worker aborts its query.  For an
                # UNKNOWN worker (controller restart while the worker's event
                # loop is deep in a long handle_work) adopt the snapshot
                # additively: without it the sole holder of a shard would look
                # file-less until its main loop resumes, failing every query
                # for that shard with 'no longer on any worker'.
                known = self.worker_map.get(worker_id)
                if known is not None:
                    known["last_seen"] = now
                    # the worker's one-shot stats advertisement may ride
                    # EITHER socket (the liveness thread races the main
                    # loop for it); dropping it here would suppress fresh
                    # stats for a whole re-advertise window
                    self._absorb_shard_stats(msg)
                    self._absorb_worker_metrics(worker_id, msg)
                elif self._adoption_blocked.get(worker_id, 0) > now:
                    # quarantined: this worker was hard-culled as an hb_only
                    # adoptee whose main loop never spoke — its heartbeat
                    # thread is still ticking, and re-adopting it would
                    # repopulate files_map and make every new query wait out
                    # another full hard-timeout window
                    return
                else:
                    # adopt as BUSY + hb_only: the worker's main loop is deep
                    # in a long handle_work and the ROUTER may only hold a
                    # route for the '.hb' identity — dispatching now would
                    # EHOSTUNREACH, remove the worker, and burn the shard's
                    # retry budget in a re-adopt loop.  The first message on
                    # the main socket (WRM/Done/result) proves the real route
                    # and clears both flags.
                    info = dict(msg)
                    info["last_seen"] = now
                    info["busy"] = True
                    info["hb_only"] = now  # adoption time: expiry-checked in cull
                    self.worker_map[worker_id] = info
                    for filename in info.get("data_files") or []:
                        self.files_map.setdefault(filename, set()).add(worker_id)
                    self._absorb_shard_stats(info)
                    self._absorb_worker_metrics(worker_id, info)
                return
            prev = self.worker_map.get(worker_id, {})
            if not prev:
                self.flight.record(
                    "worker_registered",
                    worker=worker_id,
                    workertype=msg.get("workertype"),
                    node=msg.get("node"),
                )
            self._adoption_blocked.pop(worker_id, None)  # main loop is back
            info = dict(msg)
            info["last_seen"] = now
            # an hb_only adoption's busy=True was a placeholder, not observed
            # state — a main-socket WRM proves the route and resets it
            info["busy"] = False if prev.get("hb_only") else prev.get("busy", False)
            self.worker_map[worker_id] = info
            current_files = set(info.get("data_files", []))
            for filename in current_files:
                self.files_map.setdefault(filename, set()).add(worker_id)
            for filename in list(self.files_map):
                if filename not in current_files:
                    self.files_map[filename].discard(worker_id)
                    if not self.files_map[filename]:
                        del self.files_map[filename]
                        self.shard_stats.pop(filename, None)
            self._absorb_shard_stats(info)
            self._absorb_worker_metrics(worker_id, info)
            return
        if worker_id not in self.worker_map:
            # a message from a culled worker: ask it to re-register by just
            # recording minimal liveness (reference bqueryd/controller.py:315-318)
            self.worker_map[worker_id] = {
                "worker_id": worker_id, "last_seen": now, "busy": False,
                "workertype": "unknown",
            }
        else:
            self.worker_map[worker_id]["last_seen"] = now
            # any main-socket message proves the real route exists
            self.worker_map[worker_id].pop("hb_only", None)
            self._adoption_blocked.pop(worker_id, None)

        if msg.isa(BusyMessage):
            self.worker_map[worker_id]["busy"] = True
            return
        if msg.isa(DoneMessage):
            self.worker_map[worker_id]["busy"] = False
            return
        if msg.isa(StopMessage):
            self.remove_worker(worker_id)
            return
        if msg.isa(TicketDoneMessage):
            self.release_ticket_waiters(msg.get("ticket"), msg.get("error"))
            return
        token = msg.get("token")
        if token:
            self.worker_map[worker_id]["busy"] = False
            # chaos site controller.reply (shard results only — faulting a
            # lockstep REQ verb's reply would mis-pair the client socket):
            # drop simulates a reply lost on the wire, duplicate replays it
            fault = (
                chaos.fire(
                    "controller.reply",
                    worker=worker_id,
                    token=token,
                    verb=msg.get("payload"),
                    parent=msg.get("parent_token"),
                )
                if chaos.enabled() and msg.get("parent_token") else None
            )
            if fault is not None and fault.action == "drop":
                self.flight.record(
                    "chaos_reply_dropped", token=token, worker=worker_id
                )
                return
            entry = self.inflight.pop(token, None)
            if entry is None and token in self._hedged_tokens:
                # hedge loser / outdistanced stale attempt (or a chaos
                # duplicate of the winner): the token already completed —
                # first reply won, this one is counted and dropped, never
                # merged a second time
                self._hedged_tokens.pop(token, None)
                self._discard_loser(token, worker_id)  # answered after all
                self.counters["duplicate_replies"] += 1
                return
            if entry is None and token in self._requeued_tokens:
                # the shard's retry is still parked in the dispatch queue
                # (backoff window / waiting for a free holder) and a late
                # reply from the FAILED attempt landed first
                if msg.isa(ErrorMessage):
                    # a stale fault is not news — the queued retry stands;
                    # aborting here would fail the query with a healthy
                    # replica attempt still pending
                    self.counters["duplicate_replies"] += 1
                    self.flight.record(
                        "stale_reply_dropped",
                        token=token, worker=worker_id,
                        error=str(msg.get("payload"))[:200],
                    )
                    return
                # a late VALID result wins: withdraw the queued retry (a
                # fresh execution would be redundant) and deliver.  Mark
                # the token in the dedup ring — another superseded attempt
                # may still be computing it, and its later reply (valid OR
                # a non-transient error) must be counted and dropped, not
                # abort the parent the orphan fall-through would reach
                self._requeued_tokens.discard(token)
                self._withdraw_queued(token)
                self._mark_hedged(token, time.time())
            if entry is not None:
                assigned = entry.get("worker")
                hedged = entry.get("hedged")
                outstanding = [
                    w for w in (assigned, hedged)
                    if w is not None and w != worker_id
                ]
                if worker_id not in (assigned, hedged):
                    # late reply from a PREVIOUS attempt's worker: the shard
                    # was requeued (timeout/fault) and the CURRENT attempt
                    # is still computing on `outstanding`
                    if msg.isa(ErrorMessage):
                        # a stale fault is not news — the live attempt
                        # stands; reinstate its reclaim handle untouched
                        self.inflight[token] = entry
                        self.counters["duplicate_replies"] += 1
                        self.flight.record(
                            "stale_reply_dropped",
                            token=token, worker=worker_id,
                            error=str(msg.get("payload"))[:200],
                        )
                        return
                    # a late VALID result: first reply wins (replica holders
                    # compute the identical payload).  Dedup the live
                    # attempt's eventual reply, and keep reclaim handles on
                    # every worker still computing it — the popped entry was
                    # their hard-timeout handle, and without one a wedged
                    # holder sits busy-and-advertised forever
                    self._mark_hedged(token, time.time())
                    self._note_losers(token, outstanding)
                elif hedged and msg.isa(ErrorMessage):
                    # one side of a hedged pair failed — transiently or not
                    # — while the other is still computing and may well
                    # answer: fail over THIS side only — re-key the inflight
                    # entry to the survivor.  No requeue (a third execution
                    # would be redundant while the duplicate lives), no
                    # retry charge (the attempt continues), and no abort
                    # even for a permanent error or at the budget's edge —
                    # the outstanding answer decides; if the survivor also
                    # errors, its un-hedged entry takes the normal
                    # requeue/abort path
                    refiled = self._collapse_hedge(token, entry, worker_id)
                    transient = bool(msg.get("transient"))
                    if transient:
                        self.counters["transient_faults"] += 1
                    self.flight.record(
                        "transient_fault" if transient
                        else "hedge_side_error",
                        token=token, worker=worker_id,
                        survivor=refiled["worker"],
                        error=str(msg.get("payload"))[:200],
                    )
                    return
                elif hedged:
                    self._mark_hedged(token, time.time())  # loser still due
                    # forensic outcome events (rare, never gated): the
                    # debug-bundle timeline must explain every hedge's
                    # win/loss, not just that one was issued
                    if worker_id == hedged:
                        self.counters["hedge_wins"] += 1
                        self.flight.record(
                            "hedge_win",
                            token=token, winner=worker_id, loser=assigned,
                        )
                    else:
                        self.flight.record(
                            "hedge_loss",
                            token=token, winner=worker_id, loser=hedged,
                        )
                    # the pop above destroyed the token's inflight entry,
                    # which was also the hard-timeout reclaim handle on the
                    # side that has NOT replied yet — keep one, or a wedged
                    # loser sits busy-and-advertised forever
                    # (retry_stale_dispatches reclaims it like any other
                    # hung dispatch)
                    self._note_losers(token, outstanding)
            else:
                # orphaned late reply (its dedup-ring marker may have been
                # evicted on a busy cluster): still drain any reclaim
                # handle held on this worker — a loser that answered must
                # not be hard-timeout removed as 'silent' later
                if token in self._hedge_losers:
                    # loser tracking outlives the 256-entry ring and proves
                    # this token was already answered: count-and-drop like
                    # the ring branch — a late non-transient ErrorMessage
                    # here must not abort a parent whose shard is merged
                    self._discard_loser(token, worker_id)
                    self.counters["duplicate_replies"] += 1
                    self.flight.record(
                        "stale_reply_dropped",
                        token=token, worker=worker_id,
                        error=(
                            str(msg.get("payload"))[:200]
                            if msg.isa(ErrorMessage) else None
                        ),
                    )
                    return
                self._discard_loser(token, worker_id)
            self.process_worker_result(msg, entry)
            if fault is not None and fault.action == "duplicate":
                # replay the envelope through the sink: definitionally a
                # duplicate.  A still-open segment counts it at the
                # in-segment key dedup; only a COMPLETED segment orphans
                # the replay before that site, so count it here exactly
                # when no open segment will (one injected duplicate = one
                # increment, never two)
                parent = msg.get("parent_token")
                subs = self._work_subscribers.get(token) or (
                    (parent,) if parent is not None else ()
                )
                if not any(p in self.rpc_segments for p in subs):
                    self.counters["duplicate_replies"] += 1
                self.process_worker_result(msg, None)

    def _record_inflight_span(self, msg, entry):
        """The send→reply window as a dispatch span (tag ``wait``): worker
        spans carve the actual execution out of it at higher sweep
        priority, so what this span surfaces in an autopsy is the wire /
        poll-loop transit the controller cannot otherwise see — without
        it, a fast query's coverage is eaten by gaps no node owns."""
        from bqueryd_tpu import obs

        wire = msg.get_trace()
        sent_at = (entry or {}).get("sent_at")
        if not wire or sent_at is None or not obs.enabled():
            return
        now = time.time()
        new_spans = [
            obs.make_span(
                wire["trace_id"], "dispatch", sent_at,
                max(now - float(sent_at), 0.0),
                parent_span_id=wire.get("parent_span_id"),
                node=self.address,
                tags={
                    "worker": entry.get("worker"),
                    "retries": entry.get("retries", 0),
                    # attribution charges the uncovered remainder to the
                    # dispatch segment but keeps it out of the attempts
                    # list (the queue-entry span already represents the
                    # attempt)
                    "wait": True,
                },
            )
        ]
        hedged_at = entry.get("hedged_at")
        if entry.get("hedged") and hedged_at is not None:
            # the hedge duplicate's racing window (hedge dispatch → this
            # reply): surfaces as the hedge_dispatch segment — how long
            # the query's tail was spent racing two holders.  wait=True
            # keeps it out of the attempts list (maybe_hedge's marker
            # span already lists the hedge attempt)
            new_spans.append(
                obs.make_span(
                    wire["trace_id"], "dispatch", hedged_at,
                    max(now - float(hedged_at), 0.0),
                    parent_span_id=wire.get("parent_span_id"),
                    node=self.address,
                    tags={
                        "worker": entry.get("hedged"),
                        "hedge": True,
                        "wait": True,
                    },
                )
            )
        for parent in self._work_parents(msg):
            segment = self.rpc_segments.get(parent)
            if segment is not None and segment.get("obs"):
                segment["obs"]["spans"].extend(new_spans)

    # -- results sink ------------------------------------------------------
    def process_worker_result(self, msg, entry=None):
        parent = msg.get("parent_token")
        token = msg.get("token")
        if token is not None and token in self._append_waiters:
            # streaming-append fan-out reply: collected per holder, the
            # client answered once every replica confirmed
            self._absorb_append_reply(token, msg)
            return
        if isinstance(token, str) and token.startswith("append_"):
            # orphaned append reply: its waiter already failed fast
            # (holder removal) or timed out — the client was answered.
            # Matched by the synthetic token prefix, NOT the verb: an
            # ErrorMessage reply's payload is the traceback, so
            # isa("append") would miss it and the fall-through would hand
            # the non-hex dispatch token to reply_rpc_message
            self.flight.record(
                "append_reply_orphaned", token=token,
            )
            return
        if token is not None and token in self._rollup_waiters:
            # controller-originated rollup build/refresh reply: absorbed
            # into the serving layer, never forwarded to any client
            self._absorb_rollup_reply(token, msg)
            return
        if isinstance(token, str) and token.startswith("rollup_"):
            # orphaned rollup reply: the entry was evicted/abandoned while
            # the build was in flight (same prefix-match rationale as the
            # append orphan above — ErrorMessage payloads aren't the verb)
            self.flight.record("rollup_reply_orphaned", token=token)
            return
        subscribers = self._work_subscribers.get(token)
        if entry is not None and not (
            msg.isa(ErrorMessage) and msg.get("transient")
        ):
            # the transient-fault path records its own (failed-tagged)
            # in-flight span inside _requeue
            self._record_inflight_span(msg, entry)
        if parent is None and not subscribers:
            # single-segment RPC (execute_code, sleep, readfile): a binary
            # data frame is folded into the JSON reply as base64
            data = msg.pop("data", None)
            if data is not None:
                msg.add_as_binary("result", data)
            self.reply_rpc_message(msg.get("token"), msg)
            return
        if msg.isa(ErrorMessage) and msg.get("transient"):
            # transient (retryable) worker fault — DeviceBusyError class:
            # fail the shard over to a different holder instead of killing
            # the query; _requeue excludes the faulted worker and aborts
            # with the structured envelope only once the budget is spent
            reason = str(msg.get("payload") or "transient fault")
            reason = (reason.strip().splitlines() or ["transient fault"])[-1]
            if entry is not None:
                self.counters["transient_faults"] += 1
                self.flight.record(
                    "transient_fault",
                    token=token,
                    worker=entry.get("worker"),
                    error=reason[:200],
                )
                self._requeue(
                    entry, failed_worker=entry.get("worker"), reason=reason
                )
            # entry is None: the shard was already requeued (timeout) or
            # completed (hedge), or this is a chaos replay — a duplicate of
            # a fault, not a new one (one real fault = one count)
            else:
                self.counters["duplicate_replies"] += 1
            return
        self._drop_work(token)
        parents = list(subscribers) if subscribers else [parent]
        if msg.isa(ErrorMessage):
            error_class = None
            error_text = msg.get("payload")
            if msg.get("dag") and "unknown aggregation op" in str(error_text):
                # a DAG dispatch answered by a pre-DAG worker, which
                # executed the positional params and rejected the extended
                # op string: reply the STRUCTURED mixed-version error
                # MIGRATION "PR 13" promises instead of relaying the
                # worker traceback
                error_class = "UnsupportedOp"
                error_text = (
                    "query dispatched to a worker that does not understand "
                    "operator DAGs (pre-PR-13 build); upgrade every calc "
                    "worker before using rpc.query (see MIGRATION.md PR 13)"
                )
            for p in parents:
                self.abort_parent(p, error_text, error_class=error_class)
            return
        if msg.get("_bundle_parents"):
            if msg.get("bundle_members") is not None:
                # shared-scan bundle reply: one envelope, one payload PER
                # member — demultiplexed into each member's own segment
                self._demux_bundle(msg)
            else:
                # a bundle dispatch answered WITHOUT the bundle_members key:
                # a pre-PR-9 worker executed only the positional params
                # (member 0's query).  Falling through to the shared-
                # dispatch sink would hand that one payload to EVERY
                # member — silent wrong results.  Abort all members with
                # the mixed-version error MIGRATION.md promises instead.
                self.logger.warning(
                    "bundle %s answered without bundle_members "
                    "(pre-PR-9 worker?); aborting its members",
                    token,
                )
                for p in dict.fromkeys(msg["_bundle_parents"].values()):
                    self.abort_parent(
                        p,
                        "bundle dispatched to a worker that does not "
                        "understand shared-scan bundles; keep "
                        "BQUERYD_TPU_BATCH_WINDOW_MS=0 until every calc "
                        "worker is upgraded (see MIGRATION.md PR 9)",
                    )
            return
        filename = msg.get("filename")
        # a batched shard-group reply covers several filenames with ONE
        # already-merged payload (the worker's on-device psum merge);
        # completion is counted in covered filenames, not replies
        key = tuple(filename) if isinstance(filename, list) else (filename,)
        data = msg.get("data") or b""
        # payload bytes over the wire, counted once per reply (not per
        # subscriber): the metric the bench's merge section reads as the
        # host-gather baseline the device-resident merge is judged against
        self.counters["reply_payload_bytes"] += len(data)
        delivered = False
        counted_duplicate = False
        for p in parents:
            segment = self.rpc_segments.get(p)
            if segment is None:
                continue  # that subscriber aborted earlier
            delivered = True
            if key in segment["results"] and not counted_duplicate:
                # token/key dedup backstop (late retry, hedge loser, chaos
                # duplicate): the payload slot is keyed by shard filename,
                # so a duplicate overwrites its own identical payload —
                # counted for visibility (once per ENVELOPE, not per
                # subscriber of shared work), structurally never
                # double-merged
                self.counters["duplicate_replies"] += 1
                counted_duplicate = True
            segment["results"][key] = data
            segment["timings"][key] = msg.get("phase_timings")
            effective = msg.get("effective_strategy")
            if isinstance(effective, str):
                segment.setdefault("effective", {})[key] = effective
            merge_mode = msg.get("merge_mode")
            if isinstance(merge_mode, str):
                segment.setdefault("merge", {})[key] = merge_mode
            # worker-side spans (calc root + phases) fold into the timeline;
            # shared dispatches land on every subscriber's segment
            spans = msg.get("spans")
            if isinstance(spans, list) and segment.get("obs"):
                segment["obs"]["spans"].extend(
                    s for s in spans if isinstance(s, dict)
                )
            self._maybe_complete_segment(p)
        if not delivered:
            self.logger.warning("orphaned result for parent %s dropped", parent)

    def _demux_bundle(self, msg):
        """Per-member demultiplex of a shared-scan bundle reply: the data
        frame is one pickled ``{"payloads": {member_id: bytes}, "errors":
        {member_id: text}}`` envelope.  Fault isolation is per member: an
        errored/expired member aborts ITS parent only; members whose
        parents aborted earlier (supersede, deadline) are skipped; the
        others complete normally."""
        from bqueryd_tpu import obs

        token = msg.get("token")
        data = msg.get("data") or b""
        # payload bytes over the wire, once per reply (the controller-side
        # twin of the worker's reply_bytes histogram)
        self.counters["reply_payload_bytes"] += len(data)
        bundle_parents = msg.get("_bundle_parents") or {}
        try:
            envelope = pickle.loads(data) if data else {}
        except Exception:
            for parent in set(bundle_parents.values()):
                self.abort_parent(parent, "undecodable bundle reply")
            return
        member_payloads = envelope.get("payloads") or {}
        member_errors = envelope.get("errors") or {}
        # per-member segment shares (messages.py `member_shares`): the
        # fraction of the bundle's shared scan each member is accountable
        # for — shared phase timings are scaled by it so a slow BUNDLE
        # lands each member in the slow-query ring (and its autopsy) with
        # ITS share of the wall, not the whole bundle's; pre-PR-10 workers
        # ship no shares and the timings pass through unscaled
        member_shares = msg.get("member_shares")
        if not isinstance(member_shares, dict):
            member_shares = {}
        filename = msg.get("filename")
        key = tuple(filename) if isinstance(filename, list) else (filename,)
        delivered = False
        counted_duplicate = False
        for member_id, parent in bundle_parents.items():
            # per-member demux clock: a member's span must cover ITS slice
            # of the demultiplex only — measured from iteration start to
            # span append, so an earlier member's completion work (merge,
            # attribution, reply — it runs inside _maybe_complete_segment)
            # can never inflate a later member's bundle_demux segment
            member_start_ts = time.time()
            member_clock = time.perf_counter()
            segment = self.rpc_segments.get(parent)
            if segment is None:
                continue  # that member aborted earlier
            delivered = True
            error = member_errors.get(member_id)
            if error is not None:
                # member-only failure (deadline expiry, a member-shape
                # rejection): abort THIS member; bundle-mates complete
                self.abort_parent(parent, error)
                continue
            buf = member_payloads.get(member_id)
            if buf is None:
                self.abort_parent(
                    parent, "bundle reply missing this member's payload"
                )
                continue
            if key in segment["results"] and not counted_duplicate:
                # same dedup backstop as the shared-dispatch sink: a
                # duplicate envelope overwrites its own identical payloads
                self.counters["duplicate_replies"] += 1
                counted_duplicate = True
            segment["results"][key] = buf
            share = member_shares.get(member_id)
            try:
                share = float(share) if share is not None else None
            except (TypeError, ValueError):
                share = None
            timings = msg.get("phase_timings")
            if share is not None and isinstance(timings, dict):
                scaled = {
                    k: round(v * share, 6)
                    for k, v in timings.items()
                    if isinstance(v, (int, float))
                }
                # underscore-namespaced like _total, so it can never
                # collide with a real phase name
                scaled["_member_share"] = round(share, 6)
                timings = scaled
            segment["timings"][key] = timings
            effective = msg.get("effective_strategy")
            if isinstance(effective, str):
                segment.setdefault("effective", {})[key] = effective
            merge_mode = msg.get("merge_mode")
            if isinstance(merge_mode, str):
                segment.setdefault("merge", {})[key] = merge_mode
            spans = msg.get("spans")
            if isinstance(spans, list) and segment.get("obs"):
                obs_state = segment["obs"]
                if share is not None:
                    # per-member span copies tagged with the share: the
                    # autopsy keeps true-wall segments and reports this
                    # member's accountable slice beside them
                    obs_state["spans"].extend(
                        dict(
                            s,
                            tags={
                                **(s.get("tags") or {}),
                                "bundle_share": round(share, 6),
                            },
                        )
                        for s in spans if isinstance(s, dict)
                    )
                else:
                    obs_state["spans"].extend(
                        s for s in spans if isinstance(s, dict)
                    )
                obs_state["spans"].append(
                    obs.make_span(
                        obs_state["trace_id"], "demux", member_start_ts,
                        time.perf_counter() - member_clock,
                        parent_span_id=obs_state["qspan_id"],
                        node=self.address,
                    )
                )
            self._maybe_complete_segment(parent)
        if not delivered:
            self.logger.warning(
                "orphaned bundle result %s dropped", token
            )

    def _maybe_complete_segment(self, parent):
        """Reply to the client once every requested shard is covered (by a
        worker payload, a batched group payload, or a plan-time prune)."""
        segment = self.rpc_segments.get(parent)
        if segment is None:
            return
        # greedy DISJOINT cover, largest keys first: a re-split batch can
        # leave both the late batch payload and its per-shard children in
        # results (keys are laminar — a group and/or singletons from its
        # re-split), and overlapping keys must neither complete the
        # segment early nor merge a shard's payload twice
        chosen, covered = [], set()
        for k in sorted(segment["results"], key=len, reverse=True):
            files = set(k)
            if files & covered:
                continue
            chosen.append(k)
            covered |= files
        if not covered.issuperset(segment["filenames"]):
            return
        self.rpc_segments.pop(parent)
        # payloads in requested-filename order (not reply-arrival order):
        # the aggregate=False rows path concatenates payloads client-side,
        # and the reference's row order is deterministic by filename
        covering = {f: k for k in chosen for f in k}
        payloads, seen = [], set()
        for f in segment["filenames"]:
            k = covering[f]
            if k not in seen:
                seen.add(k)
                payloads.append(segment["results"][k])
        # compact key: a batched shard-group is labelled by its first
        # file + count, not the joined list (a 10-shard join produced a
        # 130+ char key that bloated the bench's one-line JSON past what
        # log tails keep intact); same labelling as the slow-query log
        timings = self._compact_timings(segment["timings"])
        # answer provenance for the dispatched path: every shard served
        # from a worker result cache -> "cached"; any delta-maintained
        # shard -> "delta"; else a real recompute.  The serving layer's
        # direct replies (_reply_served) stamp "rollup"/"subsume"
        effective_routes = set((segment.get("effective") or {}).values())
        if effective_routes and effective_routes <= {"cached"}:
            answer_source = "cached"
        elif "delta" in effective_routes:
            answer_source = "delta"
        else:
            answer_source = "recompute"
        self._count_answer(answer_source)
        reply = pickle.dumps(
            {
                "ok": True,
                "payloads": payloads,
                "timings": timings,
                # PR-16 provenance: how this answer was produced, and (for
                # subsumption serves) which materialized view proved it
                "answer_source": answer_source,
                "subsumed_from": None,
                # planner visibility end to end: the hints issued and the
                # routes the workers actually compiled post-guards (bench's
                # chosen_strategy / regret accounting read these)
                "strategies": {
                    "hints": dict(segment.get("strategies", {})),
                    "effective": self._compact_timings(
                        segment.get("effective")
                    ),
                },
                # per shard-group: how the worker merged the payload
                # (device = ICI-mesh collective, host = hostmerge fallback,
                # none = single payload)
                "merge_modes": self._compact_timings(segment.get("merge")),
            },
            protocol=4,
        )
        self._finish_segment(parent, segment, reply)

    def _finish_segment(self, parent, segment, reply_bytes=None, error=None):
        """Final reply for a groupby parent + admission slot release.
        ``reply_bytes=None`` finishes silently (a cancelled query whose
        client is no longer waiting — replying would mis-pair with the
        identity's next request)."""
        if reply_bytes is not None:
            self.reply_rpc_raw(segment["client_token"], reply_bytes)
        self._finalize_query_obs(parent, segment, error=error)
        ticket = segment.get("admission_ticket")
        if ticket is not None:
            self.admission.release(ticket)
            self._ticket_sigs.pop(ticket, None)
            self._admit_ready()

    @staticmethod
    def _new_obs_state(ctx):
        """Per-query trace state: the client's context, the controller
        "groupby" span id every query span parents to, the span list the
        timeline is assembled from, and the submit clock the admission
        span measures against."""
        from bqueryd_tpu import obs

        return {
            "trace_id": ctx.trace_id,
            "root_span_id": ctx.span_id,
            "qspan_id": obs.new_id(),
            "spans": [],
            "submitted_ts": time.time(),
        }

    def _observe_admission_wait(self, wait_s):
        """Admission's wait hook: queued time before launch."""
        from bqueryd_tpu import obs

        if obs.enabled():
            self.admission_wait_seconds.observe(wait_s)
        # the capacity model's measured-wait cross-check has its own kill
        # switch (BQUERYD_TPU_CAPACITY) — a queue-wait sample is capacity
        # evidence whether or not the span hot path is on
        self.capacity.observe_queue_wait(wait_s, source="admission")

    def _observe_arrival(self, decision, payload):
        """Admission's arrival tap: every offered groupby (ADMIT, QUEUED
        and BUSY alike) lands in the capacity model's per-class arrival
        window — λ is offered load, and shed load is what saturation looks
        like."""
        del decision  # offered load counts every outcome alike
        msg = payload[0] if payload else None
        slo_class = (
            self.slo.resolve(msg.get("slo_class"))
            if msg is not None else "default"
        )
        self.capacity.observe_arrival(slo_class)

    def _record_capacity_advice(self, rec):
        """Shadow-advisor sink: every NEW recommendation is a flight event
        (ungated — advice changes are rare by construction) and a counter
        bump.  Nothing acts on it; a later enforcement PR consumes these."""
        action = rec.get("action")
        counter_key = f"capacity_{action}_advised"
        if counter_key in self.counters:
            self.counters[counter_key] += 1
        self.flight.record(
            "capacity_advice",
            action=action,
            n=rec.get("n"),
            shard=rec.get("shard"),
            to_worker=rec.get("to_worker"),
            reason=str(rec.get("reason"))[:200],
        )

    def _timeline_snapshot(self):
        """One ``rpc.timeline()`` ring entry: the compact controller state
        a regression diff needs — counters, queue/inflight depths, fleet
        size, groupby latency quantiles, SLO burn rates."""
        from bqueryd_tpu.obs import metrics as obs_metrics

        snap = self.query_seconds.snapshot()
        admission = self.admission.stats()
        return {
            "counters": dict(self.counters),
            "inflight": len(self.inflight),
            "workers": len(self.worker_map),
            "admission_active": admission["active"],
            "admission_queued": admission["queued"],
            "groupby_count": sum(snap.get("counts", ())),
            "groupby_p50_s": obs_metrics.quantile_from_snapshot(snap, 0.5),
            "groupby_p99_s": obs_metrics.quantile_from_snapshot(snap, 0.99),
            "slo": self.slo.snapshot(),
            # fleet utilization/saturation per tick: the existing ring
            # doubles as capacity history (was this cluster saturated an
            # hour ago is one rpc.timeline() away)
            "capacity": self._capacity_timeline_fields(),
        }

    def _capacity_timeline_fields(self):
        """The compact capacity slice each timeline-ring entry carries."""
        fleet = self.capacity.snapshot().get("fleet") or {}
        return {
            key: fleet.get(key)
            for key in (
                "utilization", "state", "arrival_qps", "knee_qps",
                "headroom_qps", "model_drift",
            )
        }

    @staticmethod
    def _compact_timings(timings):
        """Tuple-keyed per-shard timings -> JSON-safe compact keys (same
        labelling as the client reply: first file + count for a group)."""
        return {
            (k[0] if len(k) == 1 else f"{k[0]}+{len(k) - 1}more"): v
            for k, v in (timings or {}).items()
        }

    def _finalize_query_obs(self, parent, segment, error=None):
        """Every finished groupby parent (success, abort, or silent
        supersede) lands here exactly once: latency histogram observation,
        timeline assembly into the trace ring buffer, slow-query check."""
        from bqueryd_tpu import obs

        wall = time.perf_counter() - segment.get(
            "created_clock", time.perf_counter()
        )
        self.counters["queries_completed"] += 1
        obs_state = segment.get("obs")
        if error is not None:
            # forensic event (never gated): failed queries are exactly what
            # a debug bundle gets pulled for
            self.flight.record(
                "query_failed",
                parent=parent,
                trace_id=(obs_state or {}).get("trace_id"),
                wall_s=round(wall, 6),
                error=str(error)[:300],
            )
        if not obs.enabled():
            return
        if error is None:
            self.flight.record(
                "query_done",
                parent=parent,
                trace_id=(obs_state or {}).get("trace_id"),
                wall_s=round(wall, 6),
                shards=len(segment.get("filenames", ())),
            )
        self.query_seconds.observe(wall)
        # SLO accounting: every finished groupby lands in its client class's
        # deadline-margin histogram and burn-rate window.  An absolute
        # client deadline wins over the class target; without one the
        # margin is measured against the class's target_s
        msg = segment["msg"]
        deadline = msg.get("deadline")
        margin_s = (
            float(deadline) - time.time() if deadline is not None else None
        )
        self.slo.record(
            msg.get("slo_class"),
            wall,
            margin_s=margin_s,
            ok=error is None,
        )
        if not obs_state:
            return
        trace_id = obs_state["trace_id"]
        # the parent span opens at SUBMIT (so its admission-wait child can
        # never start before it) and closes now: queue wait + execution
        submitted = obs_state.get("submitted_ts", segment["created"])
        spans = [
            obs.make_span(
                trace_id, "groupby", submitted,
                wall + max(segment["created"] - submitted, 0.0),
                span_id=obs_state["qspan_id"],
                parent_span_id=obs_state["root_span_id"],
                node=self.address,
                tags={"parent_token": parent},
            )
        ]
        for span in obs_state["spans"]:
            # shared-dispatch worker spans were recorded under the trace of
            # whichever subscriber created the work unit — retag so every
            # timeline is self-consistent
            span = dict(span)
            span["trace_id"] = trace_id
            spans.append(span)
        spans.sort(key=lambda s: s.get("start_ts", 0.0))
        timeline = {
            "trace_id": trace_id,
            "ok": error is None,
            "wall_s": round(wall, 6),
            "created_ts": segment["created"],
            "filenames": list(segment["filenames"]),
            "pruned": list(segment.get("pruned", ())),
            "spans": spans,
        }
        if error is not None:
            timeline["error"] = str(error)[:500]
        # critical-path attribution, assembled at trace completion: the
        # autopsy record rides the stored timeline (rpc.autopsy /
        # debug_bundle read it back for free); a malformed span set must
        # never break query completion
        try:
            timeline["attribution"] = obs.slo.attribute(timeline)
        except Exception:
            self.logger.exception("attribution failed for %s", trace_id)
        # capacity cross-check: the query's MEASURED pre-worker wait
        # (admission_wait + dispatch segments — submit to worker send,
        # exactly what the M/G/1 prediction models; retry backoff is
        # failure-induced, not load-induced, and stays out) feeds the
        # model's measured-wait EWMA, whose gap to the prediction is the
        # model_drift gauge
        attribution = timeline.get("attribution")
        if isinstance(attribution, dict) and error is None:
            segments = attribution.get("segments") or {}
            self.capacity.observe_queue_wait(
                segments.get("admission_wait", 0.0)
                + segments.get("dispatch", 0.0),
                source="autopsy",
            )
        self.trace_store.put(trace_id, timeline)
        recorded = self.slow_queries.maybe_record(
            wall,
            {
                "trace_id": trace_id,
                "ok": error is None,
                **({"error": str(error)[:200]} if error is not None else {}),
                "filenames": len(segment["filenames"]),
                "pruned_shards": len(segment.get("pruned", ())),
                "plan_signature": segment.get("plan_sig"),
                "slo_class": self.slo.resolve(msg.get("slo_class")),
                "strategy_hints": dict(segment.get("strategies", {})),
                "effective_strategies": self._compact_timings(
                    segment.get("effective")
                ),
                "phase_timings": self._compact_timings(segment.get("timings")),
                # compact critical-path view (full record: rpc.autopsy)
                "attribution": obs.slo.summarize(
                    timeline.get("attribution")
                ),
            },
        )
        if recorded:
            self.counters["slow_queries"] += 1

    def abort_parent(self, parent, error_text, reply=True, error_class=None,
                     attempts=None):
        segment = self.rpc_segments.pop(parent, None)
        if segment is None:
            return
        # detach this parent from shared work units; units with no remaining
        # subscriber die, shared ones keep computing for their other parents
        dead = set()
        for token, subs in list(self._work_subscribers.items()):
            if parent in subs:
                subs[:] = [p for p in subs if p != parent]
                if not subs:
                    dead.add(token)
                    self._drop_work(token)
        for token in dead:
            self.inflight.pop(token, None)
        # drop queued siblings of the aborted query (shared units survive
        # via their live subscriber list)
        for queue in self.worker_out_messages.values():
            queue[:] = [
                m for m in queue
                if m.get("token") not in dead
                and not (
                    m.get("parent_token") == parent
                    and m.get("token") not in self._work_subscribers
                )
            ]
        self._finish_segment(
            parent,
            segment,
            pickle.dumps(
                {
                    "ok": False,
                    "error": str(error_text),
                    # structured failure detail (messages.py result-envelope
                    # schema): the error class plus the per-attempt
                    # worker/fault history the flight recorder accumulated,
                    # so a retry-exhausted client learns WHERE its query
                    # died instead of a bare string (or a blind timeout)
                    "error_class": error_class,
                    "attempts": list(attempts or []),
                },
                protocol=4,
            ) if reply else None,
            error=error_text,
        )

    def reply_rpc_raw(self, client_token, payload_bytes):
        client = binascii.unhexlify(client_token)
        try:
            self.socket.send_multipart([client, b"", payload_bytes])
        except zmq.ZMQError:
            self.logger.exception("could not reply to client %r", client_token)

    def reply_rpc_message(self, client_token, msg):
        if client_token is None:
            return
        msg.pop("data", None)
        self.reply_rpc_raw(client_token, msg.to_json().encode())

    # -- peer gossip -------------------------------------------------------
    def handle_peer(self, msg):
        addr = msg.get("from")
        if addr and addr != self.address:
            info = msg.get_from_binary("info", {})
            info["last_seen"] = time.time()
            self.others[addr] = info

    # -- RPC dispatch ------------------------------------------------------
    def handle_rpc(self, client, payload):
        token = binascii.hexlify(client).decode()
        try:
            msg = msg_factory(payload)
        except messages.MalformedMessage:
            self.reply_rpc_raw(token, b'{"payload": "malformed request"}')
            return
        msg["token"] = token
        verb = msg.get("payload")
        from bqueryd_tpu import obs

        # flight ring: client envelopes (hot path — kill-switch gated; pings
        # are connection noise, not forensics)
        if verb != "ping" and obs.enabled():
            self.flight.record(
                "rpc",
                verb=verb,
                client=token[:12],
                trace_id=(msg.get_trace() or {}).get("trace_id"),
            )
        handler = getattr(self, f"rpc_{verb}", None)
        if verb not in CONTROLLER_VERBS or handler is None:
            err = ErrorMessage(msg)
            err["payload"] = f"Sorry, unknown verb {verb!r}"
            self.reply_rpc_message(token, err)
            return
        try:
            handler(msg)
        except Exception as exc:
            self.logger.exception("rpc %s failed", verb)
            err = ErrorMessage(msg)
            err["payload"] = f"{type(exc).__name__}: {exc}"
            self.reply_rpc_message(token, err)

    def rpc_ping(self, msg):
        reply = msg.copy()
        reply["payload"] = "pong"
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_info(self, msg):
        reply = msg.copy()
        reply.add_as_binary("result", self.get_info())
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_metrics(self, msg):
        """Prometheus text exposition of this controller's registry — the
        RPC twin of the opt-in /metrics HTTP endpoint."""
        reply = msg.copy()
        reply.add_as_binary("result", self.metrics.render())
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_trace(self, msg):
        """The assembled per-query timeline for one trace_id (or None when
        it fell out of the ring buffer): ``rpc.trace(rpc.last_trace_id)``."""
        args, _ = msg.get_args_kwargs()
        trace_id = args[0] if args else None
        reply = msg.copy()
        reply.add_as_binary("result", self.trace_store.get(trace_id))
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_slow_queries(self, msg):
        """The slow-query ring buffer (threshold BQUERYD_TPU_SLOW_QUERY_MS),
        newest last: plan signature, strategy hints, pruned-shard count, and
        phase breakdown per offender."""
        reply = msg.copy()
        reply.add_as_binary("result", self.slow_queries.entries())
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_autopsy(self, msg):
        """``rpc.autopsy(trace_id=None)``: the attributed critical-path
        breakdown for one query (or the newest) — wall decomposed into
        non-overlapping named segments with coverage accounting, the
        per-attempt dispatch history (retries, backoff, hedges), and the
        slow-query ring entry when the query crossed the threshold.  None
        when the timeline fell out of the ring."""
        args, kwargs = msg.get_args_kwargs()
        trace_id = args[0] if args else kwargs.get("trace_id")
        reply = msg.copy()
        reply.add_as_binary("result", self.build_autopsy(trace_id))
        self.reply_rpc_message(msg.get("token"), reply)

    def build_autopsy(self, trace_id=None):
        from bqueryd_tpu import obs

        timeline = (
            self.trace_store.get(trace_id)
            if trace_id
            else self.trace_store.latest()
        )
        if timeline is None:
            return None
        record = timeline.get("attribution")
        if not isinstance(record, dict):
            # a timeline stored before attribution existed (or whose
            # assembly failed): attribute on demand
            record = obs.slo.attribute(timeline)
        record = dict(record)
        slow = self.slow_queries.entry_for(record.get("trace_id"))
        if slow is not None:
            record["slow_query"] = slow
        return record

    def rpc_timeline(self, msg):
        """``rpc.timeline()``: the bounded ring of periodic controller
        registry snapshots (counters, queue depths, latency quantiles, SLO
        burn rates; one entry per BQUERYD_TPU_TIMELINE_INTERVAL_S), oldest
        first — regression spotting from one verb."""
        reply = msg.copy()
        reply.add_as_binary("result", self.timeline_ring.entries())
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_capacity(self, msg):
        """``rpc.capacity()``: the fleet capacity model — per-worker μ/λ/ρ
        and saturation state (hysteresis applied), fleet utilization,
        predicted-vs-measured queue delay with the drift between them, the
        per-shard dispatch heat map, headroom QPS / the predicted
        saturation knee, and the shadow advisor's current recommendations
        with their evidence.  Advisory only: nothing here is acted on."""
        self.capacity.evaluate()
        reply = msg.copy()
        reply.add_as_binary("result", self.capacity.snapshot())
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_health(self, msg):
        """Per-worker health statuses (ok/degraded/wedged) from the rolling
        latency/error baselines — the view dispatch routing acts on."""
        from bqueryd_tpu.obs import health as health_mod

        reply = msg.copy()
        reply.add_as_binary(
            "result",
            {
                "workers": self.health.statuses(),
                "routing_enabled": health_mod.routing_enabled(),
            },
        )
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_debug_bundle(self, msg):
        """``rpc.debug_bundle(trace_id=None)``: the cross-node forensic
        artifact (schema ``bqueryd_tpu.debug_bundle/4``) — flight rings,
        the requested (or newest) trace timeline, metrics and slow-query
        snapshots, per-worker compile registries and device health.  One
        JSON-safe dict you can attach to a bug report; dead peers degrade
        it (stale snapshots, ``partial`` list), never fail it."""
        args, kwargs = msg.get_args_kwargs()
        trace_id = args[0] if args else kwargs.get("trace_id")
        reply = msg.copy()
        reply.add_as_binary("result", self.build_debug_bundle(trace_id))
        self.reply_rpc_message(msg.get("token"), reply)

    def build_debug_bundle(self, trace_id=None):
        """Assemble the debug artifact from controller-held state (no
        blocking round-trips: worker slices come from absorbed WRM
        heartbeats, so a wedged or dead worker can't stall the bundle)."""
        from bqueryd_tpu import obs
        from bqueryd_tpu.obs import profile as obs_profile

        timeline = (
            self.trace_store.get(trace_id)
            if trace_id
            else self.trace_store.latest()
        )
        controller_section = {
            "address": self.address,
            "node": self.node_name,
            "uptime_s": round(time.time() - self.start_time, 3),
            "flight": self.flight.events(),
            "flight_evictions": self.flight.evictions,
            "counters": dict(self.counters),
            "admission": self.admission.stats(),
            "workers_known": sorted(self.worker_map),
            "inflight": {
                token: {
                    "worker": e["worker"],
                    "age_s": round(time.time() - e["sent_at"], 3),
                    "retries": e.get("retries", 0),
                }
                for token, e in self.inflight.items()
            },
            "health": self.health.statuses(),
            "trace": timeline,
            # the attributed critical path of the bundled trace: the "where
            # did the wall go" answer inline, not one more verb away
            "autopsy": (timeline or {}).get("attribution"),
            "slow_queries": self.slow_queries.entries(),
            "metrics": self.metrics.histogram_snapshot(),
            "worker_histograms": self._aggregate_worker_histograms(),
            "runtime": obs_profile.runtime_versions(),
            "compile_cache": obs_profile.compile_cache_info(),
            # subsystems grown since PR 3 — the forensic artifact must
            # cover the failure surfaces that now shape a query's fate:
            # measured-cost calibration (PR 6), chaos/fault-injection and
            # replica placement (PR 8), the micro-batch window (PR 9), and
            # the SLO/timeline accounting this PR adds
            "calibration": {
                **self.calibration.stats(),
                "sample_cells": self.calibration.summary(max_cells=16),
            },
            "chaos": {
                "armed": chaos.enabled(),
                "injected_total": chaos.injected_total(),
                "site_stats": chaos.site_stats(),
            },
            "replication": self._replication_info(),
            "batch_window": self._batch_window_info(),
            "slo": self.slo.snapshot(),
            "timeline_ring": self.timeline_ring.entries()[-16:],
            # the fleet capacity model (PR 12): per-worker μ/ρ/state, shard
            # heat map, predicted-vs-measured queue delay, last shadow
            # recommendations — freshly evaluated, the bundle must not
            # ship a stale saturation verdict
            "capacity": self._capacity_bundle_section(),
            # the semantic serving layer (PR 16, schema /4): rollup entry
            # states + heat, and the last subsumption decisions with their
            # rejected candidates and reasons
            "serving": self.serving.snapshot(),
        }
        snapshots = {}
        for worker_id in set(self.worker_map) | set(self._worker_debug):
            absorbed = self._worker_debug.get(worker_id)
            snapshots[worker_id] = {
                "data": absorbed["data"] if absorbed else None,
                "ts": absorbed["ts"] if absorbed else None,
                "registered": worker_id in self.worker_map,
            }
        # redaction roots: serving data dirs, the runfile dir, and the
        # compile-cache path are operational facts; everything else path-
        # shaped (home dirs, site-packages in tracebacks) is reduced to
        # <redacted>/basename before the bundle can leave the cluster
        allowed = {self.runfile_dir}
        allowed.update(
            info.get("data_dir") for info in self.worker_map.values()
        )
        cache_path = controller_section["compile_cache"].get("path")
        if cache_path:
            allowed.add(cache_path)
        return obs.build_bundle(
            controller_section,
            snapshots,
            trace_id=trace_id or (timeline or {}).get("trace_id"),
            allowed_path_prefixes=[p for p in allowed if p],
        )

    def _dump_debug_signal(self, *args):
        from bqueryd_tpu.obs import flightrec

        try:
            path = flightrec.dump_bundle(
                self.build_debug_bundle(), role="controller"
            )
            self.logger.warning("SIGUSR1: debug bundle written to %s", path)
        except Exception:
            self.logger.exception("SIGUSR1 debug dump failed")

    def get_info(self, include_peers=True):
        from bqueryd_tpu.obs import profile as obs_profile

        health_runtime = {
            "runtime": obs_profile.runtime_versions(),
            "compile_cache": obs_profile.compile_cache_info(),
            "worker_runtime": {
                worker_id: (absorbed.get("data") or {}).get("runtime")
                for worker_id, absorbed in self._worker_debug.items()
                if worker_id in self.worker_map
            },
        }
        info = {
            "address": self.address,
            "node": self.node_name,
            "uptime": time.time() - self.start_time,
            "msg_count_in": self.msg_count_in,
            "workers": self.worker_map,
            "worker_out_messages": {
                str(k): len(v) for k, v in self.worker_out_messages.items()
            },
            "inflight": len(self.inflight),
            "rpc_segments": len(self.rpc_segments),
            "counters": dict(self.counters),
            "admission": self.admission.stats(),
            "shard_stats_known": len(self.shard_stats),
            # replica placement visibility: the configured factor, shards
            # bucketed by live holder count, and the shards failover can't
            # yet help (fewer holders than the factor asks for)
            "replication": self._replication_info(),
            # every worker's latency histograms, merged by bucket-vector
            # addition (identical fixed buckets are the precondition, see
            # obs.metrics) — rides peer gossip too, so any controller can
            # answer for the fleet
            "worker_histograms": self._aggregate_worker_histograms(),
            "trace_buffer": len(self.trace_store),
            "slow_queries": len(self.slow_queries),
            # heterogeneous-fleet triage facts (see ops/__init__.py's SIGILL
            # note): this process's jax/jaxlib/libtpu versions + the
            # persistent-compile-cache decision, plus every worker's own
            # versions as gossiped in WRM debug slices
            "runtime": health_runtime["runtime"],
            "compile_cache": health_runtime["compile_cache"],
            "worker_runtime": health_runtime["worker_runtime"],
            "health": self.health.statuses(),
        }
        if include_peers:
            info["others"] = self.others
        return info

    def _replication_info(self):
        """Replica placement visibility, shared by get_info and the debug
        bundle: the configured factor, shards bucketed by live holder
        count, and the shards failover can't yet help (factor 0 = "all
        nodes" mode, where a single-holder shard is still the pager
        signal)."""
        return {
            "replica_factor": self.replica_factor,
            "shards_by_holders": self._holder_counts(),
            "under_replicated": sorted(
                f for f, holders in self.files_map.items()
                if len(holders) < (self.replica_factor or 2)
            )[:64],
        }

    def _capacity_bundle_section(self):
        """The debug bundle's capacity slice: a fresh evaluation (a bundle
        pulled during an incident must carry the live saturation verdict,
        not the last heartbeat's)."""
        self.capacity.evaluate()
        return self.capacity.snapshot()

    def _batch_window_info(self):
        """Micro-batch window state for the debug bundle: the live knobs
        plus what is staged right now (a wedged flush shows up here)."""
        from bqueryd_tpu.plan import bundle as bundlemod

        window_state = {
            "window_ms": bundlemod.batch_window_ms(),
            "batch_max": bundlemod.batch_max(),
            "staged": len(self._pending_window),
        }
        if self._pending_window:
            window_state["opened_age_s"] = round(
                max(time.time() - self._window_opened, 0.0), 3
            )
        return window_state

    def _aggregate_worker_histograms(self):
        # memoized on the snapshot revision: get_info runs once per peer per
        # gossip tick, and redoing the O(workers x histograms) vector merge
        # for each peer when nothing changed is pure waste
        rev, cached = self._worker_hist_cache
        if rev == self._worker_metrics_rev:
            return cached
        from bqueryd_tpu import obs

        merged = obs.merge_histogram_snapshots(self._worker_metrics.values())
        self._worker_hist_cache = (self._worker_metrics_rev, merged)
        return merged

    def rpc_loglevel(self, msg):
        args, _ = msg.get_args_kwargs()
        self._fan_out_to_workers(msg)
        self._fan_out_to_peers(msg)
        import logging

        level = {"debug": logging.DEBUG, "info": logging.INFO}.get(
            args[0] if args else "info", logging.INFO
        )
        bqueryd_tpu.logger.setLevel(level)
        reply = msg.copy()
        reply["payload"] = "OK"
        self.reply_rpc_message(msg.get("token"), reply)

    def _fan_out_to_workers(self, msg):
        for worker_id in list(self.worker_map):
            fan = msg.copy()
            fan.pop("token", None)
            try:
                self.socket.send_multipart(
                    [worker_id.encode(), fan.to_json().encode()]
                )
            except zmq.ZMQError:
                pass

    def _fan_out_to_peers(self, msg):
        if msg.get("_relayed"):
            return  # no gossip storms
        for addr in list(self.others):
            fan = msg.copy()
            fan.pop("token", None)
            fan["_relayed"] = True
            try:
                self.socket.send_multipart([addr.encode(), fan.to_json().encode()])
            except zmq.ZMQError:
                pass

    def rpc_kill(self, msg):
        reply = msg.copy()
        reply["payload"] = "OK"
        self.reply_rpc_message(msg.get("token"), reply)
        self.running = False

    def rpc_killworkers(self, msg):
        kill = Message({"payload": "kill"})
        self._fan_out_to_workers(kill)
        reply = msg.copy()
        reply["payload"] = "OK"
        self.reply_rpc_message(msg.get("token"), reply)

    def rpc_killall(self, msg):
        fan = msg.copy()
        fan.pop("token", None)  # killall itself answers the client, not this
        self.rpc_killworkers(fan)
        if not msg.get("_relayed"):
            for addr in list(self.others):
                fan = RPCMessage({"payload": "killall", "_relayed": True})
                try:
                    self.socket.send_multipart(
                        [addr.encode(), fan.to_json().encode()]
                    )
                except zmq.ZMQError:
                    pass
        reply = msg.copy()
        reply["payload"] = "OK"
        self.reply_rpc_message(msg.get("token"), reply)
        self.running = False

    def rpc_sleep(self, msg):
        args, kwargs = msg.get_args_kwargs()
        if args and isinstance(args[0], (list, tuple)):
            # scatter without gather (reference bqueryd/controller.py:411-424)
            for duration in args[0]:
                scatter = CalcMessage({"payload": "sleep"})
                scatter.set_args_kwargs([duration], {})
                self.worker_out_messages[None].append(scatter)
            reply = msg.copy()
            reply["payload"] = "OK"
            self.reply_rpc_message(msg.get("token"), reply)
            return
        calc = CalcMessage({"payload": "sleep", "token": msg["token"]})
        calc.set_args_kwargs(args, kwargs)
        self.worker_out_messages[None].append(calc)

    def rpc_readfile(self, msg):
        calc = CalcMessage(dict(msg))
        calc["payload"] = "readfile"
        self.worker_out_messages[None].append(calc)

    def rpc_execute_code(self, msg):
        args, kwargs = msg.get_args_kwargs()
        if "function" not in kwargs and not msg.get("function"):
            raise ValueError("execute_code requires function= kwarg")
        wait = kwargs.pop("wait", False)
        calc = CalcMessage(dict(msg))
        calc["payload"] = "execute_code"
        calc.set_args_kwargs(args, kwargs)
        if not wait:
            calc.pop("token", None)
            self.worker_out_messages[None].append(calc)
            reply = msg.copy()
            reply["payload"] = "OK"
            self.reply_rpc_message(msg.get("token"), reply)
        else:
            self.worker_out_messages[None].append(calc)

    def rpc_download(self, msg):
        from bqueryd_tpu.download import setup_download

        setup_download(self, msg)

    # -- streaming append (PR 14) ------------------------------------------
    def rpc_append(self, msg):
        """``rpc.append(filename, dataframe_like)``: route the batch to
        every replica holder of the shard — one dispatch per distinct
        (node, data_dir), so co-located workers sharing one directory
        apply it once — and reply when ALL holders confirmed.  Holder
        stats for the shard are dropped on completion so plan-time pruning
        never acts on pre-append min/max while fresh WRM stats are in
        flight.  Replica divergence contract: a holder that fails leaves
        replicas inconsistent; the error reply names it, and re-issuing
        the append (or re-downloading the shard) is the repair path."""
        args, _kwargs = msg.get_args_kwargs()
        if len(args) != 2:
            raise ValueError("append needs (filename, dataframe_like)")
        filename = args[0]
        holders = sorted(self.files_map.get(filename) or ())
        if not holders:
            raise ValueError(
                f"file {filename!r} is not served by any worker"
            )
        # one target per physical replica directory: workers co-located on
        # one (node, data_dir) serve the SAME bytes — appending through
        # each would duplicate the rows
        targets = {}
        for worker_id in holders:
            info = self.worker_map.get(worker_id) or {}
            group = (info.get("node"), info.get("data_dir") or worker_id)
            targets.setdefault(group, worker_id)
        # rollups covering this shard go stale BEFORE any worker mutates
        # its replica: a stale-but-unchanged entry refreshes back to ready,
        # the reverse order could serve pre-append partials as fresh
        self.serving.note_append(filename)
        deadline = msg.get("deadline")
        seg_key = f"append_{os.urandom(8).hex()}"
        segment = {
            "client_token": msg["token"],
            "filename": filename,
            "created": time.time(),
            "expires": (
                float(deadline) if deadline is not None
                else time.time() + APPEND_TIMEOUT
            ),
            "pending": {},   # dispatch token -> worker_id
            "results": {},   # worker_id -> result dict
            "errors": {},    # worker_id -> error text
        }
        for worker_id in sorted(targets.values()):
            calc = CalcMessage(dict(msg))
            calc["payload"] = "append"
            calc["filename"] = filename
            calc["token"] = f"append_{os.urandom(8).hex()}"
            calc["worker_id"] = worker_id
            segment["pending"][calc["token"]] = worker_id
            self._append_waiters[calc["token"]] = seg_key
            self.worker_out_messages.setdefault(worker_id, []).append(calc)
            self.counters["append_dispatches"] += 1
        self._append_segments[seg_key] = segment
        self.counters["append_requests"] += 1
        self.flight.record(
            "append_fanout", filename=filename,
            holders=len(segment["pending"]),
        )

    def _absorb_append_reply(self, token, msg):
        """One holder's append reply: record it and, when every holder
        answered, reply to the client (all-ok -> per-holder summary;
        any failure -> structured error naming the failed holders)."""
        seg_key = self._append_waiters.pop(token, None)
        segment = self._append_segments.get(seg_key)
        if segment is None:
            return
        worker_id = segment["pending"].pop(token, None)
        if worker_id is None:
            return
        if msg.isa(ErrorMessage):
            text = str(msg.get("payload") or "append failed")
            if "unhandled message payload" in text:
                # pre-PR-14 worker: its base handler rejects the verb with
                # a traceback — rewrite into the structured mixed-version
                # error MIGRATION documents
                text = (
                    "UnsupportedVerb: worker predates streaming append "
                    "(PR 14); upgrade calc workers before using rpc.append"
                )
            else:
                text = (text.strip().splitlines() or ["append failed"])[-1]
            segment["errors"][worker_id] = text[:300]
        else:
            segment["results"][worker_id] = (
                msg.get_from_binary("result") or {}
            )
        if segment["pending"]:
            return
        self._finish_append_segment(seg_key, segment)

    def _finish_append_segment(self, seg_key, segment, timeout=False):
        self._append_segments.pop(seg_key, None)
        filename = segment["filename"]
        # pruning safety: advertised pre-append min/max could prune shards
        # whose NEW rows match — drop the stats until fresh WRMs land
        # (stats-less shards conservatively match everything)
        self.shard_stats.pop(filename, None)
        reply_to = segment["client_token"]
        if segment["errors"] or timeout:
            for token in list(self._append_waiters):
                if self._append_waiters.get(token) == seg_key:
                    self._append_waiters.pop(token, None)
            detail = "; ".join(
                f"{w}: {e}" for w, e in sorted(segment["errors"].items())
            )
            if timeout and segment["pending"]:
                waiting = ", ".join(sorted(segment["pending"].values()))
                detail = (
                    f"{detail}; " if detail else ""
                ) + f"no reply from {waiting}"
            ok_part = (
                f" ({len(segment['results'])} holder(s) DID apply the "
                f"append — replicas may have diverged; re-issue the "
                f"append or re-download the shard)"
                if segment["results"] else ""
            )
            err = ErrorMessage({"token": reply_to})
            err["payload"] = (
                f"append {filename!r} failed: {detail}{ok_part}"
            )
            self.flight.record(
                "append_failed", filename=filename, detail=detail[:200],
            )
            self.reply_rpc_message(reply_to, err)
            return
        reply = Message({"token": reply_to, "payload": "append"})
        reply.add_as_binary(
            "result",
            {
                "filename": filename,
                "holders": segment["results"],
                "appended": max(
                    (r.get("appended", 0) for r in
                     segment["results"].values()),
                    default=0,
                ),
            },
        )
        self.reply_rpc_message(reply_to, reply)

    def _sweep_append_segments(self):
        """Fail append fan-outs whose holders never answered (dead worker,
        lost reply) instead of hanging the client past its RPC timeout."""
        if not self._append_segments:
            return
        now = time.time()
        for seg_key, segment in list(self._append_segments.items()):
            if now > segment["expires"]:
                self._finish_append_segment(seg_key, segment, timeout=True)

    def release_ticket_waiters(self, ticket, error=None):
        segment = self.rpc_segments.pop(f"ticket_{ticket}", None)
        if segment is not None:
            if error:
                reply = ErrorMessage(segment["msg"])
                reply["payload"] = f"download ticket {ticket} failed: {error}"
            else:
                reply = segment["msg"].copy()
                reply["payload"] = "DONE"
            reply["ticket"] = ticket
            self.reply_rpc_message(segment["client_token"], reply)

    # -- groupby planning, admission & fan-out -----------------------------
    def rpc_groupby(self, msg):
        """Admission-controlled, plan-driven groupby.

        The verb no longer fans out verbatim: it compiles to a
        :class:`~bqueryd_tpu.plan.LogicalPlan` (rewrites applied), passes
        admission control (explicit BUSY backpressure instead of unbounded
        inflight growth), and launches via :meth:`_launch_plan`, which
        prunes shards against advertised stats, fuses identical concurrent
        work, and stamps each dispatch with a kernel-strategy hint."""
        from bqueryd_tpu import obs
        from bqueryd_tpu import plan as planmod

        args, kwargs = msg.get_args_kwargs()
        if len(args) != 4:
            raise ValueError(
                "groupby needs (filenames, groupby_cols, agg_list, where_terms)"
            )
        filenames, groupby_cols, agg_list, where_terms = args
        # an op outside the groupby surface fails HERE, as a structured
        # envelope (error_class="UnsupportedOp", like PR-8's
        # DispatchExhausted) — not as a worker traceback relayed three
        # hops later.  The richer operators live behind rpc.query().
        from bqueryd_tpu.models.query import AGG_OPS, normalize_agg_list

        try:
            bad = sorted(
                {
                    str(a[1]) for a in normalize_agg_list(agg_list)
                    if a[1] not in AGG_OPS
                }
            )
        except Exception:
            bad = []  # malformed agg lists fall through to plan compile
        if bad:
            self.reply_rpc_raw(
                msg["token"],
                pickle.dumps(
                    {
                        "ok": False,
                        "error_class": "UnsupportedOp",
                        "error": (
                            f"unsupported aggregation op(s) {bad}; groupby "
                            f"supports {list(AGG_OPS)} — joins, top-k, "
                            f"quantiles and window rollups go through the "
                            f"query verb (rpc.query)"
                        ),
                    },
                    protocol=4,
                ),
            )
            return
        # tracing: adopt the client's TraceContext (mint one for traceless
        # clients); the controller "groupby" span parents every query span
        # and is itself a child of the client's root span
        ctx = obs.TraceContext.from_wire(msg.get_trace())
        if ctx is None:
            ctx = obs.TraceContext.new_root()
        obs_state = self._new_obs_state(ctx)
        msg["_obs"] = obs_state
        plan_start = time.time()
        plan_clock = time.perf_counter()
        # dedup, order-preserving (inside plan compilation): duplicates would
        # double-count on the batched path and deadlock the per-shard path
        plan = planmod.plan_groupby(
            filenames, groupby_cols, agg_list, where_terms,
            aggregate=kwargs.get("aggregate", True),
            expand_filter_column=kwargs.get("expand_filter_column"),
        )
        if obs.enabled():
            obs_state["spans"].append(
                obs.make_span(
                    ctx.trace_id, "plan", plan_start,
                    time.perf_counter() - plan_clock,
                    parent_span_id=obs_state["qspan_id"], node=self.address,
                )
            )
        self._admit_plan(msg, plan, kwargs)

    def rpc_query(self, msg):
        """The operator-DAG verb: compiles the ``rpc.query(spec)`` dict
        into a typed :class:`~bqueryd_tpu.plan.dag.OperatorDAG` (broadcast
        hash joins, per-group top-k, mergeable quantile sketches,
        time-window rollups), derives its groupby-shaped logical plan, and
        admits it through the SAME machinery as ``rpc_groupby`` — so
        admission quotas, shard pruning, replica failover, SLO accounting
        and autopsy attribution all apply to the new operators for free.
        Spec validation failures reply a structured envelope
        (``error_class`` "UnsupportedOp" / "InvalidPlan")."""
        from bqueryd_tpu import obs
        from bqueryd_tpu.plan import dag as dagmod

        args, kwargs = msg.get_args_kwargs()
        if len(args) != 1 or not isinstance(args[0], dict):
            raise ValueError("query needs one spec dict argument")
        ctx = obs.TraceContext.from_wire(msg.get_trace())
        if ctx is None:
            ctx = obs.TraceContext.new_root()
        obs_state = self._new_obs_state(ctx)
        msg["_obs"] = obs_state
        plan_start = time.time()
        plan_clock = time.perf_counter()
        try:
            dag = dagmod.compile_query(args[0])
            plan, dag_kwargs = dagmod.groupby_equivalent(dag)
        except dagmod.DagValidationError as exc:
            self.reply_rpc_raw(
                msg["token"],
                pickle.dumps(
                    {
                        "ok": False,
                        "error_class": exc.error_class,
                        "error": str(exc),
                    },
                    protocol=4,
                ),
            )
            return
        kwargs = dict(kwargs)
        kwargs.update(dag_kwargs)
        if obs.enabled():
            obs_state["spans"].append(
                obs.make_span(
                    ctx.trace_id, "plan", plan_start,
                    time.perf_counter() - plan_clock,
                    parent_span_id=obs_state["qspan_id"], node=self.address,
                )
            )
        self._admit_plan(msg, plan, kwargs)

    # -- semantic serving wire plumbing (PR 16) ---------------------------
    # All rollup message construction and reply absorption live HERE (not
    # in serve/) so the wire lint audits every key both ways.

    def _dispatch_rollup_build(self, entry, prior=None):
        """Fan one ``rollup`` CalcMessage per shard of a rollup entry to a
        live holder.  A refresh (``prior`` set) ships each shard's previous
        partials plus the chunk-prefix fingerprint they were computed
        against; the worker delta-aggregates only the appended tail when
        the prefix still validates (ops.workingset.growth_since)."""
        spec = entry.spec
        dag_blob = None
        if spec.get("dag_wire") is not None:
            dag_blob = base64.b64encode(
                pickle.dumps(
                    spec["dag_wire"], protocol=messages.PICKLE_PROTOCOL
                )
            ).decode("ascii")
        keys, agg_list, where = spec["args"]
        for fname in entry.filenames:
            holders = self.files_map.get(fname) or set()
            worker_id = next(
                (w for w in sorted(holders) if w in self.worker_map), None
            )
            if worker_id is None:
                self.serving.manager.fail(entry.key, "no-holder")
                self.flight.record(
                    "rollup_build_failed", entry=entry.key,
                    filename=fname, reason="no-holder",
                )
                return
            calc = CalcMessage({
                "payload": "rollup",
                "filename": fname,
                "token": f"rollup_{os.urandom(8).hex()}",
                "worker_id": worker_id,
            })
            calc.set_args_kwargs(
                [fname, keys, agg_list, where], {"aggregate": True}
            )
            if dag_blob is not None:
                calc["dag"] = dag_blob
            pinfo = (prior or {}).get(fname) or {}
            if pinfo.get("data") and pinfo.get("base") is not None:
                # partials bytes ride base64-framed: the calc wire is JSON
                calc.add_as_binary("rollup_prior", pinfo["data"])
                calc.add_as_binary("rollup_base", pinfo["base"])
            self._rollup_waiters[calc["token"]] = (entry.key, fname)
            self.worker_out_messages.setdefault(worker_id, []).append(calc)
        self.flight.record(
            "rollup_dispatch", entry=entry.key,
            shards=len(entry.filenames), refresh=prior is not None,
        )

    def _absorb_rollup_reply(self, token, msg):
        """One shard's rollup build/refresh reply: parse the partials and
        proof metadata into the serving layer.  An error reply (including
        a pre-PR-16 worker's base-handler rejection of the verb) drops the
        whole entry — serving simply stays on the recompute path."""
        key, fname = self._rollup_waiters.pop(token)
        if msg.isa(ErrorMessage):
            text = str(msg.get("payload") or "rollup build failed")
            if "unhandled message payload" in text:
                text = (
                    "UnsupportedVerb: worker predates semantic serving "
                    "(PR 16); rollups stay disabled until calc workers "
                    "are upgraded"
                )
            else:
                text = (text.strip().splitlines() or ["failed"])[-1]
            self.serving.manager.fail(key, text)
            self.flight.record(
                "rollup_build_failed", entry=key, filename=fname,
                reason=text[:200],
            )
            return
        from bqueryd_tpu.models.query import ResultPayload

        data = msg.get("data")
        mode = msg.get("rollup_mode") or "rebuild"
        base = (
            msg.get_from_binary("rollup_base")
            if msg.get("rollup_base") else None
        )
        zones = (
            msg.get_from_binary("rollup_zones")
            if msg.get("rollup_zones") else {}
        )
        try:
            payload = dict(ResultPayload.from_bytes(data))
        except Exception:
            self.serving.manager.fail(key, "undecodable payload")
            return
        groups = (
            len(payload.get("rows", ()))
            if payload.get("kind") == "partials" else 0
        )
        state = self.serving.absorb_build(key, fname, {
            "data": data,
            "payload": payload,
            "base": base,
            "zones": zones,
            "groups": int(groups),
            "mode": mode,
        })
        if mode == "delta":
            self.counters["rollup_refreshes"] += 1
        elif mode == "rebuild":
            self.counters["rollup_builds"] += 1
        if state == "ready":
            self.flight.record(
                "rollup_materialized", entry=key, mode=mode,
            )

    def _reply_served(self, msg, payloads, source, subsumed_from):
        """Answer a groupby-shaped verb straight from the serving layer.
        The envelope mirrors _maybe_complete_segment's (empty timing /
        strategy maps: nothing was dispatched) plus the PR-16 provenance
        pair.  A live admission ticket on this REQ identity is retired
        first — the REQ socket is lockstep, so the abandoned run's reply
        would otherwise mis-pair with this client's next request."""
        token = msg["token"]
        if token in self._ticket_sigs:
            self._cancel_ticket(token)
        self._count_answer(source)
        self.reply_rpc_raw(
            token,
            pickle.dumps(
                {
                    "ok": True,
                    "payloads": payloads,
                    "timings": {},
                    "strategies": {"hints": {}, "effective": {}},
                    "merge_modes": {},
                    "answer_source": source,
                    "subsumed_from": subsumed_from,
                },
                protocol=4,
            ),
        )

    def _count_answer(self, source):
        """Per-source answer provenance counter (every client reply path
        funnels through here exactly once)."""
        self.metrics.counter(
            "bqueryd_tpu_serve_answers_total",
            "groupby answers by provenance source "
            "(recompute|cached|delta|rollup|subsume)",
            labels={"source": source},
        ).inc()

    def _admit_plan(self, msg, plan, kwargs):
        """Shared admission tail of the groupby-shaped verbs (groupby and
        query): unknown-shard check, quota/dedup/supersede handling, BUSY
        backpressure, and the micro-batch staging launch."""
        from bqueryd_tpu import plan as planmod

        unknown = [f for f in plan.filenames if f not in self.files_map]
        if unknown:
            raise ValueError(f"filenames not found on any worker: {unknown}")

        # semantic serving (PR 16): a provable subsumption/rollup hit
        # answers right here — no admission slot, no dispatch, no scan.
        # Misses (and every refusal) fall through bit-identically to the
        # pre-serving pipeline; _reply_served retires any live ticket on
        # this REQ identity first (a timed-out resend), since that run's
        # eventual reply would mis-pair with the client's next request
        if self.serving.try_serve(msg, plan, kwargs):
            return

        # admission: the REQ token is the ticket (one live ticket per
        # lockstep REQ socket); the quota key is the client-declared
        # client_id when present, so one application's many sockets share
        # one quota bucket
        quota_key = msg.get("client_id") or msg["token"]
        # deadline/priority are deliberately NOT part of the resend
        # signature: an application-level retry restamps a fresh absolute
        # deadline, and reading that as a *new* query would cancel and
        # restart the in-flight run on every retry — a livelock for any
        # query longer than the retry interval.  An identical resend joins
        # the in-flight run; that run's (earlier) deadline governs.
        req_sig = (tuple(plan.filenames), plan.signature())
        decision = self.admission.submit(
            ticket_id=msg["token"],
            client=quota_key,
            priority=msg.get("priority", 0),
            deadline=msg.get("deadline"),
            payload=(msg, plan, kwargs),
        )
        if (
            decision == planmod.DUPLICATE
            and self._ticket_sigs.get(msg["token"]) != req_sig
        ):
            # a DIFFERENT query on a live identity: the REQ socket is
            # lockstep, so the client has abandoned the earlier query — its
            # reply would mis-pair with this request.  Retire the abandoned
            # run silently and admit this one in its place.
            self.counters["admission_superseded"] += 1
            self._cancel_ticket(msg["token"])
            decision = self.admission.submit(
                ticket_id=msg["token"],
                client=quota_key,
                priority=msg.get("priority", 0),
                deadline=msg.get("deadline"),
                payload=(msg, plan, kwargs),
            )
        if decision == planmod.BUSY:
            self.counters["admission_busy"] += 1
            self.reply_rpc_raw(
                msg["token"],
                pickle.dumps(
                    {
                        "ok": False,
                        "busy": True,
                        "error": "BUSY: admission queue full or client "
                                 "quota exceeded; retry with backoff",
                    },
                    protocol=4,
                ),
            )
            return
        if decision == planmod.QUEUED:
            self._ticket_sigs[msg["token"]] = req_sig
            self.counters["admission_queued"] += 1
            return  # launched later by _admit_ready
        if decision == planmod.DUPLICATE:
            # a client retrying after its own timeout resent the identical
            # query on a live ticket: the in-flight run will answer this
            # identity; launching a second fan-out would double the work
            # outside the admission bound and queue a stale extra reply
            # for the client's NEXT call
            self.logger.info(
                "duplicate groupby from client %s ignored (already running)",
                msg["token"][:12],
            )
            return
        self._ticket_sigs[msg["token"]] = req_sig
        try:
            self._stage_plan(msg, plan, kwargs)
        except Exception:
            self.admission.release(msg["token"])
            self._ticket_sigs.pop(msg["token"], None)
            raise

    def _cancel_ticket(self, ticket):
        """Silently retire a live ticket whose client has moved on: an
        active run is detached from its work units and finished with no
        reply (replying would mis-pair with the identity's next request);
        a still-queued one is dropped before it ever launches."""
        # a plan still STAGED in the micro-batch window has no segment yet:
        # drop it before the flush can launch it — its reply would queue as
        # a stale extra answer for this identity's NEXT request
        staged = [
            entry for entry in self._pending_window
            if entry[0].get("token") == ticket
        ]
        if staged:
            self._pending_window = [
                entry for entry in self._pending_window
                if entry[0].get("token") != ticket
            ]
            if self.admission.release(ticket):
                self._ticket_sigs.pop(ticket, None)
            return
        parent = next(
            (
                p for p, s in self.rpc_segments.items()
                if s.get("admission_ticket") == ticket
            ),
            None,
        )
        if parent is not None:
            self.abort_parent(parent, "superseded", reply=False)
        elif self.admission.release(ticket):
            self._ticket_sigs.pop(ticket, None)

    def _admit_ready(self):
        """Launch queued plans into freed capacity; expire stale ones."""
        if self._admitting:
            return  # re-entered via a completion inside _launch_plan
        self._admitting = True
        try:
            while True:
                launch, expired = self.admission.pop_ready()
                if not launch and not expired:
                    return
                for payload in expired:
                    msg, _plan, _kwargs = payload
                    self._ticket_sigs.pop(msg["token"], None)
                    self.counters["deadline_expired"] += 1
                    self.reply_rpc_raw(
                        msg["token"],
                        pickle.dumps(
                            {
                                "ok": False,
                                "error": "deadline exceeded while queued "
                                         "for admission",
                            },
                            protocol=4,
                        ),
                    )
                for payload in launch:
                    msg, plan, kwargs = payload
                    try:
                        self._stage_plan(msg, plan, kwargs)
                    except Exception as exc:
                        self.logger.exception("queued plan launch failed")
                        self.admission.release(msg["token"])
                        self._ticket_sigs.pop(msg["token"], None)
                        self.reply_rpc_raw(
                            msg["token"],
                            pickle.dumps(
                                {"ok": False, "error": f"{exc}"},
                                protocol=4,
                            ),
                        )
        finally:
            self._admitting = False

    def _stage_plan(self, msg, plan, kwargs):
        """Launch now (window 0 — bit-identical to the pre-window path) or
        stage into the admission micro-batch window so concurrent
        compatible queries can fuse into one shared-scan dispatch."""
        from bqueryd_tpu.plan import bundle as bundlemod

        from bqueryd_tpu import obs

        window_ms = bundlemod.batch_window_ms()
        if window_ms <= 0:
            self._launch_plan(msg, plan, kwargs)
            return
        if not self._pending_window:
            self._window_opened = time.time()
            # flight ring: staging decisions are what a "why was this query
            # 40 ms slower" timeline needs (hot path — kill-switch gated)
            if obs.enabled():
                self.flight.record("window_open", window_ms=window_ms)
        # the batch_window span (staged -> flush) is carved out of the
        # admission wait in _open_query_segment
        obs_state = msg.get("_obs")
        if isinstance(obs_state, dict):
            obs_state["staged_ts"] = time.time()
        self._pending_window.append((msg, plan, kwargs))
        if len(self._pending_window) >= bundlemod.batch_max():
            self._flush_window(force=True)

    def _window_deadline(self):
        """Absolute time the open micro-batch window closes."""
        from bqueryd_tpu.plan import bundle as bundlemod

        return self._window_opened + bundlemod.batch_window_ms() / 1000.0

    def _flush_window(self, force=False):
        """Close the micro-batch window: group the staged plans by
        compatibility signature, launch each compatible group as ONE
        shared-scan bundle, and everything else individually.  A launch
        failure is replied per member (same contract as ``_admit_ready``)
        and never poisons the other groups."""
        if not self._pending_window:
            return
        if not force and time.time() < self._window_deadline():
            return
        from bqueryd_tpu.plan import bundle as bundlemod

        from bqueryd_tpu import obs

        pending, self._pending_window = self._pending_window, []
        groups = {}
        for staged in pending:
            msg, plan, kwargs = staged
            try:
                keep, pruned = self._prune_shards(plan)
                key = bundlemod.compat_key(plan, keep, kwargs)
            except Exception:
                # one malformed plan must not poison the whole window:
                # group it solo; its own launch path replies the error
                self.logger.exception("window compatibility probe failed")
                # forensic event (never gated): a degrade-to-solo is the
                # anomaly a "why didn't these fuse" timeline must show
                self.flight.record(
                    "window_degrade_solo",
                    token=str(msg.get("token"))[:12],
                )
                keep, pruned, key = list(plan.filenames), [], None
            if key is None:
                # unfusable (raw rows, basket expansion, non-mergeable
                # aggs, batch=False, fully pruned): solo launch
                key = ("solo", id(msg))
            groups.setdefault(key, []).append((msg, plan, kwargs, keep, pruned))
        if obs.enabled():
            self.flight.record(
                "window_flush",
                staged=len(pending),
                groups=len(groups),
                fused=sum(1 for g in groups.values() if len(g) > 1),
                held_ms=round(
                    max(time.time() - self._window_opened, 0.0) * 1000.0, 1
                ),
            )
        for entries in groups.values():
            try:
                if len(entries) == 1:
                    msg, plan, kwargs, keep, pruned = entries[0]
                    self._launch_plan(
                        msg, plan, kwargs, preplanned=(keep, pruned)
                    )
                else:
                    self._launch_bundle(entries)
            except Exception as exc:
                self.logger.exception("window flush launch failed")
                for msg, _plan, _kwargs, _keep, _pruned in entries:
                    self.admission.release(msg["token"])
                    self._ticket_sigs.pop(msg["token"], None)
                    self.reply_rpc_raw(
                        msg["token"],
                        pickle.dumps(
                            {"ok": False, "error": f"{exc}"}, protocol=4
                        ),
                    )

    def _prune_shards(self, plan):
        """Plan-time shard pruning: ``(keep, pruned)`` — a shard whose
        advertised min/max stats exclude the pushed-down predicate
        conjunction is never dispatched."""
        from bqueryd_tpu import plan as planmod

        planner_on = planmod.planner_enabled()
        keep, pruned = [], []
        for f in plan.filenames:
            stats = self.shard_stats.get(f)
            if (
                planner_on
                and plan.scan.pushdown
                and stats is not None
                and not planmod.stats_can_match(stats, plan.scan.pushdown)
            ):
                pruned.append(f)
            else:
                keep.append(f)
        return keep, pruned

    def _open_query_segment(self, msg, plan, pruned):
        """Per-query result segment + observability state (shared by the
        solo launch path and every bundle member — a member keeps its own
        trace, deadline, quota ticket and reply identity).  Pruned shards'
        (provably empty) payload slots are pre-filled so the client-side
        merge contract is unchanged."""
        from bqueryd_tpu import obs

        parent_token = os.urandom(8).hex()
        # observability state: created in rpc_groupby; a traceless caller
        # (tests driving _launch_plan directly) gets a fresh one here
        obs_state = msg.get("_obs")
        if not isinstance(obs_state, dict):
            obs_state = self._new_obs_state(obs.TraceContext.new_root())
        # the admission span covers submit -> launch (~0 for an immediate
        # ADMIT, the queue wait for staged plans); time spent staged in the
        # micro-batch window is carved into its own batch_window span so an
        # autopsy can tell fusion-induced wait from admission backpressure
        if obs.enabled():
            now = time.time()
            staged_ts = obs_state.get("staged_ts")
            admitted_until = (
                min(staged_ts, now) if staged_ts is not None else now
            )
            obs_state["spans"].append(
                obs.make_span(
                    obs_state["trace_id"], "admission",
                    obs_state["submitted_ts"],
                    max(admitted_until - obs_state["submitted_ts"], 0.0),
                    parent_span_id=obs_state["qspan_id"], node=self.address,
                )
            )
            if staged_ts is not None:
                obs_state["spans"].append(
                    obs.make_span(
                        obs_state["trace_id"], "batch_window", staged_ts,
                        max(now - staged_ts, 0.0),
                        parent_span_id=obs_state["qspan_id"],
                        node=self.address,
                    )
                )
        # capacity model: one LAUNCHED query — the shards-per-query
        # denominator counts runs that actually open (shed/expired/
        # superseded offers never reach here)
        self.capacity.observe_launch()
        segment = {
            "client_token": msg["token"],
            "msg": msg,
            "filenames": list(plan.filenames),
            "results": {(f,): b"" for f in pruned},
            "timings": {},
            "created": time.time(),
            # monotonic anchor for the reported wall (an NTP step must not
            # produce a negative or inflated query latency observation)
            "created_clock": time.perf_counter(),
            "admission_ticket": msg["token"],
            "pruned": list(pruned),
            "obs": obs_state,
            "plan_sig": str(plan.signature()),
            "strategies": {},         # hint -> dispatch count
            "effective": {},          # shard-group key -> executed route
            "merge": {},              # shard-group key -> merge_mode
        }
        self.rpc_segments[parent_token] = segment
        return parent_token

    def _launch_plan(self, msg, plan, kwargs, preplanned=None):
        # ``preplanned``: the (keep, pruned) the window flush already
        # computed for compat grouping — re-pruning every solo launch would
        # double the plan-time stats_can_match cost on the event loop
        keep, pruned = (
            preplanned if preplanned is not None
            else self._prune_shards(plan)
        )
        self.counters["plan_pruned_shards"] += len(pruned)
        parent_token = self._open_query_segment(msg, plan, pruned)
        if not keep:
            # every shard pruned: answer immediately with empty payloads
            self._maybe_complete_segment(parent_token)
            return
        try:
            self._dispatch_plan(msg, plan, kwargs, parent_token, keep)
        except Exception:
            # a half-launched parent can never complete (its later groups
            # were never queued): leaving it would leak the segment, its
            # work-unit registrations, and worker time on the groups that
            # DID queue — detach them all; the caller replies the error
            self.abort_parent(parent_token, "launch failed", reply=False)
            raise

    def _launch_bundle(self, entries):
        """Launch a compatible micro-batch as shared-scan bundles: one
        CalcMessage per shard group carrying every member's fragment; the
        worker executes one decode/align/upload pass + one mesh program and
        the reply demultiplexes per member (``_demux_bundle``)."""
        from bqueryd_tpu.plan import bundle as bundlemod

        _msg0, plan0, kwargs0, keep, _pruned0 = entries[0]
        member_parents = {}     # member_id -> parent_token
        members = []            # (member_id, plan, deadline)
        opened = []
        try:
            for msg, plan, _kwargs, _keep, pruned in entries:
                self.counters["plan_pruned_shards"] += len(pruned)
                parent_token = self._open_query_segment(msg, plan, pruned)
                opened.append(parent_token)
                member_id = os.urandom(6).hex()
                member_parents[member_id] = parent_token
                members.append((member_id, plan, msg.get("deadline")))
            groupby_cols = list(plan0.groupby.keys)
            agg_list0 = plan0.physical_agg_list()
            parents = [member_parents[m[0]] for m in members]
            # the bundle envelope's deadline is the LAST member's (its
            # expiry implies every member's); per-member deadlines ride the
            # fragment and are enforced per member on the worker
            deadlines = [m[2] for m in members]
            bundle_deadline = (
                max(deadlines)
                if deadlines and all(d is not None for d in deadlines)
                else None
            )
            sole = len(keep) == 1
            affinity = kwargs0.get("affinity")
            for group in self._shard_groups(
                keep, groupby_cols, agg_list0, kwargs0
            ):
                target = group if len(group) > 1 else group[0]
                # no per-bundle strategy selection: the shared-scan kernel
                # always runs its own batched/auto family (the hint could
                # only ever reach the worker's rare per-member fallback),
                # so issuing calibrated hints here would inflate the
                # planner-hint counters with hints that structurally
                # cannot run
                strategy = None
                hint = "auto"
                for parent in parents:
                    segment = self.rpc_segments.get(parent)
                    if segment is not None:
                        segment["strategies"][hint] = (
                            segment["strategies"].get(hint, 0) + len(group)
                        )
                shard = CalcMessage({"payload": "groupby"})
                if sole:
                    shard["sole_shard"] = True
                # reference-shaped params carry the FIRST member's query so
                # _split_batch re-splitting keeps working; the bundle
                # fragment is authoritative on capable workers (MIGRATION:
                # enable the window only on >=PR-9 fleets)
                shard.set_args_kwargs(
                    [target, groupby_cols, agg_list0,
                     [list(t) for t in plan0.where_terms]],
                    {},
                )
                shard["token"] = os.urandom(8).hex()
                shard["parent_token"] = parents[0]
                shard["filename"] = target
                shard["affinity"] = affinity
                obs_state = (
                    self.rpc_segments.get(parents[0], {}).get("obs") or {}
                )
                if obs_state:
                    shard.set_trace(
                        {
                            "trace_id": obs_state["trace_id"],
                            "span_id": os.urandom(8).hex(),
                            "parent_span_id": obs_state["qspan_id"],
                        }
                    )
                    shard["_dispatch_queued_ts"] = time.time()
                if bundle_deadline is not None:
                    shard["deadline"] = bundle_deadline
                shard.add_as_binary(
                    "bundle",
                    bundlemod.bundle_fragment(
                        plan0, group, members, strategy=strategy, sole=sole
                    ),
                )
                shard["_bundle_parents"] = dict(member_parents)
                self._register_work(shard, parents)
                self.counters["plan_bundles"] += 1
                self.counters["plan_bundled_queries"] += len(members)
                # every member beyond the first shares a dispatch it would
                # otherwise have paid for itself — the same meaning the
                # identical-work dedup counter always had
                self.counters["plan_shared_dispatches"] += len(members) - 1
                self.worker_out_messages.setdefault(affinity, []).append(
                    shard
                )
        except Exception:
            for parent in opened:
                self.abort_parent(parent, "bundle launch failed", reply=False)
            raise

    def _dispatch_plan(self, msg, plan, kwargs, parent_token, keep):
        from bqueryd_tpu import plan as planmod

        affinity = kwargs.get("affinity")
        planner_on = planmod.planner_enabled()
        # operator-DAG dispatch (rpc.query): the wire DAG rides every
        # CalcMessage under the `dag` binary key; calibrated strategy
        # hints are skipped — the DAG executor routes its own kernels, so
        # issuing hints here would inflate the planner-hint counters with
        # hints that structurally cannot run (same reasoning as bundles)
        dag_wire = kwargs.get("dag")
        dag_blob = None
        if dag_wire is not None:
            planner_on = False
            # encode ONCE: the wire DAG carries the whole broadcast
            # dimension table, and re-pickling it per shard group would
            # put O(groups x table_bytes) on the dispatch hot path
            dag_blob = base64.b64encode(
                pickle.dumps(dag_wire, protocol=messages.PICKLE_PROTOCOL)
            ).decode("ascii")
        groupby_cols = list(plan.groupby.keys)
        agg_list = plan.physical_agg_list()
        where_terms = plan.where_terms
        # single-shard queries produce exactly one payload with no merge
        # downstream: workers may finalize representation-heavy aggregations
        # (count_distinct) on device instead of shipping mergeable sets
        sole = len(keep) == 1 and plan.aggregate_rows
        plan_sig = plan.signature()  # group-invariant: computed once
        for group in self._shard_groups(
            keep, groupby_cols, agg_list, kwargs
        ):
            target = group if len(group) > 1 else group[0]
            # cost-based kernel-strategy selection from advertised stats,
            # refined by measured kernel walls when the calibration model is
            # warm (plan.calibrate; cold buckets are bit-identical to the
            # heuristic); "auto" stays the static default
            strategy = None
            if planner_on:
                strategy, _est, _rows, reason = planmod.select_calibrated(
                    self.shard_stats, group, groupby_cols,
                    calibration=self.calibration,
                )
                if strategy == planmod.STRATEGY_AUTO:
                    strategy = None
                else:
                    self.counters["plan_strategy_hints"] += 1
                if reason == "measured":
                    self.counters["plan_calibrated_overrides"] += 1
                elif reason == "explore":
                    self.counters["plan_explore_hints"] += 1
                if strategy == planmod.STRATEGY_MATMUL_BINDING:
                    self.counters["plan_matmul_promotions"] += 1
            segment = self.rpc_segments.get(parent_token)
            if segment is not None:
                hint = strategy or "auto"
                segment["strategies"][hint] = (
                    segment["strategies"].get(hint, 0) + len(group)
                )
            # multi-query batching: identical pending work is joined, not
            # re-dispatched.  The deadline is part of the identity: fusing
            # across deadlines would let one client's budget expire (or
            # never enforce) another client's work.  So is affinity: fusing
            # across pins would silently run a pinned query elsewhere
            work_key = (
                tuple(group), plan_sig, sole, msg.get("deadline"), affinity,
            )
            existing = self._work_index.get(work_key)
            if existing is not None and existing in self._work_subscribers:
                self._work_subscribers[existing].append(parent_token)
                self.counters["plan_shared_dispatches"] += 1
                continue

            shard = CalcMessage({"payload": "groupby"})
            if sole:
                shard["sole_shard"] = True
            shard.set_args_kwargs(
                [target, groupby_cols, agg_list, where_terms],
                {
                    k: v
                    for k, v in kwargs.items()
                    if k in ("aggregate", "expand_filter_column")
                },
            )
            shard["token"] = os.urandom(8).hex()
            shard["parent_token"] = parent_token
            shard["filename"] = target
            shard["affinity"] = affinity
            # per-dispatch trace hop: the worker parents its "calc" span to
            # this dispatch span id; the span itself is recorded at send
            # time (queue wait + routing), see _send_to_worker
            obs_state = (
                self.rpc_segments.get(parent_token, {}).get("obs") or {}
            )
            if obs_state:
                shard.set_trace(
                    {
                        "trace_id": obs_state["trace_id"],
                        "span_id": os.urandom(8).hex(),
                        "parent_span_id": obs_state["qspan_id"],
                    }
                )
                shard["_dispatch_queued_ts"] = time.time()
            if msg.get("deadline") is not None:
                shard["deadline"] = msg["deadline"]
            shard.add_as_binary(
                "plan",
                planmod.fragment_for(
                    plan, group, strategy=strategy, sole=sole
                ),
            )
            if dag_blob is not None:
                # capable workers execute the DAG; pre-DAG workers fall
                # back to the positional params, whose extended op strings
                # they reject — process_worker_result rewrites that
                # rejection into the structured mixed-version error
                # (MIGRATION "PR 13")
                shard["dag"] = dag_blob
            self._register_work(shard, [parent_token], work_key=work_key)
            self.worker_out_messages.setdefault(affinity, []).append(shard)

    def _shard_groups(self, filenames, groupby_cols, agg_list, kwargs):
        """Partition the requested shard files into dispatch groups.

        Shards sharing an identical advertising-worker set are batched into
        ONE CalcMessage so the worker merges them on its device mesh with a
        psum instead of the controller collecting N serialized partials —
        the core TPU redesign of the reference's per-shard fan-out
        (reference bqueryd/controller.py:494-506).  Batching applies to
        device-mergeable part kinds: the psum-mergeable classic ops, plus —
        for DAG dispatches (``kwargs["dag"]``, whose ``batch`` flag
        ``plan.dag.groupby_equivalent`` already gates on the part kinds and
        the ``BQUERYD_TPU_DAG_BATCH`` kill switch) — the extended top-k /
        quantile-sketch ops the worker's mesh fast path merges on device.
        Distinct-count and raw-rows queries keep per-shard dispatch.
        ``batch=False`` forces the reference's one-message-per-shard
        behaviour (finer retry granularity).
        """
        from bqueryd_tpu.models.query import MERGEABLE_OPS, GroupByQuery
        from bqueryd_tpu.plan.dag import is_extended_op

        probe = GroupByQuery(
            groupby_cols, agg_list, aggregate=kwargs.get("aggregate", True)
        )
        from bqueryd_tpu.parallel import devicemerge

        dag_riding = kwargs.get("dag") is not None
        batchable = (
            kwargs.get("batch", True)
            and probe.aggregate
            and all(
                op in MERGEABLE_OPS
                or (dag_riding and is_extended_op(op))
                for op in probe.ops
            )
            # BQUERYD_TPU_DEVICE_MERGE=0: the merge stays host-side end to
            # end — per-shard dispatch so every shard's partial table rides
            # the wire and merges via hostmerge (the measurable host-gather
            # baseline the device-resident merge is judged against)
            and devicemerge.device_merge_enabled()
        )
        if not batchable:
            return [[f] for f in filenames]
        groups = {}
        for f in filenames:
            placement = tuple(sorted(self.files_map.get(f, ())))
            groups.setdefault(placement, []).append(f)
        return list(groups.values())
