"""CLI entry point: ``bqueryd-tpu [controller|worker|downloader|movebcolz]``.

Capability match for the reference CLI (reference bqueryd/node.py:14-47):
role subcommands start daemons; with no subcommand an interactive shell opens
with an ``rpc`` proxy connected to the cluster (IPython when available).
Config comes from ``/etc/bqueryd_tpu.cfg`` (simple ``key = value`` lines:
``coordination_url`` / ``redis_url``, ``azure_conn_string``), overridable by
flags.
"""

import argparse
import logging
import os
import sys

CONFIG_FILE = os.environ.get("BQUERYD_TPU_CFG", "/etc/bqueryd_tpu.cfg")


def read_config(path=CONFIG_FILE):
    config = {}
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                key, _, value = line.partition("=")
                config[key.strip()] = value.strip().strip("'\"")
    return config


def main(argv=None):
    parser = argparse.ArgumentParser(prog="bqueryd-tpu")
    parser.add_argument(
        "role",
        nargs="?",
        choices=["controller", "worker", "downloader", "movebcolz", "import"],
        help=(
            "daemon role, or 'import <src> <dst>' to convert a legacy "
            "bcolz v1 rootdir; omit for an interactive RPC shell"
        ),
    )
    parser.add_argument(
        "address",
        nargs="?",
        help=(
            "controller address for the RPC shell (tcp://ip:port); "
            "source rootdir for 'import'"
        ),
    )
    parser.add_argument(
        "dest",
        nargs="?",
        help="destination rootdir for 'import'",
    )
    parser.add_argument("--data_dir", default=None)
    parser.add_argument(
        "--coordination",
        default=None,
        help="coordination store url (redis:// | mem:// | file://)",
    )
    parser.add_argument(
        "--metrics_port",
        default=None,
        type=int,
        help=(
            "serve Prometheus /metrics (+ /healthz) on this port "
            "(0 = ephemeral; default off — same as BQUERYD_TPU_METRICS_PORT)"
        ),
    )
    parser.add_argument(
        "--log_json",
        action="store_true",
        help=(
            "structured JSON log lines with trace_id/query_id correlation "
            "(same as BQUERYD_TPU_LOG_JSON=1)"
        ),
    )
    parser.add_argument("-v", action="count", default=0, help="-v/-vv for debug")
    args = parser.parse_args(argv)
    # flags translate to the env knobs the node constructors read, so
    # supervisor/systemd configs and ad-hoc CLI runs configure identically
    if args.metrics_port is not None:
        os.environ["BQUERYD_TPU_METRICS_PORT"] = str(args.metrics_port)
    if args.log_json:
        os.environ["BQUERYD_TPU_LOG_JSON"] = "1"

    config = read_config()
    coordination_url = (
        args.coordination
        or os.environ.get("BQUERYD_TPU_COORDINATION_URL")
        or config.get("coordination_url")
        or config.get("redis_url")
    )
    if config.get("azure_conn_string"):
        os.environ.setdefault(
            "AZURE_STORAGE_CONNECTION_STRING", config["azure_conn_string"]
        )
    loglevel = logging.DEBUG if args.v else logging.INFO

    kwargs = {"coordination_url": coordination_url, "loglevel": loglevel}

    if args.role == "import":
        if not args.address or not args.dest:
            parser.error("import needs <src.bcolz> <dst.bcolz>")
        from bqueryd_tpu.storage.bcolz_v1 import import_ctable, is_ctable_dir

        if not is_ctable_dir(args.address):
            parser.error(
                f"{args.address} is not a bcolz v1 ctable rootdir "
                "(no carray column subdirectories found)"
            )
        rows = import_ctable(args.address, args.dest)
        print(f"imported {rows} rows: {args.address} -> {args.dest}")
    elif args.role == "controller":
        from bqueryd_tpu.controller import ControllerNode

        ControllerNode(**kwargs).go()
    elif args.role in ("worker", "downloader", "movebcolz"):
        from bqueryd_tpu.worker import DownloaderNode, MoveBcolzNode, WorkerNode

        cls = {
            "worker": WorkerNode,
            "downloader": DownloaderNode,
            "movebcolz": MoveBcolzNode,
        }[args.role]
        if args.data_dir:
            kwargs["data_dir"] = args.data_dir
        cls(**kwargs).go()
    else:
        shell(args.address, coordination_url, loglevel)
    return 0


def shell(address, coordination_url, loglevel):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(
        address=address, coordination_url=coordination_url, loglevel=loglevel
    )
    banner = (
        f"bqueryd-tpu shell connected to {rpc.address}\n"
        "use rpc.<verb>(...): info, groupby, download, sleep, killworkers, ..."
    )
    try:
        import IPython

        IPython.embed(banner1=banner, user_ns={"rpc": rpc})
    except ImportError:
        import code

        code.interact(banner=banner, local={"rpc": rpc})


if __name__ == "__main__":
    sys.exit(main())
