"""Shared retry pacing: capped exponential backoff with deterministic jitter.

One formula for every retry loop in the stack — the RPC client's call
retries (timeouts, reconnects, BUSY backpressure) and the controller's
failover dispatch attempts — so tuning the envelope changes both sides
together instead of silently desynchronizing them.

The jitter is **deterministic**: keyed on a caller-supplied seed (socket
identity, work token) via crc32, so a thundering herd of retrying peers
de-stampedes the same way on every run and chaos scenarios replay
bit-for-bit.  Stdlib only; importable everywhere (including the jax-free
controller).
"""

import zlib

#: default envelope: base * 2^exponent, capped
BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 2.0


def backoff_delay(exponent, seed_key, base=BACKOFF_BASE_S, cap=BACKOFF_CAP_S):
    """Delay before the attempt after ``exponent`` failures: ``base *
    2^exponent`` capped at ``cap``, stretched by up to 25% keyed on
    ``seed_key`` — stable across re-runs, distinct across keys."""
    delay = min(base * (2 ** exponent), cap)
    jitter = (zlib.crc32(str(seed_key).encode()) % 256) / 1024.0  # [0, 0.25)
    return delay * (1.0 + jitter)
