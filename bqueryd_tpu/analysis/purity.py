"""jit-boundary purity lint over ``ops/`` and ``parallel/executor.py``.

A jitted function body runs at TRACE time: host-side effects inside it
either burn at every retrace (``time.*``, ``os.environ``), silently bake a
stale value into the compiled program, or — the expensive class — force a
retrace/recompile per call (Python branching on tracer values, host
coercion of tracers, unhashable static arguments).  PR 3's compile-profile
counters measure these as jit-cache misses after the fact; this lint
catches the patterns before they ship.

Detection is lexical over the jit boundary the code actually declares:

* functions decorated ``@jax.jit`` / ``@functools.partial(jax.jit,
  static_argnames=(...))`` (the package's idiom), and module-level
  ``name = jax.jit(fn)`` bindings;
* a parameter named in ``static_argnames`` is compile-time constant —
  branching on it, coercing it, and numpy over it are all fine; everything
  else is treated as traced.

Rules (scoped, so the shipped tree is clean without blanket suppressions):

* ``jit-impure-time`` / ``jit-impure-env`` — ``time.*`` call or environment
  access inside a jit body;
* ``jit-host-numpy`` — a ``np.*`` call applied directly to a traced
  parameter (forces device->host sync, breaks under tracing);
* ``jit-traced-coerce`` — ``float()/int()/bool()`` or ``.item()`` on a
  traced parameter (ConcretizationTypeError under jit, silent sync via
  ``__jax_array__`` otherwise);
* ``jit-traced-branch`` — ``if``/``while`` whose test references a traced
  parameter (``is None``/``is not None`` structure checks excluded: those
  are static pytree structure, the package's ``mask=None`` idiom);
* ``jit-nonhashable-static`` — a call site passing a list/dict/set literal
  for a ``static_argnames`` parameter of a jitted function defined in the
  same module (unhashable static arg: TypeError at best, per-call recompile
  via a hashable-but-fresh wrapper at worst);
* ``jit-lru-closure`` — ``functools.lru_cache`` on a function nested inside
  another function: the cache keys on the closure's captured objects'
  identity, pinning arrays alive and missing on every fresh closure;
* ``jit-uninstrumented`` — a module-level jitted entry point never wrapped
  with the compile profiler's ``instrument()`` in its module: its compiles
  and cache misses would be invisible to the PR 3 counters this lint is
  cross-checked against.
"""

import ast
import os

from bqueryd_tpu.analysis.core import Finding

#: the jit boundary lives in the kernel layer; control-plane modules don't
#: jit and would only add noise
SCOPE_DIRS = ("ops",)
SCOPE_FILES = ("parallel/executor.py",)


def in_scope(relpath, package):
    rel = relpath.split("/", 1)[1] if "/" in relpath else relpath
    head = rel.split("/", 1)[0]
    return head in SCOPE_DIRS or rel in SCOPE_FILES


def _is_jax_jit(node):
    """True for ``jax.jit`` / bare ``jit`` attribute or name."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return isinstance(node, ast.Name) and node.id == "jit"


def _static_spec_from_keywords(keywords):
    """Raw static spec from jit keywords: strings from ``static_argnames``,
    ints from ``static_argnums`` (resolved to names by the caller, which
    holds the FunctionDef)."""
    spec = []
    for kw in keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            try:
                value = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(value, (str, int)):
                value = (value,)
            spec.extend(v for v in value if isinstance(v, (str, int)))
    return tuple(spec)


def _partial_jit_static_spec(call):
    """For ``functools.partial(jax.jit, static_arg*=...)`` return the raw
    static spec tuple (possibly empty); None if not a jit partial."""
    func = call.func
    is_partial = (
        isinstance(func, ast.Attribute) and func.attr == "partial"
    ) or (isinstance(func, ast.Name) and func.id == "partial")
    if not (is_partial and call.args and _is_jax_jit(call.args[0])):
        return None
    return _static_spec_from_keywords(call.keywords)


def _jit_decoration(func_def):
    """``(is_jitted, static_names)`` from a FunctionDef's decorators —
    ``static_argnums`` indices are resolved against the positional
    parameter list so positionally-static params are never misread as
    traced."""
    arg_names = [a.arg for a in func_def.args.args]

    def resolve(spec):
        names = []
        for entry in spec:
            if isinstance(entry, int):
                if 0 <= entry < len(arg_names):
                    names.append(arg_names[entry])
            else:
                names.append(entry)
        return tuple(names)

    for dec in func_def.decorator_list:
        if _is_jax_jit(dec):
            return True, ()
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return True, resolve(
                    _static_spec_from_keywords(dec.keywords)
                )
            spec = _partial_jit_static_spec(dec)
            if spec is not None:
                return True, resolve(spec)
    return False, ()


class _JitBodyChecker(ast.NodeVisitor):
    def __init__(self, relpath, func_name, traced_params):
        self.relpath = relpath
        self.func_name = func_name
        self.traced = traced_params
        self.findings = []

    def _finding(self, rule, node, message, anchor):
        self.findings.append(Finding(
            rule, self.relpath, node.lineno,
            f"in jitted {self.func_name}: {message}",
            symbol=f"{self.func_name}.{anchor}",
        ))

    def _is_traced_name(self, node):
        return isinstance(node, ast.Name) and node.id in self.traced

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            root = func.value
            if isinstance(root, ast.Name):
                if root.id == "time":
                    self._finding(
                        "jit-impure-time", node,
                        f"time.{func.attr}() runs at trace time and bakes "
                        "a constant into the compiled program",
                        f"time.{func.attr}",
                    )
                elif root.id == "np" and any(
                    self._is_traced_name(a) for a in node.args
                ):
                    self._finding(
                        "jit-host-numpy", node,
                        f"np.{func.attr}() applied to a traced argument "
                        "forces host transfer / fails under trace",
                        f"np.{func.attr}",
                    )
                elif root.id == "os" and func.attr == "getenv":
                    self._finding(
                        "jit-impure-env", node,
                        "os.getenv() read at trace time: recompiles won't "
                        "see changed values, calls won't re-read it",
                        "os.getenv",
                    )
            if func.attr == "item" and (
                self._is_traced_name(func.value)
            ):
                self._finding(
                    "jit-traced-coerce", node,
                    f"{func.value.id}.item() concretizes a tracer "
                    "(device sync / ConcretizationTypeError)",
                    f"{func.value.id}.item",
                )
        elif isinstance(func, ast.Name):
            if func.id in ("float", "int", "bool") and node.args and (
                self._is_traced_name(node.args[0])
            ):
                self._finding(
                    "jit-traced-coerce", node,
                    f"{func.id}({node.args[0].id}) coerces a traced "
                    "argument to a host scalar",
                    f"{func.id}.{node.args[0].id}",
                )
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if (
            node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ):
            self._finding(
                "jit-impure-env", node,
                "os.environ read at trace time: the compiled program "
                "latches whatever the value was at first trace",
                "os.environ",
            )
        self.generic_visit(node)

    def _check_branch(self, node, kind):
        test = node.test
        # `x is None` / `x is not None` on a traced arg is STATIC pytree
        # structure (the mask=None idiom), not a tracer branch
        if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return
        for sub in ast.walk(test):
            if self._is_traced_name(sub):
                self._finding(
                    "jit-traced-branch", node,
                    f"{kind} branches on traced argument "
                    f"{sub.id!r}: concretization error under jit, or a "
                    "silent retrace per distinct value",
                    f"{kind}.{sub.id}",
                )
                return

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # nested defs: params shadow the outer traced names
        inner_params = {a.arg for a in node.args.args}
        outer = self.traced
        self.traced = self.traced - inner_params
        self.generic_visit(node)
        self.traced = outer


def _is_lru_cache_decorator(dec):
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Attribute):
        return target.attr == "lru_cache"
    return isinstance(target, ast.Name) and target.id == "lru_cache"


class JitPurityAnalyzer:
    name = "jit-purity"

    RULES = {
        "jit-impure-time": "time.* call inside a jitted body",
        "jit-impure-env": "environment access inside a jitted body",
        "jit-host-numpy": "host numpy applied to a traced argument",
        "jit-traced-coerce": "host scalar coercion of a traced argument",
        "jit-traced-branch": "Python branch on a traced argument",
        "jit-nonhashable-static":
            "list/dict/set literal passed for a static_argnames parameter",
        "jit-lru-closure":
            "functools.lru_cache on a closure (cache keyed on captured "
            "object identity; pins arrays)",
        "jit-uninstrumented":
            "module-level jitted entry point not wrapped with the compile "
            "profiler's instrument()",
    }

    def run(self, project):
        findings = []
        for sf in project.files:
            if sf.tree is None or not in_scope(sf.relpath, project.package):
                continue
            findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf):
        findings = []
        jitted = {}      # name -> static names (module-level jit defs)
        instrumented = set()
        module_name = os.path.basename(sf.relpath)

        for node in ast.walk(sf.tree):
            # name = <...>.instrument("label", fn) marks fn (and the bound
            # name) as visible to the compile-profile counters
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "instrument":
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        instrumented.add(arg.id)
                    elif isinstance(arg, ast.Call):
                        # instrument("label", jax.jit(fn))
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Name):
                                instrumented.add(sub.id)

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef):
                is_jitted, static = _jit_decoration(node)
                if is_jitted:
                    jitted[node.name] = set(static)
                    params = {a.arg for a in node.args.args}
                    checker = _JitBodyChecker(
                        sf.relpath, node.name, params - set(static)
                    )
                    for stmt in node.body:
                        checker.visit(stmt)
                    findings.extend(checker.findings)
                for dec in node.decorator_list:
                    if _is_lru_cache_decorator(dec) and self._is_nested(
                        sf.tree, node
                    ):
                        findings.append(Finding(
                            "jit-lru-closure", sf.relpath, node.lineno,
                            f"lru_cache on nested function {node.name!r}: "
                            "the cache outlives the closure and keys on "
                            "captured identity",
                            symbol=node.name,
                        ))
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                # name = jax.jit(fn) at module level
                call = node.value
                if _is_jax_jit(call.func) and len(node.targets) == 1 and (
                    isinstance(node.targets[0], ast.Name)
                ):
                    jitted.setdefault(node.targets[0].id, set())

        # call-site check: literal unhashables into static args
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                continue
            static = jitted[node.func.id]
            for kw in node.keywords:
                if kw.arg in static and isinstance(
                    kw.value, (ast.List, ast.Dict, ast.Set)
                ):
                    findings.append(Finding(
                        "jit-nonhashable-static", sf.relpath, kw.value.lineno,
                        f"call to {node.func.id} passes a "
                        f"{type(kw.value).__name__.lower()} literal for "
                        f"static arg {kw.arg!r} — unhashable static args "
                        "break the jit cache key",
                        symbol=f"{node.func.id}.{kw.arg}",
                    ))

        # compile-profile coverage: every module-level jitted entry point
        # must be instrumented somewhere in its module
        for name in sorted(jitted):
            if name not in instrumented:
                findings.append(Finding(
                    "jit-uninstrumented", sf.relpath, 0,
                    f"jitted entry point {name!r} in {module_name} is never "
                    "wrapped with profile.instrument(): its compiles are "
                    "invisible to the compile-profile counters",
                    symbol=name,
                ))
        return findings

    @staticmethod
    def _is_nested(tree, func_def):
        """True when ``func_def`` is defined inside another function."""
        class Finder(ast.NodeVisitor):
            def __init__(self):
                self.nested = False
                self._stack = 0

            def visit_FunctionDef(self, node):
                if node is func_def:
                    if self._stack > 0:
                        self.nested = True
                    return
                self._stack += 1
                self.generic_visit(node)
                self._stack -= 1

            visit_AsyncFunctionDef = visit_FunctionDef

        finder = Finder()
        finder.visit(tree)
        return finder.nested
