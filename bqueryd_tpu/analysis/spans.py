"""Span-coverage guard: literal span sites vs the schema in ``messages.py``.

A query autopsy (``rpc.autopsy``) is only as complete as its span taxonomy:
a new dispatch path that opens a ``timer.phase("...")`` or records a
``make_span(...)`` under an undeclared name ships latency that the
attribution sweep can only bucket as ``unattributed`` — silently eroding
the >= 95% coverage contract the bench gates on.  Wire-lint style, this
analyzer extracts every literal SPAN SITE in the package and diffs it
against two declared truths:

* ``messages.SPAN_SCHEMA`` — every span/phase name that may appear on a
  trace timeline (``span-undeclared-name`` / ``span-dead-name``);
* ``obs.slo.SPAN_CATEGORIES`` — the attribution map: every PUBLIC span
  name (raw PhaseTimer names resolve through ``obs.trace.PHASE_SPAN_NAMES``
  first) must map to a segment (``span-unattributed-name``), and every
  segment — mapped or synthetic (``obs.slo.SYNTHETIC_SEGMENTS``) — must
  rank in ``SEGMENT_PRIORITY`` (``span-unranked-segment``: an unranked
  segment silently falls back to dispatch priority in the sweep).

Span sites are: ``<x>.phase("name")`` / ``<x>._phase("name")`` /
``<x>.span("name")`` (PhaseTimer / SpanRecorder context managers),
``make_span(trace_id, "name", ...)`` (second positional), and
``SpanRecorder(root_name="name")``.  Non-literal names are fine — they can
only re-emit already-declared names (the generic passthroughs in
PhaseTimer/QueryEngine).  ``pipeline.stage(...)`` is NOT a span site (stage
clocks are worker-local gauges, never timeline spans).
"""

import ast

from bqueryd_tpu.analysis.core import Finding, module_literal

#: method names whose first literal argument opens a span/phase
_PHASE_ATTRS = ("phase", "_phase", "span")


def _literal_dict(tree, name):
    """A module-level ``name = {...literal...}`` from a parsed tree."""
    value = module_literal(tree, name)
    return value if isinstance(value, dict) else None


def _literal_tuple(tree, name):
    value = module_literal(tree, name)
    return tuple(value) if isinstance(value, (tuple, list)) else None


class _SpanSiteVisitor(ast.NodeVisitor):
    def __init__(self):
        self.sites = {}   # name -> [lineno, ...]

    def _mark(self, node, lineno):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            self.sites.setdefault(node.value, []).append(lineno)

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _PHASE_ATTRS:
            if node.args:
                self._mark(node.args[0], node.lineno)
        name = (
            func.id if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute) else None
        )
        if name == "make_span" and len(node.args) >= 2:
            self._mark(node.args[1], node.lineno)
        if name == "SpanRecorder":
            for kw in node.keywords:
                if kw.arg == "root_name":
                    self._mark(kw.value, node.lineno)
        self.generic_visit(node)


class SpanSchemaAnalyzer:
    name = "span-schema"

    RULES = {
        "span-undeclared-name":
            "a literal span/phase site uses a name not declared in "
            "messages.SPAN_SCHEMA",
        "span-unattributed-name":
            "a declared span name (public form) has no segment in "
            "obs.slo.SPAN_CATEGORIES — rpc.autopsy would drop its time "
            "into 'unattributed'",
        "span-dead-name":
            "a declared span name with no span site anywhere and no "
            "PHASE_SPAN_NAMES mapping — dead schema entry",
        "span-unranked-segment":
            "a segment (SPAN_CATEGORIES value or SYNTHETIC_SEGMENTS "
            "entry) missing from SEGMENT_PRIORITY — the sweep would "
            "silently rank it at dispatch priority",
    }

    def _declared(self, project):
        """(SPAN_SCHEMA, PHASE_SPAN_NAMES, SPAN_CATEGORIES, SYNTHETIC,
        PRIORITY) read from the ANALYZED tree (same contract as the wire
        analyzer: a checkout diffs against its own schema), falling back to
        the live modules for synthetic test projects."""
        schema = phase_names = categories = synthetic = priority = None
        sf = project.file(f"{project.package}/messages.py")
        if sf is not None and sf.tree is not None:
            schema = _literal_dict(sf.tree, "SPAN_SCHEMA")
        sf = project.file(f"{project.package}/obs/trace.py")
        if sf is not None and sf.tree is not None:
            phase_names = _literal_dict(sf.tree, "PHASE_SPAN_NAMES")
        sf = project.file(f"{project.package}/obs/slo.py")
        if sf is not None and sf.tree is not None:
            categories = _literal_dict(sf.tree, "SPAN_CATEGORIES")
            synthetic = _literal_tuple(sf.tree, "SYNTHETIC_SEGMENTS")
            priority = _literal_tuple(sf.tree, "SEGMENT_PRIORITY")
        if schema is None or phase_names is None or categories is None:
            from bqueryd_tpu import messages
            from bqueryd_tpu.obs import slo, trace

            schema = schema if schema is not None else dict(
                getattr(messages, "SPAN_SCHEMA", {})
            )
            phase_names = phase_names if phase_names is not None else dict(
                trace.PHASE_SPAN_NAMES
            )
            categories = categories if categories is not None else dict(
                slo.SPAN_CATEGORIES
            )
            if synthetic is None:
                synthetic = tuple(slo.SYNTHETIC_SEGMENTS)
            if priority is None:
                priority = tuple(slo.SEGMENT_PRIORITY)
        return (
            schema, phase_names, categories,
            tuple(synthetic or ()), tuple(priority or ()),
        )

    def run(self, project):
        (
            schema, phase_names, categories, synthetic, priority,
        ) = self._declared(project)
        findings = []
        schema_file = f"{project.package}/messages.py"
        slo_file = f"{project.package}/obs/slo.py"

        sites = {}   # name -> [(path, line), ...]
        for sf in project.files:
            if sf.tree is None:
                continue
            visitor = _SpanSiteVisitor()
            visitor.visit(sf.tree)
            for name, linenos in visitor.sites.items():
                sites.setdefault(name, []).extend(
                    (sf.relpath, lineno) for lineno in linenos
                )

        for name in sorted(sites):
            if name not in schema:
                path, line = sites[name][0]
                findings.append(Finding(
                    "span-undeclared-name", path, line,
                    f"span/phase name {name!r} used at a span site but not "
                    "declared in messages.SPAN_SCHEMA",
                    symbol=name,
                ))

        for name in sorted(schema):
            public = phase_names.get(name, name)
            if public not in categories:
                findings.append(Finding(
                    "span-unattributed-name", slo_file, 0,
                    f"declared span name {name!r} (public {public!r}) has "
                    "no segment in obs.slo.SPAN_CATEGORIES — its time "
                    "would land in 'unattributed'",
                    symbol=name,
                ))
            used = name in sites or name in phase_names.values()
            if not used:
                findings.append(Finding(
                    "span-dead-name", schema_file, 0,
                    f"declared span name {name!r} has no span site in the "
                    "package and is not a PHASE_SPAN_NAMES mapping — dead "
                    "schema entry",
                    symbol=name,
                ))

        # every segment the sweep can produce must hold an explicit rank
        # ("unattributed" is the residue, never ranked); priority () means
        # the analyzed tree has no slo module — nothing to rank against
        if priority:
            segments = set(categories.values()) | {
                s for s in synthetic if s != "unattributed"
            }
            for segment in sorted(segments - set(priority)):
                findings.append(Finding(
                    "span-unranked-segment", slo_file, 0,
                    f"segment {segment!r} is produced by the attribution "
                    "map but missing from SEGMENT_PRIORITY — it would "
                    "silently rank at dispatch priority",
                    symbol=segment,
                ))
        return findings
