"""Runtime lock-order recorder: prove deadlock-freedom of the real paths.

The static checker (:mod:`bqueryd_tpu.analysis.concurrency`) proves each
shared object takes ITS lock; it cannot see ordering BETWEEN locks.  With
the pipeline pool live, one thread holding the metrics-registry lock while
touching a cache whose lock another thread holds while rendering metrics is
a classic ABBA deadlock — invisible until the interleaving lands in
production.

This module records instead of hoping: :class:`TrackedLock` wraps a real
``threading.Lock``; every successful acquisition while other tracked locks
are held adds a directed edge (held -> acquired) to a process-wide (per
recorder) graph, remembering the exact acquisition SITES of both ends.
After driving the real pipeline/worker code paths under instrumented locks,
:meth:`LockOrderRecorder.cycles` answers whether any ordering cycle — any
potential deadlock — was ever observable, and the report names both
acquisition sites of every edge so the fix is a file:line away.

Tests adopt real objects with :func:`instrument_object`, which swaps every
``threading.Lock`` attribute for a tracked wrapper in place.  Acquiring a
tracked lock a thread already holds raises immediately (``threading.Lock``
is non-reentrant: that interleaving is a guaranteed self-deadlock, better
surfaced as an exception with a stack than as a hung test).

Deliberately not installed in production paths: the recorder costs a stack
walk per acquisition.  It is a test-harness instrument, same tier as the
injected-fault fixtures.
"""

import threading
import traceback


class LockOrderError(RuntimeError):
    pass


def _call_site(skip_internal=True):
    """`file:line (function)` of the acquiring frame, skipping this module's
    own wrapper frames."""
    for frame in reversed(traceback.extract_stack()):
        if skip_internal and frame.filename == __file__:
            continue
        return f"{frame.filename}:{frame.lineno} ({frame.name})"
    return "<unknown>"


class TrackedLock:
    """``threading.Lock`` lookalike that reports acquisitions to a recorder.

    Supports the surface the package's classes use: ``acquire``/``release``,
    context manager, ``locked``.
    """

    def __init__(self, recorder, name, inner=None):
        self._recorder = recorder
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        self._recorder._before_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._recorder._acquired(self, _call_site())
        return ok

    def release(self):
        self._recorder._released(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TrackedLock {self.name}>"


class LockOrderRecorder:
    """Per-test acquisition graph with cycle detection (module docstring)."""

    def __init__(self):
        self._tls = threading.local()
        self._graph_lock = threading.Lock()
        # (held_name, acquired_name) -> (held_site, acquired_site): first
        # observation wins — one witness per edge keeps reports readable
        self._edges = {}
        self.acquisitions = 0

    # -- TrackedLock callbacks ----------------------------------------------
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _before_acquire(self, lock):
        for other, _site in self._held():
            if other is lock:
                raise LockOrderError(
                    f"self-deadlock: thread re-acquires non-reentrant "
                    f"{lock.name} already held (acquired at {_site}), "
                    f"re-acquired at {_call_site()}"
                )

    def _acquired(self, lock, site):
        held = self._held()
        with self._graph_lock:
            self.acquisitions += 1
            for other, other_site in held:
                self._edges.setdefault(
                    (other.name, lock.name), (other_site, site)
                )
        held.append((lock, site))

    def _released(self, lock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    # -- analysis ------------------------------------------------------------
    def edges(self):
        with self._graph_lock:
            return dict(self._edges)

    def cycles(self):
        """Every elementary cycle in the acquisition graph, as lists of lock
        names (each cycle reported once, from its lexically-smallest node)."""
        edges = self.edges()
        adjacency = {}
        for a, b in edges:
            adjacency.setdefault(a, set()).add(b)
        cycles = []
        seen = set()

        def dfs(start, node, path, on_path):
            for nxt in sorted(adjacency.get(node, ())):
                if nxt == start:
                    # dedup by the ORDERED path (DFS always starts a cycle
                    # at its smallest node): both orientations over the
                    # same lock set are distinct deadlock orderings and
                    # must both be reported with their witness sites
                    key = tuple(path)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(path))
                elif nxt > start and nxt not in on_path:
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(adjacency):
            dfs(start, start, [start], {start})
        return cycles

    def report(self):
        """Readable cycle report naming both acquisition sites of every edge
        in every cycle; empty string when the graph is acyclic."""
        cycles = self.cycles()
        if not cycles:
            return ""
        edges = self.edges()
        lines = []
        for cycle in cycles:
            lines.append(
                "lock-order cycle: " + " -> ".join(cycle + [cycle[0]])
            )
            ring = cycle + [cycle[0]]
            for a, b in zip(ring, ring[1:]):
                held_site, acq_site = edges[(a, b)]
                lines.append(
                    f"  {b} acquired at {acq_site}"
                    f" while holding {a} (acquired at {held_site})"
                )
        return "\n".join(lines)

    def assert_no_cycles(self):
        report = self.report()
        if report:
            raise LockOrderError(report)

    # -- adoption helpers ----------------------------------------------------
    def lock(self, name):
        """A fresh tracked lock (for fixtures and new objects)."""
        return TrackedLock(self, name)

    def instrument_object(self, obj, prefix=None):
        """Swap every plain ``threading.Lock`` attribute of ``obj`` for a
        tracked wrapper in place (the wrapper adopts the existing inner lock,
        so already-held locks keep working).  Returns the names wrapped."""
        prefix = prefix or type(obj).__name__
        lock_type = type(threading.Lock())
        wrapped = []
        for attr, value in sorted(vars(obj).items()):
            if isinstance(value, lock_type):
                setattr(
                    obj, attr,
                    TrackedLock(self, f"{prefix}.{attr}", inner=value),
                )
                wrapped.append(f"{prefix}.{attr}")
        return wrapped

    def instrument_module_lock(self, module, attr, prefix=None):
        """Swap a module-global lock (e.g. ``pipeline._pool_lock``); returns
        a zero-arg restore callable."""
        original = getattr(module, attr)
        name = f"{prefix or module.__name__}.{attr}"
        setattr(module, attr, TrackedLock(self, name, inner=original))

        def restore():
            setattr(module, attr, original)

        return restore
