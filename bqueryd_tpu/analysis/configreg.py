"""Config-registry lint: one typed table of every ``BQUERYD_TPU_*`` env var.

The config surface sprawled past forty env vars read ad-hoc across every
layer; nothing guaranteed a new var got documented, an old one got removed
from the README when its read site died, or that a "live-tunable" knob was
not actually latched at import time.  This module is the single source of
truth — :data:`ENV_REGISTRY` declares name, type, default (as the code
spells it), help, and read-time — and :class:`ConfigRegistryAnalyzer` is the
AST pass that keeps code, registry, and README from drifting:

* every ``os.environ`` / ``os.getenv`` touch of a ``BQUERYD_TPU_*`` key must
  name a registered var (``config-unregistered-env``);
* every registered var must appear in the README config table
  (``config-undocumented``) and every ``BQUERYD_TPU_*`` token in the README
  must be registered (``config-readme-unknown``);
* a registered var whose name appears nowhere in package source is dead
  (``config-dead-var``);
* a var declared ``read_time="call"`` (live-tunable) must not be read at
  module scope, where the value latches at import (``config-import-time-read``);
* reads of non-``BQUERYD_TPU_`` env vars must be in
  :data:`EXTERNAL_ENV_ALLOWED` (``config-external-env``) — the package must
  not silently grow dependencies on ambient environment;
* env reads with a non-literal key are opaque to all of the above and
  require an inline suppression explaining where the keys come from
  (``config-dynamic-env-key``);
* registered names where one extends the other (``FOO`` vs ``FOO_BARS``)
  must cross-reference via ``related=`` or they read as near-collisions —
  the ``TRACE_BUFFER`` (entries) vs ``TRACE_BUFFER_BYTES`` (bytes) class of
  confusion (``config-name-collision``).

Stdlib only.
"""

import ast
import re

from bqueryd_tpu.analysis.core import Finding

ENV_PREFIX = "BQUERYD_TPU_"

#: exact var-name tokens (substring matching would let BQUERYD_TPU_FOO hide
#: inside BQUERYD_TPU_FOO_BYTES — precisely the near-collision pairs this
#: module polices)
_TOKEN_RE = re.compile(r"BQUERYD_TPU_[A-Z0-9_]+")

#: reads of env vars owned by other systems (JAX, cloud SDKs, the machine
#: image) that the package legitimately consults; anything else non-BQUERYD
#: is a finding
EXTERNAL_ENV_ALLOWED = frozenset({
    "JAX_PLATFORMS",            # ops backend selection mirrors jax's own var
    "XLA_FLAGS",                # virtual-device test meshes
    "_AXON_REGISTERED",         # machine-image marker for the TPU tunnel
    "AZURE_STORAGE_CONNECTION_STRING",  # azure SDK's own credential var
    "JAX_COMPILATION_CACHE_DIR",        # jax's persistent-cache location
})

READ_IMPORT = "import"   # latched at module import; restart to change
READ_CALL = "call"       # re-read per use; live-tunable


class EnvVar:
    """One registered config var.  ``default`` is the human-readable default
    exactly as operators should understand it; ``related`` names registered
    vars this one is easily confused with (prefix extensions) and doubles as
    the near-collision waiver."""

    __slots__ = ("name", "type", "default", "help", "read_time", "related")

    def __init__(self, name, type, default, help, read_time=READ_CALL,
                 related=()):
        self.name = name
        self.type = type
        self.default = default
        self.help = help
        self.read_time = read_time
        self.related = tuple(related)


def _v(name, type, default, help, read_time=READ_CALL, related=()):
    return EnvVar(ENV_PREFIX + name, type, default, help, read_time,
                  tuple(ENV_PREFIX + r for r in related))


#: the central typed registry; ordering is the README config-table ordering
ENV_REGISTRY = {
    var.name: var
    for var in [
        _v("CFG", "path", "/etc/bqueryd_tpu.cfg",
           "config file path", READ_IMPORT),
        _v("COORDINATION_URL", "str", "redis://localhost:6379",
           "membership/tickets/locks store", READ_IMPORT),
        _v("DATA_DIR", "path", "/srv/bcolz/",
           "served shard directory", READ_IMPORT),
        _v("RUNFILE_DIR", "path", "/srv",
           "controller address/pid runfiles", READ_IMPORT),
        _v("IP", "str", "auto", "advertised IP override"),
        _v("PLATFORM", "str", "auto",
           "force a JAX platform (cpu, tpu)", READ_IMPORT),
        _v("MATMUL_GROUPS", "int", "8192",
           "MXU groupby path cardinality limit (0=off)"),
        _v("MATMUL_CELLS", "int", "2^36",
           "rows x groups budget for the MXU path"),
        _v("PALLAS", "flag", "0",
           "route the contraction through the Pallas kernels",
           related=("PALLAS_HICARD_GROUPS", "PALLAS_HICARD_GT",
                    "PALLAS_HICARD_KT")),
        _v("PALLAS_HICARD_GROUPS", "int", "2^18",
           "group-count ceiling of the hicard Pallas route",
           related=("PALLAS",)),
        _v("PALLAS_HICARD_GT", "int", "2048",
           "hicard kernel group-tile size (hardware sweeps)",
           related=("PALLAS",)),
        _v("PALLAS_HICARD_KT", "int", "512",
           "hicard kernel row-tile size (hardware sweeps)",
           related=("PALLAS",)),
        _v("DEVICE_PROBE_TIMEOUT_S", "float", "60",
           "wedge-latch deadline for backend liveness probes (0 disables)"),
        _v("DEVICE_PROBE_INTERVAL_S", "float", "30",
           "backend liveness probe cadence"),
        _v("HOST_KERNEL_ROWS", "int", "auto",
           "host-route queries below this many rows (0 = always device)"),
        _v("PACKED_FETCH", "flag", "1",
           "fetch merged results as one packed buffer",
           related=("DEVICE_MERGE",)),
        _v("DEVICE_MERGE", "flag", "1",
           "device-resident distributed merge over the mesh (0 = host-side "
           "hostmerge fallback + per-shard dispatch)",
           related=("PACKED_FETCH",)),
        _v("RESULT_CACHE_BYTES", "int", "256 MiB",
           "worker result cache (0=off)"),
        _v("PIPELINE_THREADS", "int", "min(16, cpu)",
           "shard-pipeline pool width (1 = fully serial stages)"),
        _v("HBM_CACHE_BYTES", "int", "1 GiB",
           "working-set blocks segment: device-resident measure columns"),
        _v("CODES_CACHE_BYTES", "int", "256 MiB",
           "working-set codes segment: device-resident folded group codes"),
        _v("ALIGN_CACHE_BYTES", "int", "512 MiB",
           "working-set align segment: host key alignment"),
        _v("HBM_EVICT_WATERMARK", "float", "0.9",
           "shed LRU device cache above this fraction of HBM bytes_limit"),
        _v("COLUMN_CACHE_BYTES", "int", "2 GiB",
           "decoded-column cache byte budget", READ_IMPORT),
        _v("NATIVE_LIB", "path", "auto", "path to libtpucolz.so"),
        _v("ENABLE_EXECUTE_CODE", "flag", "0",
           "allow the remote-execution verb"),
        _v("S3_ENDPOINT", "str", "-",
           "S3 endpoint override (localstack testing)"),
        _v("BLOB_DIR", "path", "-", "local-dir blob backend root (testing)"),
        _v("PROFILE", "flag", "0", "jax.profiler span annotations",
           related=("PROFILE_DIR",)),
        _v("PROFILE_DIR", "path", "-",
           "capture a TensorBoard trace around each query",
           related=("PROFILE",)),
        _v("DIST_COORDINATOR", "str", "-",
           "host:port to join a multi-host JAX job"),
        _v("DIST_NPROCS", "int", "auto",
           "multi-host process count off-TPU"),
        _v("DIST_PROC_ID", "int", "auto", "multi-host process id off-TPU"),
        _v("WARMUP", "flag", "1",
           "background JAX kernel warmup at worker start (0=off)"),
        _v("FACTORIZE_CACHE_BYTES", "int", "256 MiB",
           "per-column factorization cache"),
        _v("DISK_FACTOR_CACHE", "flag", "1",
           "persist factorizations/composites next to shards (0=off)"),
        _v("ALIGN_THREADS", "int", "auto",
           "shard-alignment concurrency cap (1=sequential)"),
        _v("COMPILE_CACHE", "str", "1",
           "persistent XLA compile cache (0=off, <path>=relocate)",
           READ_IMPORT),
        _v("SHAPE_BUCKETS", "flag", "1",
           "round program shapes onto a coarse grid (0=exact shapes)"),
        _v("DISTINCT_VALUES_LIMIT", "int", "5_000_000",
           "cap on shipped (group, value) pairs per count_distinct payload"),
        _v("TOPK_LIMIT", "int", "1024",
           "per-group k ceiling for DAG top-k operators (payload grows "
           "with k x groups x shards)",
           related=("JOIN_BROADCAST_LIMIT", "SKETCH_ALPHA")),
        _v("JOIN_BROADCAST_LIMIT", "int", "100_000",
           "max dimension-table rows a broadcast hash join ships per "
           "dispatch envelope (larger tables belong in shards)",
           related=("TOPK_LIMIT", "SKETCH_ALPHA")),
        _v("SKETCH_ALPHA", "float", "0.01",
           "default relative accuracy of DAG quantile sketches "
           "(DDSketch-style log buckets; estimate error <= alpha)",
           related=("TOPK_LIMIT", "JOIN_BROADCAST_LIMIT")),
        _v("DAG_BATCH", "flag", "1",
           "batched shard-group dispatch + device-resident merge for "
           "extended DAG queries (0 = PR-13 per-shard dispatch + host "
           "merge, bit-identical; the mixed-version fallback)",
           related=("DEVICE_MERGE", "SKETCH_GRID_CELLS")),
        _v("SKETCH_GRID_CELLS", "int", "2^23",
           "dense sketch-grid cell budget (padded groups x bucket width) "
           "for the DAG fast path's device merge; above it quantile "
           "queries fall back to the per-shard host merge",
           related=("DAG_BATCH", "SKETCH_ALPHA")),
        _v("DOWNLOAD_THREADS", "int", "3",
           "parallel blob fetches per downloader"),
        _v("INCOMING", "path", "data_dir/incoming",
           "download staging directory"),
        _v("FORCE_MATMUL", "flag", "0",
           "force the MXU one-hot path on CPU backends (tests)"),
        _v("PLANNER", "flag", "1",
           "plan-time shard pruning + kernel-strategy hints (0=static)"),
        _v("CALIB", "flag", "1",
           "measured-cost strategy calibration feeding the planner "
           "(0 = PR-5 heuristic hints exactly)",
           related=("CALIB_PATH", "CALIB_EPSILON", "CALIB_MIN_SAMPLES")),
        _v("CALIB_PATH", "path", "-",
           "persist worker calibration cells to this JSON file across "
           "restarts (- = in-memory only)",
           related=("CALIB",)),
        _v("CALIB_EPSILON", "float", "0.05",
           "bounded exploration rate: ~every 1/eps-th warm-bucket decision "
           "samples an unmeasured legal route (0 = off)",
           related=("CALIB",)),
        _v("CALIB_MIN_SAMPLES", "int", "3",
           "measured kernel walls a strategy cell needs before calibration "
           "trusts it",
           related=("CALIB",)),
        _v("BATCH_WINDOW_MS", "float", "0",
           "admission micro-batch window: hold admitted groupby plans this "
           "many ms so compatible concurrent queries fuse into one "
           "shared-scan bundle (0 = off, single-query behaviour)",
           related=("BATCH_MAX",)),
        _v("BATCH_MAX", "int", "16",
           "member-query cap per micro-batch flush (a full window flushes "
           "early)",
           related=("BATCH_WINDOW_MS",)),
        _v("ADMIT_MAX_ACTIVE", "int", "64",
           "concurrent executing plans before queueing"),
        _v("ADMIT_QUEUE_DEPTH", "int", "256",
           "admission wait-queue depth before BUSY"),
        _v("ADMIT_CLIENT_QUOTA", "int", "0",
           "max tickets per quota bucket (0 = unlimited)"),
        _v("SHARD_STATS", "flag", "1",
           "advertise per-shard planning stats in worker WRMs"),
        _v("METRICS", "flag", "1",
           "observability hot path: spans + histogram observes (0=off)",
           related=("METRICS_PORT",)),
        _v("METRICS_PORT", "int", "-",
           "serve Prometheus /metrics on this port (0 = ephemeral)",
           related=("METRICS",)),
        _v("TRACE_BUFFER", "int", "256",
           "ENTRY-COUNT cap: how many per-query trace timelines rpc.trace() "
           "retains (distinct from the _BYTES total-size cap)",
           related=("TRACE_BUFFER_BYTES",)),
        _v("TRACE_BUFFER_BYTES", "int", "16 MiB",
           "BYTE cap on the same trace ring: total retained timeline bytes, "
           "whichever of the two caps trips first evicts",
           related=("TRACE_BUFFER",)),
        _v("SLOW_QUERY_MS", "int", "1000",
           "slow-query log threshold (0 records everything)"),
        _v("SLOW_QUERY_BYTES", "int", "4 MiB",
           "byte cap on the slow-query ring"),
        _v("SLO_CLASSES", "str", "",
           "SLO class table: comma list of name:target_s[:objective] "
           "(e.g. interactive:0.5:0.999,batch:30); a default class "
           "(2 s, 0.99) always exists — clients pick theirs via "
           "RPC(slo_class=...)", read_time="import"),
        _v("TIMELINE_INTERVAL_S", "float", "10",
           "rpc.timeline() snapshot period in SECONDS (<=0 disables the "
           "ring; distinct from the _ENTRIES count cap)",
           related=("TIMELINE_ENTRIES",)),
        _v("TIMELINE_ENTRIES", "int", "360",
           "ENTRY-COUNT cap on the rpc.timeline() snapshot ring (newest "
           "kept; distinct from the _INTERVAL_S period)",
           read_time="import", related=("TIMELINE_INTERVAL_S",)),
        _v("CAPACITY", "flag", "1",
           "fleet capacity model: μ/λ/ρ accounting, saturation states and "
           "the shadow scaling advisor behind rpc.capacity() (0 = taps and "
           "evaluation off)",
           related=("CAPACITY_WINDOW_S", "CAPACITY_RHO_WARM",
                    "CAPACITY_RHO_SATURATED", "CAPACITY_HYSTERESIS_S",
                    "CAPACITY_TARGET_RHO")),
        _v("CAPACITY_WINDOW_S", "float", "60",
           "rolling window the capacity model's arrival/dispatch rates are "
           "measured over",
           related=("CAPACITY",)),
        _v("CAPACITY_RHO_WARM", "float", "0.5",
           "utilization at which a worker/fleet classifies warm "
           "(saturated and overloaded sit above; see _RHO_SATURATED)",
           related=("CAPACITY", "CAPACITY_RHO_SATURATED")),
        _v("CAPACITY_RHO_SATURATED", "float", "0.8",
           "utilization at which a worker/fleet classifies saturated "
           "(>= 1.0 is overloaded by definition, not a knob)",
           related=("CAPACITY", "CAPACITY_RHO_WARM")),
        _v("CAPACITY_HYSTERESIS_S", "float", "10",
           "a capacity state change must persist this many seconds before "
           "it takes (0 = flip immediately)",
           related=("CAPACITY",)),
        _v("CAPACITY_TARGET_RHO", "float", "0.7",
           "utilization the shadow advisor sizes the fleet for: scale_up "
           "asks for enough workers to return ρ here, scale_down sheds "
           "only what the target leaves headroom for",
           related=("CAPACITY",)),
        _v("LOG_JSON", "flag", "0",
           "structured JSON log lines with trace correlation ids"),
        _v("COMPILE_PROFILE", "flag", "1",
           "jit/compile accounting on instrumented entry points (0=off)"),
        _v("COST_ANALYSIS", "flag", "1",
           "host-side HLO cost analysis per new program shape (0=off)"),
        _v("FLIGHT_CAPACITY", "int", "512",
           "flight-ring entry cap per node"),
        _v("FLIGHT_BYTES", "int", "1 MiB", "flight-ring byte cap per node"),
        _v("HEALTH_ROUTING", "flag", "1",
           "dispatch deprioritizes degraded/wedged workers (0 = score only)"),
        _v("DEBUG_DIR", "path", "tmpdir",
           "where SIGUSR1 debug bundles are written"),
        _v("DEAD_WORKER_TIMEOUT", "float", "60",
           "cull workers silent longer than this many seconds",
           related=("DISPATCH_TIMEOUT", "DISPATCH_HARD_TIMEOUT")),
        _v("DISPATCH_TIMEOUT", "float", "120",
           "re-queue (fail over) in-flight shard work older than this many "
           "seconds when its worker stopped heartbeating",
           related=("DEAD_WORKER_TIMEOUT", "DISPATCH_HARD_TIMEOUT",
                    "MAX_DISPATCH_RETRIES")),
        _v("DISPATCH_HARD_TIMEOUT", "float", "1800",
           "re-queue in-flight shard work older than this many seconds even "
           "on a live, heartbeating worker (wedged-but-alive reclaim)",
           related=("DISPATCH_TIMEOUT", "DEAD_WORKER_TIMEOUT")),
        _v("MAX_DISPATCH_RETRIES", "int", "2",
           "failover attempts per shard before the query aborts with the "
           "structured DispatchExhausted envelope",
           related=("DISPATCH_TIMEOUT",)),
        _v("FAULT_PLAN", "str", "-",
           "arm deterministic fault injection: a FaultPlan JSON file path "
           "or inline JSON (bqueryd_tpu.chaos); unset = every injection "
           "site is a no-op"),
        _v("HEDGE_MS", "float", "0",
           "duplicate a tail shard still inflight past this many ms onto a "
           "second healthy holder, first reply wins (0 = hedging off)"),
        _v("REPLICA_FACTOR", "int", "0 (all nodes)",
           "placement hint: holders per shard — download fan-out targets "
           "this many nodes per file (0 = every node, the historical "
           "full fan-out); under-replicated shards surface in "
           "rpc.info()['replication'] (failover needs >=2)"),
        _v("APPEND", "flag", "1",
           "accept rpc.append on this worker (0 = reject streaming "
           "ingest with a structured error)",
           related=("DELTA_SERVE", "CHUNK_PRUNE")),
        _v("CHUNK_PRUNE", "flag", "1",
           "chunk-granular zone-map pruning: filtered queries decode only "
           "chunks whose per-chunk min/max can match (0 = whole-column "
           "decode, the pre-PR-14 path)",
           related=("CHUNK_PRUNE_SELECTIVITY", "APPEND")),
        _v("CHUNK_PRUNE_SELECTIVITY", "float", "0.9",
           "surviving-chunk fraction ABOVE which chunk pruning is skipped "
           "(near-full selections would fragment the content-keyed caches "
           "for no decode savings)",
           related=("CHUNK_PRUNE",)),
        _v("DELTA_SERVE", "flag", "1",
           "delta-maintained hot aggregates: a cached result whose tables "
           "only grew refreshes by aggregating the appended chunks alone "
           "and merging the delta partial (0 = full recompute on every "
           "append)",
           related=("DELTA_CACHE_BYTES", "APPEND")),
        _v("DELTA_CACHE_BYTES", "int", "128 MiB",
           "byte budget of the worker's delta-maintained aggregate cache",
           related=("DELTA_SERVE",)),
        _v("SERVE", "flag", "1",
           "semantic serving layer (PR 16): answer admitted queries from "
           "materialized rollups via plan subsumption (0 = exact-signature "
           "caches only, bit-identical to the pre-serving tree)",
           related=("ROLLUP_MAX", "ROLLUP_HEAT_MIN", "ROLLUP_CACHE_BYTES",
                    "DELTA_SERVE")),
        _v("ROLLUP_MAX", "int", "16",
           "max materialized rollup entries held controller-side",
           related=("SERVE", "ROLLUP_CACHE_BYTES")),
        _v("ROLLUP_HEAT_MIN", "float", "3.0",
           "decayed hit-score a plan view must reach before the controller "
           "materializes a rollup for it",
           related=("SERVE", "ROLLUP_HEAT_HALFLIFE_S")),
        _v("ROLLUP_HEAT_HALFLIFE_S", "float", "300",
           "half-life (seconds) of the rollup heat tracker's exponential "
           "decay",
           related=("ROLLUP_HEAT_MIN",)),
        _v("ROLLUP_CACHE_BYTES", "int", "256 MiB",
           "byte budget for stored rollup partials (least-recently-hit "
           "entries evicted past it)",
           related=("ROLLUP_MAX", "SERVE")),
    ]
}


def registry_markdown_rows():
    """``| name | default | help |`` rows in registry order — the generator
    behind the README config-reference table (the lint checks the README
    covers every name; this helper regenerates the table wholesale)."""
    rows = []
    for var in ENV_REGISTRY.values():
        live = "" if var.read_time == READ_CALL else " (restart required)"
        rows.append(f"| `{var.name}` | {var.default} | {var.help}{live} |")
    return rows


class _EnvReadVisitor(ast.NodeVisitor):
    """Collect env-API touch sites: (key_or_None, lineno, module_scope)."""

    def __init__(self):
        # (key | None, lineno, at_module_scope, scope_name)
        self.sites = []
        self._scopes = []           # enclosing function-name stack

    # -- scope tracking ----------------------------------------------------
    def _scoped(self, node):
        self._scopes.append(getattr(node, "name", "<lambda>"))
        self.generic_visit(node)
        self._scopes.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_Lambda = _scoped

    # -- env APIs ----------------------------------------------------------
    @staticmethod
    def _is_environ(node):
        """True for ``os.environ`` (Attribute) or a bare ``environ`` Name."""
        if isinstance(node, ast.Attribute) and node.attr == "environ":
            return True
        return isinstance(node, ast.Name) and node.id == "environ"

    def _record(self, key_node, lineno):
        key = (
            key_node.value
            if isinstance(key_node, ast.Constant)
            and isinstance(key_node.value, str)
            else None
        )
        scope = self._scopes[-1] if self._scopes else "<module>"
        self.sites.append((key, lineno, not self._scopes, scope))

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            # os.environ.get/setdefault/pop("KEY"...), os.getenv("KEY"...)
            if (
                func.attr in ("get", "setdefault", "pop")
                and self._is_environ(func.value)
                and node.args
            ):
                self._record(node.args[0], node.lineno)
            elif func.attr == "getenv" and node.args:
                self._record(node.args[0], node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if self._is_environ(node.value):
            self._record(node.slice, node.lineno)
        self.generic_visit(node)

    def visit_Compare(self, node):
        # "KEY" in os.environ
        if len(node.ops) == 1 and isinstance(
            node.ops[0], (ast.In, ast.NotIn)
        ):
            if self._is_environ(node.comparators[0]):
                self._record(node.left, node.lineno)
        self.generic_visit(node)


class ConfigRegistryAnalyzer:
    name = "config-registry"

    RULES = {
        "config-unregistered-env":
            "BQUERYD_TPU_* env var touched in code but absent from "
            "ENV_REGISTRY",
        "config-undocumented":
            "registered env var missing from the README config table",
        "config-readme-unknown":
            "README names a BQUERYD_TPU_* var that is not registered",
        "config-dead-var":
            "registered env var referenced nowhere in package source",
        "config-import-time-read":
            "var declared read_time='call' (live-tunable) is read at module "
            "scope, latching its value at import",
        "config-external-env":
            "read of a non-BQUERYD env var outside the external allowlist",
        "config-dynamic-env-key":
            "env access with a non-literal key (opaque to the registry lint)",
        "config-name-collision":
            "registered names where one extends the other without a "
            "related= cross-reference",
    }

    def __init__(self, registry=None, external_allowed=None):
        self.registry = ENV_REGISTRY if registry is None else registry
        self.external = (
            EXTERNAL_ENV_ALLOWED if external_allowed is None
            else frozenset(external_allowed)
        )

    def run(self, project):
        findings = []
        referenced = set()   # registered names seen anywhere in source text
        seen_keys = set()    # env keys actually touched via the env APIs

        for sf in project.files:
            if sf.tree is None:
                continue
            is_registry_module = sf.relpath.endswith("analysis/configreg.py")
            if not is_registry_module:
                # exact tokens, not substrings: a reference to FOO_BYTES
                # must not keep FOO alive
                file_tokens = set(_TOKEN_RE.findall(sf.text))
                referenced |= file_tokens & set(self.registry)
            visitor = _EnvReadVisitor()
            visitor.visit(sf.tree)
            for key, lineno, at_module, scope in visitor.sites:
                if key is None:
                    # symbol anchors on the enclosing scope, not the line:
                    # fingerprints (and hence baselines) must survive
                    # unrelated edits above the site
                    findings.append(Finding(
                        "config-dynamic-env-key", sf.relpath, lineno,
                        "env access with a non-literal key — the registry "
                        "lint cannot see which vars flow through here",
                        symbol=f"dynamic:{scope}",
                    ))
                    continue
                if not key.startswith(ENV_PREFIX):
                    if key not in self.external:
                        findings.append(Finding(
                            "config-external-env", sf.relpath, lineno,
                            f"reads env var {key!r} not in the external "
                            "allowlist (EXTERNAL_ENV_ALLOWED)",
                            symbol=key,
                        ))
                    continue
                seen_keys.add(key)
                var = self.registry.get(key)
                if var is None:
                    findings.append(Finding(
                        "config-unregistered-env", sf.relpath, lineno,
                        f"{key} is read here but not declared in "
                        "analysis.configreg.ENV_REGISTRY",
                        symbol=key,
                    ))
                    continue
                if at_module and var.read_time == READ_CALL:
                    findings.append(Finding(
                        "config-import-time-read", sf.relpath, lineno,
                        f"{key} is declared live-tunable "
                        "(read_time='call') but read at module scope — "
                        "the value latches at import",
                        symbol=key,
                    ))

        readme = project.readme_text
        readme_file = "README.md"
        # readme_text is None when the file is absent — the framework
        # reports that once (analysis-missing-readme); per-var findings
        # here would just be noise on top
        readme_present = readme is not None
        readme_tokens = set(_TOKEN_RE.findall(readme or ""))
        for name, var in self.registry.items():
            if readme_present and name not in readme_tokens:
                findings.append(Finding(
                    "config-undocumented", readme_file, 0,
                    f"{name} is registered but missing from the README "
                    "config table",
                    symbol=name,
                ))
            if name not in referenced:
                findings.append(Finding(
                    "config-dead-var",
                    f"{project.package}/analysis/configreg.py", 0,
                    f"{name} is registered but referenced nowhere in "
                    "package source — remove it or its reader came back "
                    "unregistered",
                    symbol=name,
                ))

        # README tokens that look like config vars but aren't registered
        for token in sorted(readme_tokens):
            if token not in self.registry:
                findings.append(Finding(
                    "config-readme-unknown", readme_file, 0,
                    f"README documents {token} which is not in ENV_REGISTRY",
                    symbol=token,
                ))

        # prefix near-collisions must be cross-referenced
        names = sorted(self.registry)
        for a in names:
            for b in names:
                if b.startswith(a + "_") and a != b:
                    va, vb = self.registry[a], self.registry[b]
                    if b not in va.related or a not in vb.related:
                        findings.append(Finding(
                            "config-name-collision",
                            f"{project.package}/analysis/configreg.py", 0,
                            f"{a} vs {b}: one name extends the other; "
                            "declare related= on both (with help text that "
                            "distinguishes them) or rename",
                            symbol=f"{a}~{b}",
                        ))
        return findings
