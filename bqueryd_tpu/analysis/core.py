"""Analysis framework core: findings, suppressions, baseline, project model.

Every analyzer (config registry, lock discipline, jit purity, wire schema,
metric lints) produces :class:`Finding` objects over one shared
:class:`Project` (parsed-once ASTs of every package module).  The framework
owns the three escape hatches so no analyzer grows private ones:

* **inline pragma** — ``# bqtpu: allow[rule-id] <reason>`` on the offending
  line (or as a standalone comment on the line above) suppresses that rule
  there.  A reason is MANDATORY: a bare pragma is itself a finding
  (``analysis-bad-pragma``), as is a pragma naming a rule no analyzer
  declares (``analysis-unknown-rule``) — suppressions must not outlive the
  rules they silence.
* **baseline file** — ``ANALYSIS_BASELINE.json`` at the repo root maps
  finding fingerprints to justification strings for grandfathered findings.
  Fingerprints are ``rule:path:symbol`` (no line numbers, so unrelated edits
  don't churn the baseline).  A baseline entry matching nothing is a finding
  (``analysis-stale-baseline``): the baseline can only shrink.
* **severity** — ``error`` findings gate (non-zero exit / test failure);
  ``warning`` and ``info`` report without gating.

Control-plane module: stdlib only (ast, json, os, time).
"""

import ast
import json
import os
import re
import time

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

#: default baseline filename, resolved against the project root
BASELINE_FILENAME = "ANALYSIS_BASELINE.json"

_PRAGMA_RE = re.compile(
    r"#\s*bqtpu:\s*allow\[(?P<rules>[a-z0-9_,\s-]*)\]\s*(?P<reason>.*)$"
)

# framework-owned rules (analyzers declare their own in their RULES dicts)
FRAMEWORK_RULES = {
    "analysis-parse-error": "a package module failed to parse as Python",
    "analysis-bad-pragma": "suppression pragma without a reason",
    "analysis-unknown-rule": "suppression pragma names a rule no analyzer declares",
    "analysis-stale-baseline": "baseline entry whose finding no longer occurs",
    "analysis-unused-pragma":
        "suppression pragma that matched no finding this run",
    "analysis-missing-readme":
        "project root has no README.md — doc-coverage rules cannot run",
}


class Finding:
    """One analyzer hit.  ``symbol`` is the stable anchor (env-var name,
    ``Class.attr``, envelope key, function name) used for the fingerprint so
    baselines survive line drift."""

    def __init__(self, rule, path, line, message, symbol=None,
                 severity=SEV_ERROR):
        self.rule = rule
        self.path = path            # project-relative, '/'-separated
        self.line = int(line or 0)
        self.message = message
        self.symbol = symbol if symbol is not None else message[:60]
        self.severity = severity

    @property
    def fingerprint(self):
        return f"{self.rule}:{self.path}:{self.symbol}"

    def to_dict(self):
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "severity": self.severity,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self):
        return (
            f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        )

    def __repr__(self):
        return f"<Finding {self.fingerprint} @{self.line}>"


class Suppression:
    def __init__(self, line, rules, reason):
        self.line = line
        self.rules = rules          # tuple of rule ids ("*" allowed)
        self.reason = reason
        self.used = False

    def matches(self, rule):
        return "*" in self.rules or rule in self.rules


def module_literal(tree, name):
    """The literal value of a module-level ``name = <literal>`` assignment
    in a parsed tree, or None (absent, non-literal, or unparseable).  The
    shared extraction for analyzers that diff code against a declared
    schema constant (wire envelope schemas, span schemas) — one walker
    instead of a per-analyzer copy."""
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            try:
                return ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return None
    return None


def _comment_lines(text):
    """(lineno, comment_text) for every real COMMENT token — tokenizing (not
    regexing raw lines) keeps pragma syntax mentioned in docstrings from
    parsing as live pragmas."""
    import io
    import tokenize

    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparseable file: the AST pass reports it; no pragmas here
        return []
    return out


def parse_suppressions(text):
    """Extract ``# bqtpu: allow[rule] reason`` pragmas.  Returns
    ``(suppressions, problems)`` where problems are (line, message) pairs for
    malformed pragmas (no reason / empty rule list)."""
    suppressions = []
    problems = []
    for lineno, line in _comment_lines(text):
        if "bqtpu:" not in line:
            continue
        match = _PRAGMA_RE.search(line)
        if match is None:
            problems.append((lineno, "malformed 'bqtpu:' pragma (expected "
                             "'# bqtpu: allow[rule-id] <reason>')"))
            continue
        rules = tuple(
            r.strip() for r in match.group("rules").split(",") if r.strip()
        )
        reason = match.group("reason").strip()
        if not rules:
            problems.append((lineno, "pragma allows no rules"))
            continue
        if not reason:
            problems.append(
                (lineno, f"pragma allow[{','.join(rules)}] has no reason — "
                         "every suppression must say why")
            )
            continue
        suppressions.append(Suppression(lineno, rules, reason))
    return suppressions, problems


class SourceFile:
    """One parsed package module: text, lines, AST, pragmas."""

    def __init__(self, abspath, relpath):
        self.abspath = abspath
        self.relpath = relpath
        with open(abspath, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = None
        self.parse_error = None
        try:
            self.tree = ast.parse(self.text, filename=relpath)
        except SyntaxError as exc:
            self.parse_error = f"{exc.msg} (line {exc.lineno})"
        self.suppressions, self.pragma_problems = parse_suppressions(
            self.text
        )

    def suppression_for(self, finding):
        """The pragma covering ``finding``, or None.  A pragma applies to its
        own line and — when it is a standalone comment — to the next line."""
        for sup in self.suppressions:
            if not sup.matches(finding.rule):
                continue
            if sup.line == finding.line:
                return sup
            if sup.line == finding.line - 1 and self._standalone(sup.line):
                return sup
        return None

    def _standalone(self, lineno):
        line = self.lines[lineno - 1] if lineno - 1 < len(self.lines) else ""
        return line.lstrip().startswith("#")


class Project:
    """The analyzed tree: every ``.py`` under the package dir, parsed once,
    plus the README text for doc-coverage rules."""

    def __init__(self, root, package="bqueryd_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        self.files = []
        package_dir = os.path.join(self.root, package)
        for dirpath, dirnames, filenames in os.walk(package_dir):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, name)
                rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
                self.files.append(SourceFile(abspath, rel))
        if not self.files:
            # a wheel install's site-packages parent, a typo'd --root: fail
            # loudly instead of producing an empty-but-green run
            raise FileNotFoundError(
                f"{package_dir}: no Python sources found — --root must "
                "point at a source checkout"
            )
        #: None (not "") when the file is absent, so doc-coverage rules can
        #: report ONE missing-readme finding instead of one bogus
        #: undocumented finding per registered name
        self.readme_text = None
        readme = os.path.join(self.root, "README.md")
        if os.path.exists(readme):
            with open(readme, "r", encoding="utf-8") as f:
                self.readme_text = f.read()

    def file(self, relpath):
        for sf in self.files:
            if sf.relpath == relpath:
                return sf
        return None

    def framework_findings(self):
        """Parse errors + malformed pragmas (+ missing README) as findings."""
        out = []
        if self.readme_text is None:
            out.append(Finding(
                "analysis-missing-readme", "README.md", 0,
                "README.md not found at the project root — doc-coverage "
                "rules (config table, metrics table) skipped",
                symbol="readme",
            ))
        for sf in self.files:
            if sf.parse_error:
                out.append(Finding(
                    "analysis-parse-error", sf.relpath, 0, sf.parse_error,
                    symbol="parse",
                ))
            for lineno, message in sf.pragma_problems:
                # default (message-derived) symbol: line numbers in
                # fingerprints would churn baselines on unrelated edits
                out.append(Finding(
                    "analysis-bad-pragma", sf.relpath, lineno, message,
                ))
        return out


def load_baseline(path):
    """``{fingerprint: justification}`` from the baseline file (missing file
    = empty baseline)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: baseline must be a JSON object")
    return {str(k): str(v) for k, v in data.items()}


class SuiteResult:
    """Outcome of one suite run: new findings (gate), suppressed/baselined
    (reported, don't gate), per-rule and per-analyzer counts, wall time."""

    def __init__(self):
        self.new = []               # gating findings
        self.suppressed = []        # (finding, reason)
        self.baselined = []         # (finding, justification)
        self.per_analyzer = {}      # analyzer name -> raw finding count
        self.duration_s = 0.0
        self.files_scanned = 0
        self.analyzers_run = []

    @property
    def gating(self):
        return [f for f in self.new if f.severity == SEV_ERROR]

    def counts_by_rule(self):
        counts = {}
        for f in self.new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return counts

    def to_dict(self):
        return {
            "schema": "bqueryd_tpu.analysis/1",
            "files_scanned": self.files_scanned,
            "analyzers": self.analyzers_run,
            "duration_s": round(self.duration_s, 4),
            "findings": [f.to_dict() for f in self.new],
            "suppressed": [
                {**f.to_dict(), "reason": reason}
                for f, reason in self.suppressed
            ],
            "baselined": [
                {**f.to_dict(), "justification": just}
                for f, just in self.baselined
            ],
            "counts_by_rule": self.counts_by_rule(),
            "counts_by_analyzer": dict(self.per_analyzer),
            "exit_code": 1 if self.gating else 0,
        }

    def render_text(self):
        lines = []
        for f in sorted(
            self.new, key=lambda f: (f.path, f.line, f.rule)
        ):
            lines.append(f.render())
        lines.append(
            f"-- {len(self.new)} finding(s) "
            f"({len(self.gating)} gating), "
            f"{len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined, "
            f"{self.files_scanned} files, "
            f"{len(self.analyzers_run)} analyzers, "
            f"{self.duration_s:.2f}s"
        )
        return "\n".join(lines)


def known_rules(analyzers):
    rules = dict(FRAMEWORK_RULES)
    for a in analyzers:
        rules.update(a.RULES)
    return rules


def run_suite(root=None, analyzers=None, baseline_path=None, project=None):
    """Run ``analyzers`` (default: the full registered suite) over the tree
    at ``root`` and fold suppressions + baseline into a :class:`SuiteResult`.
    """
    from bqueryd_tpu.analysis import default_analyzers

    t0 = time.perf_counter()
    if analyzers is None:
        analyzers = default_analyzers()
    if project is None:
        if root is None:
            # package dir sits at <root>/bqueryd_tpu/analysis/core.py
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )))
        project = Project(root)
    if baseline_path is None:
        baseline_path = os.path.join(project.root, BASELINE_FILENAME)
    baseline = load_baseline(baseline_path)

    result = SuiteResult()
    result.files_scanned = len(project.files)
    raw = project.framework_findings()
    result.per_analyzer["framework"] = len(raw)
    for analyzer in analyzers:
        found = list(analyzer.run(project))
        result.per_analyzer[analyzer.name] = len(found)
        result.analyzers_run.append(analyzer.name)
        raw.extend(found)

    # the known-rule universe is the FULL default suite plus whatever custom
    # analyzers ran: running a subset (--analyzer) must not misflag pragmas
    # for the families that sat out
    rules = known_rules(default_analyzers())
    rules.update(known_rules(analyzers))
    # unknown-rule pragmas: a suppression for a rule nobody declares is dead
    # weight that would silently mask a future rename
    for sf in project.files:
        for sup in sf.suppressions:
            for rule in sup.rules:
                if rule != "*" and rule not in rules:
                    raw.append(Finding(
                        "analysis-unknown-rule", sf.relpath, sup.line,
                        f"pragma suppresses unknown rule {rule!r}",
                        symbol=rule,
                    ))

    matched_fingerprints = set()
    for finding in raw:
        sf = project.file(finding.path)
        sup = sf.suppression_for(finding) if sf is not None else None
        if sup is not None:
            sup.used = True
            result.suppressed.append((finding, sup.reason))
            continue
        just = baseline.get(finding.fingerprint)
        if just is not None:
            matched_fingerprints.add(finding.fingerprint)
            result.baselined.append((finding, just))
            continue
        result.new.append(finding)

    for fingerprint, just in sorted(baseline.items()):
        if fingerprint not in matched_fingerprints:
            result.new.append(Finding(
                "analysis-stale-baseline", BASELINE_FILENAME, 0,
                f"baseline entry {fingerprint!r} matched no finding "
                f"(justification: {just!r}) — remove it",
                symbol=fingerprint,
            ))

    # unused pragmas: same only-shrinks contract as the baseline.  Only
    # gate pragmas whose rules BELONG to an analyzer that actually ran —
    # a subset run (--analyzer) must not misflag the families that sat out
    ran_rules = set(FRAMEWORK_RULES)
    for analyzer in analyzers:
        ran_rules.update(analyzer.RULES)
    for sf in project.files:
        for sup in sf.suppressions:
            if sup.used or "*" in sup.rules:
                continue
            if all(rule in ran_rules for rule in sup.rules):
                result.new.append(Finding(
                    "analysis-unused-pragma", sf.relpath, sup.line,
                    f"pragma allow[{','.join(sup.rules)}] matched no "
                    "finding — the suppressed code was fixed; remove the "
                    "pragma",
                    symbol=f"pragma@{','.join(sup.rules)}",
                ))

    result.duration_s = time.perf_counter() - t0
    return result
