"""Static lock-discipline checker for declared guarded attributes.

PR 4 made the query path concurrent: one shared stage pool mutating LRU
caches, stage clocks, metric registries and flight rings from many threads.
Each of those classes already takes a lock on its hot paths — what nothing
checked is that EVERY touch of the shared state happens under it.  A missed
``with self._lock`` is exactly the bug class that surfaces as one flaky test
a month later.

Classes opt in by declaring their guarded attributes next to the state they
protect::

    class BytesCappedCache:
        #: lock discipline, checked by bqueryd_tpu.analysis (lock-unguarded-attr)
        _bqtpu_guarded_ = {"_lock": ("_data", "_sizes", "_bytes")}

``_bqtpu_guarded_`` maps lock-attribute name to the attributes it guards (a
bare tuple is shorthand for ``{"_lock": (...)}``).  The analyzer then walks
every method and reports any ``self.<attr>`` touch (read or write) of a
guarded attribute that is not lexically inside ``with self.<lock>`` —
except in ``__init__`` (construction happens-before publication) and in
methods named ``*_locked`` (the convention for helpers whose contract is
"caller holds the lock"; the analyzer verifies that convention's other half
by flagging any CALL of a ``*_locked`` method outside the lock).

This is lexical, not interprocedural, by design: the discipline it enforces
is "take the lock in the method that touches the state", which is also the
discipline that keeps the code reviewable.  Accesses that are deliberately
lock-free (GIL-atomic monitoring reads) carry an inline
``# bqtpu: allow[lock-unguarded-attr] <why>`` pragma, so every exception is
written down where it happens.
"""

import ast

from bqueryd_tpu.analysis.core import Finding

DECLARATION_ATTR = "_bqtpu_guarded_"


def _literal_declaration(node):
    """Parse the ``_bqtpu_guarded_ = {...}`` class-body assignment into
    ``{lock_attr: (attr, ...)}``.  Returns None if the node isn't the
    declaration at all, and the string ``"unparseable"`` when it IS the
    declaration but not a literal — the caller must turn that into a
    finding, never silently skip the class (a refactor to a computed value
    would otherwise disable the whole check while CI stays green)."""
    if not isinstance(node, ast.Assign) or len(node.targets) != 1:
        return None
    target = node.targets[0]
    if not isinstance(target, ast.Name) or target.id != DECLARATION_ATTR:
        return None
    try:
        value = ast.literal_eval(node.value)
    except (ValueError, SyntaxError):
        return "unparseable"
    if isinstance(value, (tuple, list)):
        return {"_lock": tuple(value)}
    if isinstance(value, dict):
        return {
            str(lock): tuple(attrs) for lock, attrs in value.items()
        }
    return "unparseable"


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking which declared locks are lexically
    held (``with self.<lock>:`` nesting)."""

    def __init__(self, guarded, relpath, classname, methodname):
        self.guarded = guarded          # attr -> lock name
        self.locks = set(guarded.values())
        self.relpath = relpath
        self.classname = classname
        self.methodname = methodname
        self.held = set()
        self.findings = []

    def _self_attr(self, node):
        return (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr
        ) or None

    def visit_With(self, node):
        # items are processed left to right, mirroring runtime semantics:
        # in ``with self._lock, ctx(self._data):`` the lock IS held while
        # the second context expression evaluates
        newly = set()
        for item in node.items:
            attr = self._self_attr(item.context_expr)
            if attr in self.locks:
                if attr not in self.held:
                    newly.add(attr)
                    self.held.add(attr)
            else:
                # non-lock context expressions may touch guarded state
                # (e.g. ``with open(self._path)``): check them as usual
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= newly

    def visit_Attribute(self, node):
        attr = self._self_attr(node)
        if attr:
            lock = self.guarded.get(attr)
            if lock is not None and lock not in self.held:
                self.findings.append(Finding(
                    "lock-unguarded-attr", self.relpath, node.lineno,
                    f"{self.classname}.{self.methodname} touches guarded "
                    f"attribute self.{attr} outside 'with self.{lock}'",
                    symbol=f"{self.classname}.{self.methodname}.{attr}",
                ))
        self.generic_visit(node)

    def visit_Call(self, node):
        # the *_locked convention's caller side: such helpers must only be
        # invoked while the guarding lock is held
        func = node.func
        attr = self._self_attr(func)
        if attr and attr.endswith("_locked") and self.locks - self.held:
            held_none = not (self.locks & self.held)
            if held_none:
                self.findings.append(Finding(
                    "lock-helper-outside-lock", self.relpath, node.lineno,
                    f"{self.classname}.{self.methodname} calls "
                    f"self.{attr}() without holding any declared lock — "
                    "the *_locked suffix promises the caller holds it",
                    symbol=f"{self.classname}.{self.methodname}.{attr}",
                ))
        self.generic_visit(node)


class LockDisciplineAnalyzer:
    name = "lock-discipline"

    RULES = {
        "lock-unguarded-attr":
            "declared-guarded attribute touched outside its lock's 'with' "
            "block",
        "lock-helper-outside-lock":
            "*_locked helper called without holding a declared lock",
        "lock-missing-lock-attr":
            "_bqtpu_guarded_ names a lock attribute the class never "
            "assigns",
        "lock-bad-declaration":
            "_bqtpu_guarded_ is not a literal dict/tuple — the class "
            "cannot be checked",
    }

    def run(self, project):
        findings = []
        for sf in project.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                declaration = None
                for stmt in node.body:
                    declaration = _literal_declaration(stmt)
                    if declaration is not None:
                        break
                if declaration == "unparseable" or declaration == {}:
                    # an opted-in class whose declaration we cannot read
                    # must FAIL, not silently lose its checking
                    findings.append(Finding(
                        "lock-bad-declaration", sf.relpath, node.lineno,
                        f"{node.name}._bqtpu_guarded_ must be a literal "
                        "dict {lock: (attrs...)} or tuple of attrs — a "
                        "computed value silently disables the lock check "
                        "for the whole class",
                        symbol=node.name,
                    ))
                    continue
                if declaration is None:
                    continue
                attr_to_lock = {}
                for lock, attrs in declaration.items():
                    for attr in attrs:
                        attr_to_lock[attr] = lock
                assigned = {
                    n.attr
                    for meth in node.body
                    if isinstance(meth, ast.FunctionDef)
                    for n in ast.walk(meth)
                    if isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and isinstance(n.ctx, ast.Store)
                }
                for lock in declaration:
                    if lock not in assigned:
                        findings.append(Finding(
                            "lock-missing-lock-attr", sf.relpath,
                            node.lineno,
                            f"{node.name}._bqtpu_guarded_ names lock "
                            f"{lock!r} but no method assigns self.{lock}",
                            symbol=f"{node.name}.{lock}",
                        ))
                for meth in node.body:
                    if not isinstance(meth, ast.FunctionDef):
                        continue
                    if meth.name == "__init__" or meth.name.endswith(
                        "_locked"
                    ):
                        # __init__ publishes nothing concurrently; *_locked
                        # helpers run under the caller's lock (their call
                        # sites are checked instead)
                        continue
                    checker = _MethodChecker(
                        attr_to_lock, sf.relpath, node.name, meth.name
                    )
                    for stmt in meth.body:
                        checker.visit(stmt)
                    findings.extend(checker.findings)
        return findings
