"""Wire-schema drift guard: envelope keys vs the schema in ``messages.py``.

The protocol is JSON dict envelopes; a key added on one side of the wire
(``controller.py``) without the other (``worker.py``/``rpc.py``) is not a
type error anywhere — it is a silent protocol bug that surfaces as a
``None`` default three hops later.  This analyzer extracts every envelope
key LITERAL the wire modules read or write and diffs the result against the
declared schema (:data:`bqueryd_tpu.messages.ENVELOPE_SCHEMA` /
``RESULT_ENVELOPE_SCHEMA``):

* ``wire-undeclared-key`` — a wire module touches an envelope key the
  schema does not declare;
* ``wire-one-sided-key`` — a declared key that is only ever written or only
  ever read across the wire modules (unless the schema entry explicitly
  waives it via ``WIRE_ONE_SIDED_OK`` with the reason — e.g. keys consumed
  by external clients or produced by the base ``Message`` constructor);
* ``wire-dead-key`` — a declared key neither read nor written anywhere.

Extraction is receiver-name based: within the three wire modules, variables
conventionally holding envelopes (``msg``, ``reply``, ``wrm``, ``shard``,
...) are treated as Message dicts; ``X.get("k")`` / ``X["k"]`` /
``"k" in X`` / ``X.pop("k")`` count as reads, ``X["k"] = v`` /
``X.add_as_binary("k", ...)`` / ``X.setdefault("k", v)`` and dict literals
passed to ``*Message({...})`` constructors count as writes.  The pickled
groupby result envelope (``{"ok": ..., "payloads": ...}``) is covered by
extracting every key of a dict literal serialized via ``pickle.dumps(...)``
— its single write idiom in the wire modules.
"""

import ast

from bqueryd_tpu.analysis.core import Finding

WIRE_FILES = ("controller.py", "worker.py", "rpc.py")

#: variable names that hold wire envelopes in the wire modules — the
#: receiver convention the extraction keys on (``segment``/``info``/
#: ``entry`` etc. are controller-local bookkeeping dicts, deliberately out)
RECEIVERS = frozenset({
    "msg", "reply", "wrm", "shard", "calc", "child", "err", "scatter",
    "fan", "envelope", "newmsg", "gossip",
    # the controller's worker_map entry: the absorbed WRM dict plus the
    # controller-local bookkeeping keys declared in the schema
    "info",
})


def _schema(project):
    """The declared schemas, read from the ANALYZED tree's ``messages.py``
    (``--root`` must diff a checkout against its own schema, not against
    whatever bqueryd_tpu the running environment imports).  Falls back to
    the live module only when the project has no parseable messages.py —
    the synthetic-project case in tests."""
    sf = project.file(f"{project.package}/messages.py")
    if sf is not None and sf.tree is not None:
        from bqueryd_tpu.analysis.core import module_literal

        found = {}
        for name in (
            "ENVELOPE_SCHEMA", "RESULT_ENVELOPE_SCHEMA",
            "WIRE_ONE_SIDED_OK",
        ):
            value = module_literal(sf.tree, name)
            if isinstance(value, dict):
                found[name] = value
        if "ENVELOPE_SCHEMA" in found:
            declared = dict(found.get("ENVELOPE_SCHEMA", {}))
            declared.update(found.get("RESULT_ENVELOPE_SCHEMA", {}))
            return declared, dict(found.get("WIRE_ONE_SIDED_OK", {}))
    from bqueryd_tpu import messages

    declared = {}
    declared.update(messages.ENVELOPE_SCHEMA)
    declared.update(messages.RESULT_ENVELOPE_SCHEMA)
    return declared, dict(messages.WIRE_ONE_SIDED_OK)


class _KeyUseVisitor(ast.NodeVisitor):
    def __init__(self):
        self.reads = {}    # key -> [lineno]
        self.writes = {}   # key -> [lineno]

    def _mark(self, table, key_node, lineno):
        if isinstance(key_node, ast.Constant) and isinstance(
            key_node.value, str
        ):
            table.setdefault(key_node.value, []).append(lineno)

    @staticmethod
    def _receiver(node):
        return isinstance(node, ast.Name) and node.id in RECEIVERS

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute) and self._receiver(func.value):
            if func.attr in ("get", "get_from_binary", "pop") and node.args:
                self._mark(self.reads, node.args[0], node.lineno)
            elif func.attr in ("add_as_binary", "setdefault") and node.args:
                self._mark(self.writes, node.args[0], node.lineno)
        # CalcMessage({...}) / RPCMessage({...}) constructor payloads
        if isinstance(func, ast.Name) and func.id.endswith("Message"):
            for arg in node.args:
                if isinstance(arg, ast.Dict):
                    for key in arg.keys:
                        self._mark(self.writes, key, node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if self._receiver(node.value):
            if isinstance(node.ctx, ast.Load):
                self._mark(self.reads, node.slice, node.lineno)
            else:
                self._mark(self.writes, node.slice, node.lineno)
        self.generic_visit(node)

    def visit_Compare(self, node):
        if len(node.ops) == 1 and isinstance(
            node.ops[0], (ast.In, ast.NotIn)
        ) and self._receiver(node.comparators[0]):
            self._mark(self.reads, node.left, node.lineno)
        self.generic_visit(node)


class _ResultEnvelopeVisitor(ast.NodeVisitor):
    """Writes of the pickled result envelope, anchored on its ONE
    serialization idiom — a dict literal as the first argument of
    ``pickle.dumps(...)``.  Matching bare dict literals by key intersection
    would count controller bookkeeping dicts that happen to share a key
    ('busy', 'error') and leave the guard vacuous for the real envelope.
    EVERY key of a pickled envelope counts as a write (so an undeclared key
    added to the envelope is caught, not just drift on declared ones)."""

    def __init__(self):
        self.writes = {}

    @staticmethod
    def _is_pickle_dumps(func):
        if isinstance(func, ast.Attribute) and func.attr == "dumps":
            return isinstance(func.value, ast.Name) and func.value.id in (
                "pickle", "pkl",
            )
        return isinstance(func, ast.Name) and func.id == "dumps"

    def visit_Call(self, node):
        if self._is_pickle_dumps(node.func) and node.args and isinstance(
            node.args[0], ast.Dict
        ):
            for key in node.args[0].keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    self.writes.setdefault(key.value, []).append(
                        node.lineno
                    )
        self.generic_visit(node)


class WireSchemaAnalyzer:
    name = "wire-schema"

    RULES = {
        "wire-undeclared-key":
            "wire module touches an envelope key not declared in "
            "messages.ENVELOPE_SCHEMA / RESULT_ENVELOPE_SCHEMA",
        "wire-one-sided-key":
            "declared envelope key written but never read (or read but "
            "never written) across the wire modules",
        "wire-dead-key":
            "declared envelope key neither read nor written in any wire "
            "module",
    }

    def run(self, project):
        declared, one_sided_ok = _schema(project)
        findings = []
        reads = {}
        writes = {}
        schema_file = f"{project.package}/messages.py"

        for sf in project.files:
            name = sf.relpath.rsplit("/", 1)[-1]
            if sf.tree is None or name not in WIRE_FILES:
                continue
            visitor = _KeyUseVisitor()
            visitor.visit(sf.tree)
            envelope = _ResultEnvelopeVisitor()
            envelope.visit(sf.tree)
            for key, sites in visitor.reads.items():
                reads.setdefault(key, []).extend(
                    (sf.relpath, s) for s in sites
                )
            for table in (visitor.writes, envelope.writes):
                for key, sites in table.items():
                    writes.setdefault(key, []).extend(
                        (sf.relpath, s) for s in sites
                    )

        for key in sorted(set(reads) | set(writes)):
            if key not in declared:
                path, line = (reads.get(key) or writes.get(key))[0]
                findings.append(Finding(
                    "wire-undeclared-key", path, line,
                    f"envelope key {key!r} used on the wire but not "
                    "declared in messages.py schemas",
                    symbol=key,
                ))

        for key in sorted(declared):
            read = bool(reads.get(key))
            written = bool(writes.get(key))
            if key in one_sided_ok:
                continue
            if not read and not written:
                findings.append(Finding(
                    "wire-dead-key", schema_file, 0,
                    f"declared envelope key {key!r} is neither read nor "
                    "written by any wire module — dead schema entry",
                    symbol=key,
                ))
            elif read != written:
                side = "read" if read else "written"
                other = "written" if read else "read"
                where = (reads if read else writes)[key][0]
                findings.append(Finding(
                    "wire-one-sided-key", where[0], where[1],
                    f"envelope key {key!r} is {side} (e.g. here) but never "
                    f"{other} in any wire module — one-sided key; declare "
                    "it in messages.WIRE_ONE_SIDED_OK with the reason if "
                    "the peer lives outside controller/worker/rpc",
                    symbol=key,
                ))
        return findings
