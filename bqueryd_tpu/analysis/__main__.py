"""CLI: ``python -m bqueryd_tpu.analysis [--format text|json] [...]``.

Exit codes: 0 = clean (suppressed/baselined findings don't gate), 1 = new
gating findings, 2 = usage/internal error.  The JSON format is the artifact
CI archives (schema ``bqueryd_tpu.analysis/1``, see
:meth:`bqueryd_tpu.analysis.core.SuiteResult.to_dict`).
"""

import argparse
import json
import sys


def main(argv=None):
    from bqueryd_tpu.analysis import default_analyzers, run_suite

    parser = argparse.ArgumentParser(
        prog="python -m bqueryd_tpu.analysis",
        description="bqueryd_tpu project-wide static analysis suite",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (json is the CI artifact)",
    )
    parser.add_argument(
        "--root", default=None,
        help="project root (default: the checkout containing this package)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: <root>/ANALYSIS_BASELINE.json)",
    )
    parser.add_argument(
        "--analyzer", action="append", default=None, metavar="NAME",
        help="run only the named analyzer(s); repeatable",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id with its description and exit",
    )
    args = parser.parse_args(argv)

    analyzers = default_analyzers()
    if args.list_rules:
        from bqueryd_tpu.analysis.core import known_rules

        for rule, description in sorted(known_rules(analyzers).items()):
            print(f"{rule}: {description}")
        return 0

    if args.analyzer:
        wanted = set(args.analyzer)
        analyzers = [a for a in analyzers if a.name in wanted]
        missing = wanted - {a.name for a in analyzers}
        if missing:
            print(
                f"unknown analyzer(s): {', '.join(sorted(missing))}",
                file=sys.stderr,
            )
            return 2

    try:
        result = run_suite(
            root=args.root, analyzers=analyzers,
            baseline_path=args.baseline,
        )
    except Exception as exc:  # a broken suite must fail loudly, not pass
        print(f"analysis suite error: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=1, sort_keys=True))
    else:
        print(result.render_text())
    return 1 if result.gating else 0


if __name__ == "__main__":
    sys.exit(main())
