"""Metric lints, migrated into the analysis framework as static analyzers.

The originals — :meth:`bqueryd_tpu.obs.metrics.MetricsRegistry.lint` and
:func:`bqueryd_tpu.obs.metrics.readme_coverage_problems` — run against LIVE
registries from tests and keep doing so (they see runtime-constructed
names like the ``RegistryCounters`` mirrors, which no static pass can).
These analyzers are their static twins over the source: every metric name
LITERAL at a registration/construction site is checked for the naming
contract and README coverage without having to boot a node, so the suite
CLI covers the whole package in milliseconds.

* ``metric-name-format`` — literal metric name fails
  ``^bqueryd_tpu_[a-z0-9_]+$`` (counters may suffix ``_total``);
* ``metric-missing-help`` — registration with a missing/empty literal help
  string;
* ``metric-readme-coverage`` — literal metric name absent from the README
  metrics documentation.

F-string/computed names are skipped here; the runtime lint owns those.
"""

import ast

from bqueryd_tpu.analysis.core import Finding
from bqueryd_tpu.obs.metrics import METRIC_NAME_RE

#: registration-call attribute names and constructor class names whose first
#: argument is a metric name literal
_REGISTRATION_ATTRS = frozenset({"counter", "gauge", "histogram"})
_CONSTRUCTOR_NAMES = frozenset({"Counter", "Gauge", "Histogram"})


def _metric_sites(tree):
    """(name, help_or_None, lineno) per literal registration site."""
    sites = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_reg = (
            isinstance(func, ast.Attribute)
            and func.attr in _REGISTRATION_ATTRS
        )
        is_ctor = isinstance(func, ast.Name) and func.id in _CONSTRUCTOR_NAMES
        if isinstance(func, ast.Attribute) and (
            func.attr in _CONSTRUCTOR_NAMES
        ):
            is_ctor = True
        if not (is_reg or is_ctor):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant):
            continue
        name = node.args[0].value
        if not isinstance(name, str):
            continue
        help_text = None
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            if isinstance(node.args[1].value, str):
                help_text = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "help_text" and isinstance(kw.value, ast.Constant):
                help_text = kw.value.value
        sites.append((name, help_text, node.lineno))
    return sites


class MetricNameAnalyzer:
    """Static twin of ``MetricsRegistry.lint`` (names + help text)."""

    name = "metric-lint"

    RULES = {
        "metric-name-format":
            "literal metric name fails ^bqueryd_tpu_[a-z0-9_]+$",
        "metric-missing-help":
            "metric registered with no (or empty) literal help text",
    }

    def run(self, project):
        findings = []
        for sf in project.files:
            if sf.tree is None:
                continue
            for name, help_text, lineno in _metric_sites(sf.tree):
                base = name[:-len("_total")] if name.endswith(
                    "_total"
                ) else name
                if not METRIC_NAME_RE.match(base):
                    findings.append(Finding(
                        "metric-name-format", sf.relpath, lineno,
                        f"metric name {name!r} fails "
                        f"{METRIC_NAME_RE.pattern}",
                        symbol=name,
                    ))
                if help_text is not None and not help_text.strip():
                    findings.append(Finding(
                        "metric-missing-help", sf.relpath, lineno,
                        f"metric {name!r} registered with empty help text",
                        symbol=name,
                    ))
        return findings


class MetricReadmeAnalyzer:
    """Static twin of ``readme_coverage_problems``: every literal metric
    name must appear in the README metrics documentation."""

    name = "metric-readme"

    RULES = {
        "metric-readme-coverage":
            "literal metric name missing from the README metrics table",
    }

    def run(self, project):
        if project.readme_text is None:
            # the framework's analysis-missing-readme finding covers this
            return []
        findings = []
        seen = set()
        for sf in project.files:
            if sf.tree is None:
                continue
            for name, _help, lineno in _metric_sites(sf.tree):
                if name in seen:
                    continue
                seen.add(name)
                if name not in project.readme_text:
                    findings.append(Finding(
                        "metric-readme-coverage", sf.relpath, lineno,
                        f"metric {name!r} registered here but missing from "
                        "the README metrics table",
                        symbol=name,
                    ))
        return findings
