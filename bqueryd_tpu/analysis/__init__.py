"""Project-wide static analysis: the correctness tooling tier-1 gates on.

Once the query path went multi-threaded with device-resident caches (PR 4),
the dominant failure classes stopped being kernel math and became lock
discipline, silent recompiles at the jit boundary, and config/wire drift —
classes a static pass catches before they are flaky-test archaeology.  This
package is that pass, runnable three ways:

* ``python -m bqueryd_tpu.analysis`` — text report, non-zero exit on new
  findings (``--format json`` for the machine-readable artifact CI stores);
* from tests — ``tests/test_analysis.py`` asserts the shipped tree is
  clean, so drift fails tier-1;
* as a library — ``run_suite()`` returns the structured result bench.py
  records in BENCH_DETAIL.json.

Analyzer families (rule ids in each module's ``RULES``):

====================  =====================================================
config-registry       every ``BQUERYD_TPU_*`` env var in one typed table
                      (:mod:`.configreg`), README-synced, no unregistered /
                      dead / import-latched reads
lock-discipline       declared guarded attributes touched only under their
                      lock (:mod:`.concurrency`); runtime lock-ORDER
                      recording with cycle detection lives in
                      :mod:`.lockorder` and is driven from tests
jit-purity            host impurity and cache-key hazards inside jitted
                      bodies in ``ops/`` + ``parallel/executor.py``
                      (:mod:`.purity`), cross-checked against the PR 3
                      compile-profile counters via ``jit-uninstrumented``
wire-schema           envelope key literals in controller/worker/rpc vs the
                      schemas declared in ``messages.py`` (:mod:`.wire`)
span-schema           literal span/phase sites vs ``messages.SPAN_SCHEMA``
                      and the attribution map in ``obs.slo``
                      (:mod:`.spans`) — a new dispatch path cannot ship
                      spans that ``rpc.autopsy`` drops into unattributed
metric-lint /         static twins of the PR 2/3 runtime metric lints
metric-readme         (:mod:`.metricslint`); the runtime entry points in
                      ``obs.metrics`` keep working unchanged
====================  =====================================================

Suppression model (framework-owned, :mod:`.core`): inline
``# bqtpu: allow[rule-id] <reason>`` pragmas with mandatory reasons, plus
the ``ANALYSIS_BASELINE.json`` fingerprint baseline for grandfathered
findings — shipped near-empty, and stale entries are themselves findings.
"""

from bqueryd_tpu.analysis.core import (  # noqa: F401
    Finding,
    Project,
    SuiteResult,
    run_suite,
)


def default_analyzers():
    """The full suite, in report order."""
    from bqueryd_tpu.analysis.concurrency import LockDisciplineAnalyzer
    from bqueryd_tpu.analysis.configreg import ConfigRegistryAnalyzer
    from bqueryd_tpu.analysis.metricslint import (
        MetricNameAnalyzer,
        MetricReadmeAnalyzer,
    )
    from bqueryd_tpu.analysis.purity import JitPurityAnalyzer
    from bqueryd_tpu.analysis.spans import SpanSchemaAnalyzer
    from bqueryd_tpu.analysis.wire import WireSchemaAnalyzer

    return [
        ConfigRegistryAnalyzer(),
        LockDisciplineAnalyzer(),
        JitPurityAnalyzer(),
        WireSchemaAnalyzer(),
        SpanSchemaAnalyzer(),
        MetricNameAnalyzer(),
        MetricReadmeAnalyzer(),
    ]
