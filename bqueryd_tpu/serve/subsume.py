"""Plan-signature subsumption lattice: which cached answers PROVE a query.

Every earlier cache in the system hits only on exact identities — the
worker result cache on (table identity, query signature), PR-1 dedup on
the request signature, PR-9 bundles on one admission window.  This module
is the semantic half: given the logical plan of an admitted query and the
set of controller-resident materialized rollups (:mod:`.rollup`), it
enumerates the candidates that *provably contain* the query's answer and
the transform that extracts it:

* **exact** — same plan view: serve the stored partials verbatim;
* **window-fold** — a finer time-window rollup answers a coarser window
  when the coarse grid is a refinement-aligned multiple of the fine one
  (``q_every % c_every == 0`` and the origins agree modulo the fine
  width): every fine bucket lands wholly inside one coarse bucket, so
  re-flooring the bucket keys and re-aggregating through the hostmerge
  value-kinds forms is exact;
* **key-fold** — a finer group-key rollup answers a coarser groupby by
  dropping key columns and re-aggregating.  Sound ONLY when every
  dropped key column is a null-free integer column (proven by the
  build-time column census): null group keys drop rows from the finer
  grouping that the coarser query would have kept;
* **zone-proof filter** — a cached *weaker-filtered* (usually
  unfiltered) rollup answers a filtered query when per-chunk zone maps
  (PR 14) prove each extra predicate term selects EVERY committed chunk
  whole.  Integer columns only — float/datetime zone maps skip NaN/NaT
  rows, so "full chunk" cannot be proven for them.

Refusal is the default: partial-chunk filter overlap, window
misalignment, non-mergeable aggregations (count_distinct, top-k,
sketches, raw rows, basket expansion), joins, and anything this module
cannot prove falls back to ``recompute`` — the dispatch path is always
correct, serving is only ever an optimization.

The chosen source is costed through the PR-6 calibration model
(:func:`bqueryd_tpu.plan.calibrate.analytic_units`): folding a
G-group partial must be cheaper than re-scanning N rows, which it is
whenever G << N — the *Global Hash Tables Strike Back!* observation this
layer is built on.

Pure control-plane module: NumPy only, importable by the (JAX-free)
controller; all functions are deterministic on their inputs.
"""

from bqueryd_tpu.models.query import MERGEABLE_OPS

#: aggregation ops a rollup partial can be re-aggregated under (the
#: hostmerge-mergeable classics; distinct counts carry value sets, top-k /
#: sketch parts are DAG-only and never enter the lattice)
SERVE_OPS = frozenset(MERGEABLE_OPS)

#: index of the window signature inside :meth:`plan.dag.OperatorDAG.signature`
_DAG_WINDOW_IDX = 7
#: index of the join signature (any join disqualifies)
_DAG_JOIN_IDX = 6

#: operators a zone map can prove full-chunk selection for
_FULL_SELECT_OPS = frozenset({"==", "!=", ">", ">=", "<", "<=", "in"})


def _freeze_term(term):
    col, op, value = term
    if isinstance(value, (list, tuple, set)):
        value = tuple(value)
    return (col, op, value)


def plan_view(plan):
    """The hashable lattice view of a logical plan: the fields subsumption
    reasons over, decoupled from :meth:`LogicalPlan.signature`'s frozen
    encoding so candidate/query comparison can be structural."""
    return {
        "filenames": tuple(plan.filenames),
        "keys": tuple(plan.groupby.keys),
        "aggs": tuple(tuple(a) for a in plan.physical_agg_list()),
        "where": tuple(_freeze_term(t) for t in plan.where_terms),
        "aggregate_rows": bool(plan.aggregate_rows),
        "expand": plan.expand_filter_column,
        "dag_sig": getattr(plan, "dag_sig", None),
    }


def view_key(view):
    """Stable string identity of a view — the rollup-store key and the
    ``subsumed_from`` wire value."""
    import hashlib

    digest = hashlib.sha1(repr(sorted(view.items())).encode()).hexdigest()
    return f"rollup:{'+'.join(view['keys']) or 'all'}:{digest[:12]}"


def plan_eligible(view):
    """``(ok, reason)``: can this plan shape be semantically served / rolled
    up at all?  Strict — anything outside the mergeable-aggregate lattice
    is refused with a reason (surfaced in the flight recorder)."""
    if not view["aggregate_rows"]:
        return False, "raw-rows"
    if view["expand"]:
        return False, "expand-filter"
    for _in_col, op, _out in view["aggs"]:
        if op not in SERVE_OPS:
            return False, f"op:{op}"
    dag_sig = view["dag_sig"]
    if dag_sig is not None:
        if dag_sig[_DAG_JOIN_IDX] is not None:
            return False, "join"
        if dag_sig[_DAG_WINDOW_IDX] is None:
            # a plain DAG (rpc.query without window): exact signature
            # match still works, but no fold is defined over it
            return True, None
    return True, None


def zone_full_select(lo, hi, op, value):
    """PROOF from one chunk's ``(min, max)`` zone that ``op value`` selects
    every row of the chunk.  Conservative: unknown ops / incomparable
    values return False."""
    try:
        if op == "==":
            return lo == hi == value
        if op == "!=":
            return not isinstance(value, (list, tuple)) and (
                value < lo or value > hi
            )
        if op == ">":
            return lo > value
        if op == ">=":
            return lo >= value
        if op == "<":
            return hi < value
        if op == "<=":
            return hi <= value
        if op == "in":
            return lo == hi and lo in value
    except TypeError:
        return False
    return False


def term_full_selects(cols_meta, term):
    """True when the build-time column census proves ``term`` selects every
    committed chunk of ONE file whole.  Requires an integer column (float /
    datetime zone maps skip NaN/NaT — "all rows" is unprovable) with a zone
    map on every chunk."""
    col, op, value = term
    if op not in _FULL_SELECT_OPS:
        return False
    info = (cols_meta or {}).get(col)
    if not info or info.get("kind") != "int":
        return False
    zones = info.get("zones")
    if not zones:
        return False
    return all(
        z is not None and zone_full_select(z[0], z[1], op, value)
        for z in zones
    )


def _match_window(cand, query):
    """Window-fold match over two DAG-shaped views; returns (transform,
    refusal_reason)."""
    c_sig, q_sig = cand["dag_sig"], query["dag_sig"]
    if len(c_sig) != len(q_sig):
        return None, "dag-version"
    for i in range(len(c_sig)):
        if i != _DAG_WINDOW_IDX and c_sig[i] != q_sig[i]:
            return None, "dag-shape"
    c_win, q_win = c_sig[_DAG_WINDOW_IDX], q_sig[_DAG_WINDOW_IDX]
    if c_win is None or q_win is None:
        return None, "window-missing"
    c_col, c_every, c_alias, c_origin = c_win
    q_col, q_every, q_alias, q_origin = q_win
    if c_col != q_col or c_alias != q_alias:
        return None, "window-column"
    if q_every % c_every != 0:
        return None, "window-misaligned"
    if (q_origin - c_origin) % c_every != 0:
        return None, "window-origin"
    return {
        "kind": "fold",
        "window": (q_alias, int(q_every), int(q_origin)),
    }, None


def _match_fold(cand, query, meta):
    """Key-fold + agg projection + zone-proof extra-filter match over two
    plain (dag-free) views.  ``meta`` is ``{filename: {col: {"kind", "zones",
    "nulls"}}}`` from the candidate's build census."""
    if not set(query["keys"]) <= set(cand["keys"]):
        return None, "keys"
    dropped = [k for k in cand["keys"] if k not in query["keys"]]
    for k in dropped:
        # a null group key drops its row from the finer grouping; the
        # coarser query keeps that row — fold only over proven-null-free
        # (integer) key columns, checked per file
        for fname in query["filenames"]:
            info = ((meta or {}).get(fname) or {}).get(k)
            if not info or info.get("kind") != "int" or info.get("nulls"):
                return None, f"key-nullable:{k}"
    cand_aggs = list(cand["aggs"])
    agg_idx = []
    for agg in query["aggs"]:
        if agg not in cand_aggs:
            return None, f"agg-missing:{agg[2]}"
        agg_idx.append(cand_aggs.index(agg))
    c_where, q_where = set(cand["where"]), set(query["where"])
    if not c_where <= q_where:
        return None, "filter-weaker"
    extra = [t for t in query["where"] if t not in c_where]
    for term in extra:
        for fname in query["filenames"]:
            if not term_full_selects((meta or {}).get(fname), term):
                return None, f"filter-partial:{term[0]}"
    transform = {"kind": "fold"}
    if tuple(query["keys"]) != tuple(cand["keys"]):
        transform["keys"] = tuple(query["keys"])
    if agg_idx != list(range(len(cand_aggs))):
        transform["aggs"] = tuple(agg_idx)
    if len(transform) == 1 and not extra:
        # structurally identical after all: exact
        transform = {"kind": "exact"}
    elif len(transform) == 1:
        # zone-proven filter over the identical shape: the stored payload
        # serves verbatim, no fold needed
        transform = {"kind": "zone"}
    return transform, None


def match(cand, query, meta=None):
    """Match one candidate view against a query view.

    Returns ``(transform, None)`` on success or ``(None, reason)`` on
    refusal.  ``transform["kind"]`` is ``"exact"`` (serve stored bytes
    verbatim), ``"zone"`` (verbatim, justified by zone proofs), or
    ``"fold"`` (re-key and/or project and collapse)."""
    if cand["filenames"] != query["filenames"]:
        return None, "filenames"
    if cand["aggregate_rows"] != query["aggregate_rows"] or (
        cand["expand"] != query["expand"]
    ):
        return None, "shape"
    if cand == query:
        return {"kind": "exact"}, None
    c_dag, q_dag = cand["dag_sig"], query["dag_sig"]
    if (c_dag is None) != (q_dag is None):
        return None, "shape"
    if c_dag is not None:
        return _match_window(cand, query)
    return _match_fold(cand, query, meta)


def apply_transform(payload, transform):
    """Apply a match transform to ONE partials payload dict, returning a new
    payload dict.  ``exact``/``zone`` pass through; ``fold`` projects the
    aggregation slots, re-keys (window re-floor and/or key-column drop) and
    collapses duplicate key tuples through
    :func:`bqueryd_tpu.parallel.hostmerge.collapse_partials` — the same
    value-kinds merge forms every cross-shard combine uses."""
    import numpy as np

    from bqueryd_tpu.parallel import hostmerge

    if payload.get("kind") != "partials" or transform["kind"] != "fold":
        return payload
    p = dict(payload)
    sel = transform.get("aggs")
    if sel is not None:
        p["aggs"] = [payload["aggs"][i] for i in sel]
        p["ops"] = [payload["ops"][i] for i in sel]
        p["out_cols"] = [payload["out_cols"][i] for i in sel]
        kinds = payload.get("value_kinds")
        if kinds is not None:
            p["value_kinds"] = [kinds[i] for i in sel]
    window = transform.get("window")
    if window is not None:
        alias, every, origin = window
        arr = np.asarray(p["keys"][alias])
        ints = arr.astype(np.int64, copy=False)
        floored = origin + ((ints - origin) // every) * every
        # NaT bucket keys (int64 min) pass through unfloored — the window
        # derivation drops NaT rows, so none should exist; belt-and-braces
        nat = ints == np.iinfo(np.int64).min
        if nat.any():
            floored = np.where(nat, ints, floored)
        keys = dict(p["keys"])
        keys[alias] = (
            floored.view(arr.dtype) if arr.dtype.kind == "M"
            else floored.astype(arr.dtype)
        )
        p["keys"] = keys
    keep = transform.get("keys")
    if keep is not None:
        p["key_cols"] = list(keep)
        p["keys"] = {c: p["keys"][c] for c in keep}
    return hostmerge.collapse_partials(p)


def serving_cost(groups, out_groups):
    """Relative cost of answering from a G-group partial (host fold)."""
    from bqueryd_tpu.plan import calibrate

    return calibrate.analytic_units("scatter", groups, max(out_groups, 1))


def recompute_cost(total_rows, out_groups):
    """Relative cost of the dispatch path re-scanning ``total_rows``."""
    from bqueryd_tpu.plan import calibrate

    return calibrate.analytic_units(
        "scatter", max(total_rows, 1), max(out_groups, 1)
    )


def choose_source(matches, total_rows):
    """Pick the cheapest-correct candidate: ``matches`` is a list of
    ``(entry_key, transform, candidate_group_rows)``; returns the winning
    tuple or None when recompute is estimated cheaper than every candidate
    (tiny tables) — the calibration-model cost decision the lattice defers
    to."""
    best = None
    floor = recompute_cost(total_rows, 1)
    for entry_key, transform, groups in matches:
        cost = serving_cost(groups, 1)
        if cost >= floor:
            continue
        if best is None or cost < best[3]:
            best = (entry_key, transform, groups, cost)
    if best is None:
        return None
    return best[:3]
