"""Semantic serving layer: answer admitted queries from proofs, not scans.

``ServingLayer`` is the controller's single entry point into PR-16
serving.  It composes the two halves of the subsystem:

* :mod:`bqueryd_tpu.serve.subsume` — the pure plan-subsumption lattice
  (exact / window-fold / key-fold / zone-proof matching plus the
  calibrated source choice);
* :mod:`bqueryd_tpu.serve.rollup` — heat tracking and the materialized
  rollup entry lifecycle (build / delta-refresh / evict, append-epoch
  staleness).

The layer sits at the very top of ``ControllerNode._admit_plan``: a hit
replies to the client immediately — consuming no admission slot, no
worker dispatch, no scan — and a miss falls through to the ordinary
pipeline untouched.  ``BQUERYD_TPU_SERVE=0`` (read per call, so it can
be flipped on a live process) disables both serving and rollup
bookkeeping; behavior then round-trips bit-identically to the exact
-signature-only tree.

All zmq message construction and envelope assembly stay in
``controller.py`` (``_dispatch_rollup_build`` / ``_absorb_rollup_reply``
/ ``_reply_served``) where the wire lint audits them; this package never
touches a socket.
"""

import os
import time
from collections import deque

from bqueryd_tpu.serve import rollup, subsume


def serve_enabled():
    """Kill switch ``BQUERYD_TPU_SERVE`` (default on).  Checked on every
    public entry point rather than cached: flipping the env var mid-run
    must restore exact-signature-only behavior immediately."""
    return os.environ.get("BQUERYD_TPU_SERVE", "1") == "1"


class ServingLayer:
    """Controller-side orchestration of subsumption + rollups."""

    def __init__(self, controller):
        self.controller = controller
        self.manager = rollup.RollupManager()
        self.decisions = deque(maxlen=32)
        self.served = 0

    # -- admission hook ------------------------------------------------

    def try_serve(self, msg, plan, kwargs):
        """Called by ``_admit_plan`` after shard validation, before any
        admission accounting.  Returns True when the query was answered
        here (reply already sent); False on any miss or refusal — the
        caller then proceeds exactly as before PR 16."""
        if not serve_enabled():
            return False
        try:
            return self._try_serve(msg, plan, kwargs)
        except Exception:
            # serving is an optimization: any internal error must degrade
            # to the always-correct dispatch path, never fail the query
            self.controller.logger.exception("serving layer error (miss)")
            return False

    def _try_serve(self, msg, plan, kwargs):
        now = time.monotonic()
        view = subsume.plan_view(plan)
        ok, reason = subsume.plan_eligible(view)
        if not ok:
            self._record_decision(None, "recompute", [("plan", reason)])
            return False
        key = subsume.view_key(view)
        spec = {
            "args": [
                list(view["keys"]),
                [list(a) for a in plan.physical_agg_list()],
                [list(t) for t in plan.where_terms],
            ],
            "dag_wire": kwargs.get("dag"),
        }
        if self.manager.note_query(key, view, spec, now):
            entry = self.manager.start_build(key, now)
            if entry is not None:
                self.controller._dispatch_rollup_build(entry)
        matches, rejected = [], []
        for entry in self.manager.candidates(view["filenames"]):
            transform, why = subsume.match(entry.view, view, entry.meta())
            if transform is None:
                rejected.append((entry.key, why))
            else:
                matches.append((entry.key, transform, entry.group_rows()))
        total_rows = 0
        for fname in view["filenames"]:
            stats = self.controller.shard_stats.get(fname) or {}
            total_rows += int(stats.get("rows", 0) or 0)
        choice = subsume.choose_source(matches, total_rows)
        if choice is None:
            if matches:
                rejected.extend((m[0], "cost") for m in matches)
            self._record_decision(key, "recompute", rejected)
            return False
        entry_key, transform, _groups = choice
        entry = self.manager.entries[entry_key]
        payloads = self._render(entry, transform)
        if payloads is None:
            self.manager.fail(entry_key, "render")
            self._record_decision(key, "recompute", rejected + [
                (entry_key, "render-error")
            ])
            return False
        source = "rollup" if transform["kind"] in ("exact", "zone") else "subsume"
        self.manager.note_hit(entry_key, now)
        self.served += 1
        self._record_decision(key, source, rejected, chosen=entry_key)
        self.controller._reply_served(msg, payloads, source, entry_key)
        return True

    def _render(self, entry, transform):
        """Per-file payload bytes for the reply envelope; None on any
        transform failure (falls back to recompute)."""
        import pickle

        out = []
        try:
            for fname in entry.filenames:
                info = entry.per_file[fname]
                if transform["kind"] in ("exact", "zone"):
                    out.append(info["data"])
                else:
                    folded = subsume.apply_transform(
                        info["payload"], transform
                    )
                    out.append(pickle.dumps(dict(folded), protocol=4))
        except Exception:
            self.controller.logger.exception("rollup fold failed")
            return None
        return out

    def _record_decision(self, key, source, rejected, chosen=None):
        self.decisions.append({
            "view": key,
            "source": source,
            "chosen": chosen,
            "rejected": [list(r) for r in rejected],
        })
        if rejected or source != "recompute":
            self.controller.flight.record(
                "serve_decision",
                view=key,
                source=source,
                chosen=chosen,
                rejected=[list(r) for r in rejected],
            )

    # -- lifecycle hooks ------------------------------------------------

    def note_append(self, filename):
        """An append for ``filename`` is about to be dispatched: stale-out
        covering rollups *before* any worker mutates its shard."""
        if not serve_enabled():
            return
        flipped = self.manager.note_append(filename, time.monotonic())
        if flipped:
            self.controller.flight.record(
                "rollup_stale", filename=filename, entries=flipped
            )

    def absorb_build(self, key, fname, info):
        """One worker build/refresh reply landed (controller-decoded)."""
        return self.manager.absorb(key, fname, info, time.monotonic())

    def tick(self):
        """Heartbeat-paced housekeeping: abandon wedged builds, enforce
        retention caps, and dispatch delta refreshes for stale entries."""
        if not serve_enabled():
            return
        now = time.monotonic()
        dropped = self.manager.sweep(now)
        if dropped:
            for key, why in dropped:
                self.controller.flight.record(
                    "rollup_evict", entry=key, reason=why
                )
            self.controller.counters["rollup_evictions"] += len(dropped)
        for key in self.manager.stale_keys():
            res = self.manager.begin_refresh(key, now)
            if res is None:
                continue
            entry, prior = res
            self.controller._dispatch_rollup_build(entry, prior=prior)

    def snapshot(self):
        """``serving`` section of the debug bundle."""
        return {
            "enabled": serve_enabled(),
            "served": self.served,
            "rollups": self.manager.snapshot(),
            "recent_decisions": list(self.decisions),
        }
