"""Materialized-rollup manager: controller-resident hot-plan partials.

The controller watches the stream of admitted, lattice-eligible plans
(:func:`bqueryd_tpu.serve.subsume.plan_view`), scores each view with an
exponentially-decaying hit counter, and materializes the ones that stay
hot: one ``rollup`` verb per holder file builds the mergeable partials
payload for the view (plus the column census and chunk-prefix fingerprint
that later proofs need) and ships it back to live here, controller-side.

Freshness is delegated to the PR-14 machinery: every entry stores the
:func:`~bqueryd_tpu.ops.workingset.table_growth_base` fingerprint its
partials were computed against, ``note_append`` flips covering entries to
``stale`` the moment an append for their file is *dispatched* (before any
row lands — a stale-but-actually-unchanged entry refreshes back to ready,
never the reverse), and the refresh verb re-validates the stored prefix
with ``growth_since`` on the worker: exact prefix → aggregate only the
new tail chunks and hostmerge into the prior partials; any rewrite or
desync → full rebuild.  A stale or building entry is never served from.

This module is pure bookkeeping — no sockets, no clock reads (callers
pass ``now``), no numpy — so every lifecycle edge is unit-testable.
Dispatch and reply absorption live in ``controller.py`` where the wire
lint can see them.
"""

import os


def _env_int(name, default):
    try:
        # bqtpu: allow[config-dynamic-env-key] callers pass literal registered names: ROLLUP_MAX and ROLLUP_CACHE_BYTES below; both in ENV_REGISTRY
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        # bqtpu: allow[config-dynamic-env-key] callers pass literal registered names: ROLLUP_HEAT_MIN and ROLLUP_HEAT_HALFLIFE_S below; both in ENV_REGISTRY
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def rollup_max():
    """Max live rollup entries (``BQUERYD_TPU_ROLLUP_MAX``)."""
    return _env_int("BQUERYD_TPU_ROLLUP_MAX", 16)


def heat_min():
    """Decayed hit-score a view must reach before it is materialized
    (``BQUERYD_TPU_ROLLUP_HEAT_MIN``)."""
    return _env_float("BQUERYD_TPU_ROLLUP_HEAT_MIN", 3.0)


def heat_halflife_s():
    """Heat decay half-life in seconds (``BQUERYD_TPU_ROLLUP_HEAT_HALFLIFE_S``)."""
    return _env_float("BQUERYD_TPU_ROLLUP_HEAT_HALFLIFE_S", 300.0)


def cache_bytes():
    """Byte budget for stored rollup partials (``BQUERYD_TPU_ROLLUP_CACHE_BYTES``)."""
    return _env_int("BQUERYD_TPU_ROLLUP_CACHE_BYTES", 256 * 1024 * 1024)


#: seconds after which an unfinished build/refresh is abandoned
BUILD_TIMEOUT_S = 120.0


class RollupEntry:
    """One materialized view: per-file partials plus the proofs metadata."""

    __slots__ = (
        "key", "view", "spec", "state", "per_file", "epochs",
        "started_at", "ready_at", "last_hit", "hits", "nbytes",
    )

    def __init__(self, key, view, spec, epochs, now):
        self.key = key
        self.view = view
        #: {"args": [keys, agg_list, where_terms], "dag": dag_blob | None}
        self.spec = spec
        self.state = "building"
        #: {fname: {"data", "payload", "base", "zones", "groups", "mode"}}
        self.per_file = {}
        #: append-epoch snapshot the stored partials correspond to
        self.epochs = dict(epochs)
        self.started_at = now
        self.ready_at = None
        self.last_hit = now
        self.hits = 0
        self.nbytes = 0

    @property
    def filenames(self):
        return self.view["filenames"]

    def group_rows(self):
        """Total stored partial-group rows across files (fold-cost input)."""
        return sum(f.get("groups", 0) for f in self.per_file.values())

    def meta(self):
        """{filename: column census} for the subsumption proofs."""
        return {f: info.get("zones") or {} for f, info in self.per_file.items()}

    def snapshot(self):
        """Debug-bundle row."""
        return {
            "key": self.key,
            "state": self.state,
            "keys": list(self.view["keys"]),
            "filenames": list(self.filenames),
            "windowed": self.view.get("dag_sig") is not None,
            "hits": self.hits,
            "bytes": self.nbytes,
            "group_rows": self.group_rows(),
            "modes": {f: i.get("mode") for f, i in self.per_file.items()},
        }


class RollupManager:
    """Heat tracking + entry lifecycle.  All mutation goes through the
    methods below; the controller owns dispatch and absorption."""

    def __init__(self):
        self._heat = {}          # view_key -> (score, last_seen)
        self._views = {}         # view_key -> (view, spec), latest eligible shape
        self.entries = {}        # view_key -> RollupEntry
        self.file_epochs = {}    # filename -> int, bumped per append dispatch
        self.evictions = 0

    # -- heat ---------------------------------------------------------

    def note_query(self, key, view, spec, now):
        """Record one admitted eligible query; returns True when the view
        just crossed the materialization threshold and has no entry yet."""
        score, last = self._heat.get(key, (0.0, now))
        hl = heat_halflife_s()
        if hl > 0 and now > last:
            score *= 0.5 ** ((now - last) / hl)
        score += 1.0
        self._heat[key] = (score, now)
        self._views[key] = (view, spec)
        if len(self._heat) > 4 * max(rollup_max(), 1):
            self._decay_prune(now)
        return key not in self.entries and score >= heat_min()

    def _decay_prune(self, now):
        hl = heat_halflife_s()
        for key in list(self._heat):
            score, last = self._heat[key]
            if hl > 0:
                score *= 0.5 ** (max(now - last, 0.0) / hl)
            if score < 0.5 and key not in self.entries:
                del self._heat[key]
                self._views.pop(key, None)

    # -- lifecycle ----------------------------------------------------

    def start_build(self, key, now):
        """Create a ``building`` entry for a hot view (idempotent)."""
        if key in self.entries:
            return None
        view, spec = self._views[key]
        entry = RollupEntry(key, view, spec, {
            f: self.file_epochs.get(f, 0) for f in view["filenames"]
        }, now)
        self.entries[key] = entry
        return entry

    def absorb(self, key, fname, info, now):
        """Store one file's build/refresh reply; flips the entry to
        ``ready`` once every file is in *and* no append arrived meanwhile.
        Returns the entry state, or None for an unknown/retired key."""
        entry = self.entries.get(key)
        if entry is None or fname not in entry.filenames:
            return None
        entry.per_file[fname] = info
        entry.nbytes = sum(
            len(f.get("data") or b"") for f in entry.per_file.values()
        )
        if set(entry.per_file) == set(entry.filenames):
            current = {f: self.file_epochs.get(f, 0) for f in entry.filenames}
            if current == entry.epochs:
                entry.state = "ready"
                entry.ready_at = now
            else:
                # an append was dispatched mid-build: never serve this
                entry.state = "stale"
        return entry.state

    def fail(self, key, _reason=None):
        """Drop an entry whose build/refresh errored."""
        return self.entries.pop(key, None)

    def note_append(self, filename, now):
        """An append for ``filename`` is being dispatched: bump the epoch
        and mark covering entries stale.  Returns the stale-flipped keys."""
        self.file_epochs[filename] = self.file_epochs.get(filename, 0) + 1
        flipped = []
        for entry in self.entries.values():
            if filename in entry.filenames and entry.state != "building":
                if entry.state != "stale":
                    flipped.append(entry.key)
                entry.state = "stale"
        return flipped

    def begin_refresh(self, key, now):
        """Move a stale entry back to ``building`` for a delta refresh;
        returns (entry, prior_per_file) or None."""
        entry = self.entries.get(key)
        if entry is None or entry.state != "stale":
            return None
        prior = entry.per_file
        entry.per_file = {}
        entry.state = "building"
        entry.started_at = now
        entry.epochs = {
            f: self.file_epochs.get(f, 0) for f in entry.filenames
        }
        return entry, prior

    def stale_keys(self):
        return [k for k, e in self.entries.items() if e.state == "stale"]

    # -- serving ------------------------------------------------------

    def candidates(self, filenames):
        """Ready entries covering exactly ``filenames`` whose epochs still
        match — the only entries the lattice may reason over."""
        out = []
        for entry in self.entries.values():
            if entry.state != "ready" or entry.filenames != tuple(filenames):
                continue
            current = {f: self.file_epochs.get(f, 0) for f in entry.filenames}
            if current != entry.epochs:
                entry.state = "stale"
                continue
            out.append(entry)
        return out

    def note_hit(self, key, now):
        entry = self.entries.get(key)
        if entry is not None:
            entry.hits += 1
            entry.last_hit = now

    # -- retention ----------------------------------------------------

    def sweep(self, now):
        """Abandon wedged builds, enforce count + byte caps; returns the
        evicted/abandoned keys with reasons."""
        dropped = []
        for key, entry in list(self.entries.items()):
            if (
                entry.state == "building"
                and now - entry.started_at > BUILD_TIMEOUT_S
            ):
                del self.entries[key]
                dropped.append((key, "build-timeout"))
        limit = max(rollup_max(), 0)
        budget = max(cache_bytes(), 0)

        def _victims():
            live = [e for e in self.entries.values() if e.state != "building"]
            live.sort(key=lambda e: (e.last_hit, e.hits))
            return live

        while len(self.entries) > limit:
            victims = _victims()
            if not victims:
                break
            victim = victims[0]
            del self.entries[victim.key]
            self.evictions += 1
            dropped.append((victim.key, "count-cap"))
        while sum(e.nbytes for e in self.entries.values()) > budget:
            victims = _victims()
            if not victims:
                break
            victim = victims[0]
            del self.entries[victim.key]
            self.evictions += 1
            dropped.append((victim.key, "byte-cap"))
        return dropped

    def snapshot(self):
        """Debug-bundle ``serving.rollups`` section."""
        return {
            "entries": [e.snapshot() for e in self.entries.values()],
            "tracked_views": len(self._heat),
            "file_epochs": dict(self.file_epochs),
            "evictions": self.evictions,
        }
